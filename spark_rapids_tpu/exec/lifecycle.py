"""Query lifecycle governor (ISSUE 6 tentpole): deadlines + cooperative
cancellation, partition-granular recovery accounting, and degradation
circuit breakers — the control plane that bounds what one query may cost
the process.

The reference engine leans on Spark's scheduler for all three: tasks are
killed cooperatively (`TaskContext.isInterrupted` polled at batch
boundaries), recovery is task/stage-granular rather than query-granular,
and a persistently failing executor is blacklisted instead of burning
every job's retry budget (SURVEY §2.5). Standalone, this module rebuilds
those contracts for the single-process multi-thread engine:

* **QueryContext** — one cancellation token per driven query.
  `DataFrame.collect()` installs it thread-locally (pipeline producer
  threads adopt it like conf/query-id/attempt); `TpuExec.execute()`
  ticks it every batch (one pointer check when no query is governed,
  the faults/eventLog cost discipline) and the blocking seams — the
  admission semaphore, pipeline stage waits, spill-writeback waits —
  check it inside their poll loops. A deadline
  (`spark.rapids.tpu.query.timeoutMs`, spanning ALL task re-execution
  attempts) or `TpuSession.cancel_query()` makes every checker raise
  `QueryCancelledError`; the query unwinds through the existing
  try/finally chains (stages join, spillables close, budget settles)
  and a single `query_cancelled` event records WHERE the cancellation
  was noticed (compute / sem-wait / pipeline-wait / spill-wait /
  task-retry).

* **Partition-recovery accounting** — the recovery itself lives where
  the lineage is alive (shuffle/manager.py consults the handle's
  committed map outputs + the lineage the exchange captured at write
  time); this module carries the provenance vocabulary, the
  conf gate, and the partition-vs-whole-plan counters that
  tools/profile_report.py and bench.py roll up.

* **Circuit breakers** — a sliding failure window per fault domain
  (`BREAKER_DOMAINS`). `exec/task_retry.py` records every
  classified-transient attempt failure against the domains the attempt
  engaged (the Pallas tiers note engagement at trace time; device-ish
  errors always implicate `device_dispatch`); at
  `spark.rapids.tpu.breaker.threshold` failures inside `windowMs` the
  breaker opens and `ops/pallas_tier.py` demotes the domain to its XLA
  safe path until a post-cooldown half-open probe succeeds. One
  persistently bad kernel path degrades one domain instead of spending
  all of `task.maxAttempts` on every query. `TpuSession.health()`
  surfaces the whole state.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


class QueryCancelledError(RuntimeError):
    """The governed query was cancelled (deadline or user) — classified
    `fatal` by faults.classify, so it unwinds straight through the
    task-retry layer instead of burning attempts."""

    def __init__(self, msg: str, phase: str = "compute",
                 reason: str = "user"):
        super().__init__(msg)
        self.phase = phase
        self.reason = reason


#: phases a cancellation can be noticed in (docs/robustness.md);
#: admission-wait is the workload governor's queue (exec/workload.py)
CANCEL_PHASES = ("compute", "sem-wait", "pipeline-wait", "spill-wait",
                 "task-retry", "admission-wait")


# ---------------------------------------------------------------------------
# counters (bench.py {"lifecycle": ...} deltas + profile_report roll-up)
# ---------------------------------------------------------------------------

_counter_lock = threading.Lock()
_counters: Dict[str, int] = {
    "cancelled": 0,
    "partition_recompute": 0,
    "breaker_open": 0,
    "breaker_half_open": 0,
    "breaker_close": 0,
}


def _count(key: str) -> None:
    with _counter_lock:
        _counters[key] += 1


def counters() -> Dict[str, int]:
    """Snapshot of the process-cumulative lifecycle counters, plus the
    whole-plan re-execution total from exec/task_retry.py — one dict so
    bench.py can delta it per record."""
    from .task_retry import task_retry_total
    with _counter_lock:
        out = dict(_counters)
    out["whole_plan_retries"] = task_retry_total()
    return out


def note_partition_recompute() -> None:
    """Called by the shuffle read path when one map output was
    recomputed in place (the partition-granular lane)."""
    _count("partition_recompute")


# ---------------------------------------------------------------------------
# QueryContext + registry
# ---------------------------------------------------------------------------

_tls = threading.local()

_reg_lock = threading.Lock()
_active: Dict[int, "QueryContext"] = {}


class QueryContext:
    """Per-query cancellation token + deadline + engaged-domain notes.
    Shared across every thread serving the query (pipeline producers
    adopt it); all methods are thread-safe."""

    _ids = itertools.count(1)

    __slots__ = ("ctx_id", "owner", "t0", "deadline", "check_every",
                 "_cancel", "reason", "_ticks", "_emit_lock", "_emitted",
                 "engaged_domains", "workload_ticket",
                 "phase", "current_op", "root_op_id", "batches_produced",
                 "rows_produced", "attempt_no", "spill_count",
                 "spill_bytes", "runtime_stats", "phase_ledger",
                 "events_qid", "adaptive_batch_target", "stall_retry")

    def __init__(self, timeout_ms: int = 0, check_every: int = 8,
                 owner: Any = None):
        self.ctx_id = next(QueryContext._ids)
        self.owner = owner
        self.t0 = time.monotonic()
        self.deadline = (self.t0 + timeout_ms / 1000.0
                         if timeout_ms and timeout_ms > 0 else None)
        self.check_every = max(1, check_every)
        self._cancel = threading.Event()
        self.reason: Optional[str] = None
        self._ticks = 0
        self._emit_lock = threading.Lock()
        self._emitted = False
        #: fault domains this attempt engaged (pallas tiers note at
        #: trace time); cleared per task attempt by begin_attempt()
        self.engaged_domains: set = set()
        #: workload-governor admission ticket (exec/workload.py) —
        #: rides the context so producer threads that adopt_context
        #: resolve the same per-query memory quota
        self.workload_ticket = None
        # -- live introspection surface (ISSUE 11): read lock-free by
        # TpuSession.active_queries(); every field is a single attribute
        # assignment on its write path, and torn reads are harmless
        # (the snapshot is advisory, never a control decision)
        #: queued | admitted | executing | retrying (ADMISSION-adjacent
        #: phases are set by exec/workload.py, the others by task_retry)
        self.phase = "executing"
        #: operator that most recently yielded a batch on any thread
        self.current_op: Optional[str] = None
        #: the plan root's op id (set by DataFrame._collect_once) —
        #: batches/rows produced count only ROOT output, i.e. actual
        #: query results, not inner-operator traffic
        self.root_op_id = -1
        self.batches_produced = 0
        self.rows_produced = 0
        self.attempt_no = 1
        self.spill_count = 0
        self.spill_bytes = 0
        #: per-attempt RuntimeStats (obs/stats.py) — exchanges record
        #: map-output/partition distributions into it mid-flight
        self.runtime_stats = None
        #: per-query wall-clock phase ledger (obs/phase.py, ISSUE 17):
        #: attached by DataFrame.collect when phases.enabled; every
        #: accrual site pays one pointer check when None
        self.phase_ledger = None
        #: the events-plane query id of the LATEST attempt's
        #: query_scope (api/session._collect_once) — the id space
        #: query_start/query_end records carry. query_phases must join
        #: them in the log, and the lifecycle ctx_id drifts from it as
        #: soon as any query retries (one events id per attempt, one
        #: ctx per governed drive)
        self.events_qid = None
        #: OOM-feedback batch right-sizing (exec/adaptive.py): set by
        #: the first with_retry SPLIT of the query, consumed by
        #: CoalesceBatchesExec as a shrunken target so later batches of
        #: the same query stop re-triggering the retry lane. Persists
        #: across attempts (unlike runtime_stats) — the signal is about
        #: the query's data shape, not one attempt's luck
        self.adaptive_batch_target: Optional[int] = None
        #: progress-watchdog verdict under stall.action=retry-seam
        #: (exec/speculation_shield.py): set by the watchdog thread,
        #: consumed ONCE by check() at the stalled attempt's next
        #: cancellation checkpoint — the seam raises a transient
        #: QueryStalledError onto the bounded task-retry lane
        self.stall_retry = False

    def note_batch(self, op: str, op_id: int,
                   rows: Optional[int]) -> None:
        """Batch-boundary progress note (TpuExec._drive): cheap enough
        to run per batch on every governed query — two attribute writes,
        three when the batch is root output."""
        self.current_op = op
        if op_id == self.root_op_id:
            self.batches_produced += 1
            if rows:
                self.rows_produced += rows

    def info(self) -> Dict[str, Any]:
        """One query's live introspection row — assembled lock-light
        from this context + its workload ticket (quota read through the
        manager only when a ticket exists)."""
        now = time.monotonic()
        out = {
            "query": self.ctx_id,
            "phase": self.phase,
            "current_op": self.current_op,
            "batches": self.batches_produced,
            "rows": self.rows_produced,
            "elapsed_ms": int((now - self.t0) * 1000),
            "deadline_remaining_ms": (
                int((self.deadline - now) * 1000)
                if self.deadline is not None else None),
            "attempt": self.attempt_no,
            "spill_count": self.spill_count,
            "spill_bytes": self.spill_bytes,
            "cancelled": self._cancel.is_set(),
        }
        t = self.workload_ticket
        if t is not None:
            from ..memory.budget import memory_budget
            from . import workload
            limit = memory_budget().limit
            quota = workload.manager().quota_bytes(limit, t.quota_frac)
            out["quota"] = {
                "priority": t.priority,
                "used_bytes": t.device_bytes,
                "granted_bytes": quota if quota is not None else limit,
            }
        return out

    def cancel(self, reason: str = "user") -> None:
        if not self._cancel.is_set():
            if self.reason is None:
                self.reason = reason
            self._cancel.set()

    def cancelled(self) -> bool:
        if self._cancel.is_set():
            return True
        if self.deadline is not None and time.monotonic() >= self.deadline:
            self.cancel("timeout")
            return True
        return False

    def check(self, phase: str = "compute") -> None:
        """Raise QueryCancelledError when the query is cancelled or past
        its deadline. The FIRST checker (any thread) emits the single
        `query_cancelled` event with its phase attribution — that is the
        wait the query actually died in."""
        if self.stall_retry:
            # watchdog retry-seam verdict: consume the flag (a retried
            # attempt starts clean) and fail THIS attempt transiently —
            # it routes onto the task-retry lane, not the fatal unwind
            self.stall_retry = False
            from ..faults import QueryStalledError
            raise QueryStalledError(
                f"query stalled at seam {self.current_op!r}; retrying "
                f"the attempt (noticed in phase {phase})")
        if not self.cancelled():
            return
        reason = self.reason or "user"
        emit = False
        with self._emit_lock:
            if not self._emitted:
                self._emitted = True
                emit = True
        if emit:
            _count("cancelled")
            from ..obs import events as obs_events
            obs_events.emit(
                "query_cancelled", phase=phase, reason=reason,
                elapsed_ms=int((time.monotonic() - self.t0) * 1000))
        raise QueryCancelledError(
            f"query cancelled ({reason}) in phase {phase} after "
            f"{time.monotonic() - self.t0:.3f}s", phase=phase,
            reason=reason)

    def tick(self) -> None:
        """Batch-boundary hook (TpuExec.execute): cheap counter, a real
        deadline/cancel check every `check_every` ticks."""
        self._ticks += 1
        if self._ticks >= self.check_every:
            self._ticks = 0
            self.check("compute")


def current_context() -> Optional[QueryContext]:
    """This thread's governed query context (None outside one — the
    entire cost of the disabled mode)."""
    return getattr(_tls, "ctx", None)


def adopt_context(ctx: Optional[QueryContext]) -> None:
    """Install a captured context on this (producer) thread, like
    conf/query-id/speculation/attempt adoption at a stage boundary."""
    _tls.ctx = ctx


def check_current(phase: str = "compute") -> None:
    """Raise QueryCancelledError if this thread's governed query is
    cancelled; no-op (one pointer check) otherwise. The call blocking
    waits put inside their poll loops."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.check(phase)


def current_cancelled() -> bool:
    """Predicate flavor of check_current (for callers that must clean
    up before raising)."""
    ctx = getattr(_tls, "ctx", None)
    return ctx is not None and ctx.cancelled()


@contextlib.contextmanager
def governed(conf=None, owner: Any = None,
             timeout_ms: Optional[int] = None) -> Iterator[QueryContext]:
    """Install a QueryContext around one driven query (the
    DataFrame.collect wrapper — OUTSIDE with_task_retry, so the deadline
    spans every task re-execution attempt). Registers the context so
    cancel_owner / the conftest leak tripwire can see it; always
    unregisters on the way out."""
    from ..config import (QUERY_CANCEL_CHECK_BATCHES, QUERY_TIMEOUT_MS,
                          active_conf)
    conf = conf if conf is not None else active_conf()
    if timeout_ms is None:
        timeout_ms = conf.get(QUERY_TIMEOUT_MS)
    ctx = QueryContext(timeout_ms=timeout_ms,
                       check_every=conf.get(QUERY_CANCEL_CHECK_BATCHES),
                       owner=owner)
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    with _reg_lock:
        _active[ctx.ctx_id] = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev
        with _reg_lock:
            _active.pop(ctx.ctx_id, None)


def cancel_owner(owner: Any, reason: str = "user") -> int:
    """Cancel every registered context belonging to `owner` (the
    TpuSession.cancel_query entry — runs on any thread). Returns how
    many contexts were cancelled."""
    with _reg_lock:
        targets = [c for c in _active.values() if c.owner is owner]
    for c in targets:
        c.cancel(reason)
    return len(targets)


def active_query_ids() -> List[int]:
    with _reg_lock:
        return sorted(_active)


def set_phase(phase: str) -> None:
    """Live-introspection phase note for this thread's governed query
    (no-op outside one — a single pointer check)."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.phase = phase


def note_spill(freed_bytes: int) -> None:
    """Per-query spill attribution (ISSUE 11): the catalog calls this
    once per synchronous_spill pass that freed anything, on the thread
    whose reservation triggered it — the query that EXPERIENCED the
    pressure, which is what active_queries() reports."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.spill_count += 1
        ctx.spill_bytes += freed_bytes


def active_queries(owner: Any = None) -> List[Dict[str, Any]]:
    """Live introspection rows for every registered (in-flight) query,
    oldest first — the TpuSession.active_queries() payload. The
    registry lock is held only to snapshot the context list; each row
    assembles from lock-free attribute reads. `owner` marks (never
    filters) rows: introspection is engine-wide, `mine` says which
    queries belong to the asking session."""
    with _reg_lock:
        ctxs = sorted(_active.values(), key=lambda c: c.ctx_id)
    out = []
    for c in ctxs:
        row = c.info()
        row["mine"] = owner is not None and c.owner is owner
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# degradation circuit breakers
# ---------------------------------------------------------------------------

#: domain -> (what it covers, its safe path when open). The
#: docs/robustness.md domain table is lint-checked against this
#: registry (tests/test_docs_lint.py), like the fault-point table.
BREAKER_DOMAINS: Dict[str, str] = {
    "pallas_fused": "fused scan-filter-project-aggregate Pallas tier "
                    "(ops/pallas_fused.py) -> XLA formulation",
    "pallas_join": "fused join-probe Pallas tier (ops/pallas_join.py) "
                   "-> XLA formulation",
    "pallas_gather": "DMA row-gather Pallas tier (ops/pallas_gather.py) "
                     "-> XLA packed row gather (ops/rowpack.py)",
    "pallas_hash": "murmur3 Pallas kernels (ops/pallas_kernels.py) "
                   "-> XLA elementwise murmur3 (ops/hashing.py)",
    "device_dispatch": "guarded device dispatch (memory/retry.py "
                       "oom_guard) -> advisory: already the guarded "
                       "path; open state surfaces in health()/events",
    "ici_exchange": "ICI device-to-device shuffle lane "
                    "(exec/exchange.py + parallel/exchange.py) "
                    "-> host serialize/LZ4 shuffle lane",
    "adaptive": "runtime replanner (exec/adaptive.py) "
                "-> static plan: measured-statistics decisions (skew "
                "split, broadcast demotion, coalescing, batch "
                "right-sizing) are skipped while open",
}

#: Pallas kernel family (ops/pallas_tier.PALLAS_FAMILIES) -> breaker
#: domain; test_docs_lint asserts every family has an entry
FAMILY_DOMAINS: Dict[str, str] = {
    "scan_agg": "pallas_fused",
    "join_probe": "pallas_join",
    "gather": "pallas_gather",
    # the device shuffle partition split's tiered step IS the packed
    # row gather (ops/partition_split.py routes through ops/gather), so
    # it degrades with the same breaker domain
    "partition_split": "pallas_gather",
    "murmur3": "pallas_hash",
    # the packed upload's single device copy is a guarded device
    # dispatch (it rides the device.dispatch fault point); repeated
    # upload failures implicate the device itself
    "h2d_upload": "device_dispatch",
    # the ICI lane degrades as a whole (to the host serialize path),
    # not kernel-by-kernel: its bench family maps onto its own domain
    "ici_all_to_all": "ici_exchange",
    # the encoded lane's code-indexed take (columnar/encoded.dict_take)
    # is a row gather over the per-dictionary lookup table — it rides
    # the same Pallas DMA kernel and degrades with the same breaker
    "dict_gather": "pallas_gather",
}

BREAKER_STATES = ("closed", "open", "half_open")


class _Breaker:
    __slots__ = ("domain", "state", "failures", "opened_at", "trips",
                 "probe_at")

    def __init__(self, domain: str):
        self.domain = domain
        self.state = "closed"
        self.failures: List[float] = []  # monotonic failure timestamps
        self.opened_at = 0.0
        self.trips = 0
        #: when the half-open probe was let through (0 = none in
        #: flight): concurrent consults stay demoted while one probe
        #: runs, and a probe that never concludes (fatal crash skips
        #: the attempt hooks) expires after another cooldown
        self.probe_at = 0.0


_breaker_lock = threading.Lock()
_breakers: Dict[str, _Breaker] = {}


def _breaker_conf(conf=None):
    from ..config import (BREAKER_COOLDOWN_MS, BREAKER_ENABLED,
                          BREAKER_THRESHOLD, BREAKER_WINDOW_MS, active_conf)
    conf = conf if conf is not None else active_conf()
    return (bool(conf.get(BREAKER_ENABLED)),
            max(1, conf.get(BREAKER_THRESHOLD)),
            max(1, conf.get(BREAKER_WINDOW_MS)) / 1000.0,
            max(1, conf.get(BREAKER_COOLDOWN_MS)) / 1000.0)


def _emit_breaker(kind: str, br: _Breaker, **fields) -> None:
    _count(kind)
    from ..obs import events as obs_events
    obs_events.emit(kind, domain=br.domain, trips=br.trips,
                    failures=len(br.failures), **fields)


def breaker_allows(domain: str) -> bool:
    """May `domain`'s accelerated path engage right now? closed ->
    yes; open -> no until cooldown, then the consult itself half-opens
    the breaker and lets ONE probe through; half_open -> only while no
    probe is in flight (a probe that never concludes expires after
    another cooldown, so a crashed probe cannot wedge the breaker).
    An explicitly disabled conf (breaker.enabled=false — the operator
    kill-switch) answers yes regardless of recorded state. With no
    breaker ever tripped this is one empty-dict check."""
    if not _breakers:
        return True
    enabled, _thr, _window, cooldown = _breaker_conf()
    if not enabled:
        # the kill-switch must restore the accelerated tier NOW, not
        # after a cooldown + lucky probe (review r4)
        return True
    emit = None
    with _breaker_lock:
        br = _breakers.get(domain)
        if br is None or br.state == "closed":
            return True
        now = time.monotonic()
        if br.state == "open":
            if now - br.opened_at < cooldown:
                return False
            br.state = "half_open"
            br.probe_at = now
            emit = br
        else:  # half_open
            if br.probe_at and now - br.probe_at <= cooldown:
                return False  # one probe at a time
            br.probe_at = now
    if emit is not None:
        _emit_breaker("breaker_half_open", emit)
    return True


def record_domain_failure(domain: str) -> None:
    """One classified-transient failure attributed to `domain`.
    Conf-gated (spark.rapids.tpu.breaker.enabled, default off): runs
    only on failure paths, so the conf read costs nothing steady-state."""
    enabled, threshold, window, _cooldown = _breaker_conf()
    if not enabled or domain not in BREAKER_DOMAINS:
        return
    now = time.monotonic()
    opened = None
    with _breaker_lock:
        br = _breakers.get(domain)
        if br is None:
            br = _breakers[domain] = _Breaker(domain)
        br.failures = [t for t in br.failures if now - t <= window]
        br.failures.append(now)
        if br.state == "half_open" or (br.state == "closed"
                                       and len(br.failures) >= threshold):
            br.state = "open"
            br.opened_at = now
            br.probe_at = 0.0
            br.trips += 1
            opened = br
    if opened is not None:
        _emit_breaker("breaker_open", opened,
                      safe_path=BREAKER_DOMAINS[domain])


def record_domain_success(domain: str) -> None:
    """A successful attempt that engaged `domain`: a half-open breaker's
    probe passed — close it and forget the failure history."""
    if not _breakers:
        return
    closed = None
    with _breaker_lock:
        br = _breakers.get(domain)
        if br is not None and br.state == "half_open":
            br.state = "closed"
            br.failures = []
            br.probe_at = 0.0
            closed = br
    if closed is not None:
        _emit_breaker("breaker_close", closed)


def breaker_shed_hint_ms(domain: str, conf=None) -> Optional[int]:
    """Read-only admission consult (exec/workload.py, ISSUE 7): while
    `domain`'s breaker is OPEN and still inside its cooldown, return the
    remaining cooldown in ms (the shed retry-after hint); None
    otherwise. Unlike breaker_allows this never transitions state —
    half-open probes belong to already-running attempts; admission must
    not consume (or block behind) the single probe slot. `conf` is the
    ADMITTING conf: admission runs before collect installs the session
    conf thread-locally, so active_conf() could answer for the wrong
    session."""
    if not _breakers:
        return None
    enabled, _thr, _window, cooldown = _breaker_conf(conf)
    if not enabled:
        return None
    with _breaker_lock:
        br = _breakers.get(domain)
        if br is None or br.state != "open":
            return None
        remaining = cooldown - (time.monotonic() - br.opened_at)
        if remaining <= 0:
            return None
        return max(1, int(remaining * 1000))


def open_breakers() -> List[str]:
    """Domains whose breaker is not closed (conftest leak tripwire +
    health surface)."""
    with _breaker_lock:
        return sorted(d for d, b in _breakers.items()
                      if b.state != "closed")


# -- attempt attribution (exec/task_retry.py hooks) -------------------------

def note_engagement(family: str) -> None:
    """Trace-time note from ops/pallas_tier.py that a fused kernel
    family engaged for the current attempt; maps the family onto its
    breaker domain. Lands on the QueryContext when one is governed
    (shared across producer threads), else on a thread-local attempt
    scope installed by begin_attempt()."""
    domain = FAMILY_DOMAINS.get(family)
    if domain is None:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.engaged_domains.add(domain)
        return
    s = getattr(_tls, "engaged", None)
    if s is not None:
        s.add(domain)


def engage_domain(domain: str) -> None:
    """Engage a breaker DOMAIN directly (ISSUE 14): a CompiledStageExec
    notes `device_dispatch` at its stage boundary so a classified-
    transient failure of the fused execution counts against the domain
    and PR 5 degradation demotes the stage back to per-operator
    execution. The family-keyed twin (note_engagement) stays the tier
    selector's surface; this one is for callers that ARE a domain."""
    if domain not in BREAKER_DOMAINS:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.engaged_domains.add(domain)
        return
    s = getattr(_tls, "engaged", None)
    if s is not None:
        s.add(domain)


def _engaged_set(create: bool = False) -> set:
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        return ctx.engaged_domains
    s = getattr(_tls, "engaged", None)
    if s is None and create:
        s = _tls.engaged = set()
    return s if s is not None else set()


def capture_engagement() -> Optional[set]:
    """The live engaged-domain set serving this thread's attempt (the
    QueryContext's when governed, else the thread-local attempt set) —
    captured at a pipeline stage boundary so producer-thread
    engagements land in the CONSUMER's attempt set even for un-governed
    queries (a bench lane without a deadline; a test driving
    with_task_retry directly)."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        return ctx.engaged_domains
    return getattr(_tls, "engaged", None)


def adopt_engagement(s: Optional[set]) -> None:
    """Install a captured engagement set on this (producer) thread.
    The governed case needs nothing (adopt_context already shares the
    QueryContext's set); this covers the thread-local fallback."""
    if s is not None and getattr(_tls, "ctx", None) is None:
        _tls.engaged = s


def begin_attempt(attempt: int = 1) -> None:
    """Task-attempt start (with_task_retry): clear the engaged-domain
    notes so failures attribute to THIS attempt's engagements, and note
    the attempt number + executing phase on the governed context (the
    live-introspection surface)."""
    _engaged_set(create=True).clear()
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.attempt_no = attempt
        ctx.phase = "executing"
        # per-attempt progress, like the per-attempt RuntimeStats: a
        # re-executed plan starts its root output from zero — without
        # this, active_queries() double-counts across task retries
        ctx.current_op = None
        ctx.batches_produced = 0
        ctx.rows_produced = 0


def attempt_failed(exc: BaseException) -> None:
    """A classified-transient task-attempt failure: record it against
    every domain the attempt engaged, plus device_dispatch for
    device-ish errors (an injected device fault or a non-OOM XLA
    runtime error always implicates the dispatch domain)."""
    domains = set(_engaged_set())
    from ..faults import InjectedDeviceError
    if isinstance(exc, InjectedDeviceError) \
            or type(exc).__name__ == "XlaRuntimeError":
        domains.add("device_dispatch")
    for d in domains:
        record_domain_failure(d)


def _rearm_if_cooled(domain: str) -> None:
    """open + cooldown elapsed -> half_open. The advisory
    device_dispatch domain is consulted by nothing, so a successful
    attempt performs its cooldown transition here (NOT via
    breaker_allows, whose single-probe gate would refuse while the
    attempt's own probe is in flight)."""
    enabled, _thr, _window, cooldown = _breaker_conf()
    if not enabled:
        return
    emit = None
    with _breaker_lock:
        br = _breakers.get(domain)
        if br is not None and br.state == "open" \
                and time.monotonic() - br.opened_at >= cooldown:
            br.state = "half_open"
            br.probe_at = 0.0
            emit = br
    if emit is not None:
        _emit_breaker("breaker_half_open", emit)


def attempt_succeeded() -> None:
    """A task attempt completed: any half-open breaker whose domain the
    attempt engaged (probed) closes unconditionally — the success IS
    the probe outcome; device_dispatch's probe is every successful
    attempt (dispatch is engaged by running at all), re-armed from open
    first when its cooldown has elapsed."""
    if not _breakers:
        return
    for d in set(_engaged_set()) | {"device_dispatch"}:
        _rearm_if_cooled(d)
        record_domain_success(d)


# ---------------------------------------------------------------------------
# health surface + test reset
# ---------------------------------------------------------------------------

def health() -> Dict[str, Any]:
    """The TpuSession.health() payload: breaker states, governed-query
    count, the cumulative lifecycle counters, and the workload
    governor's admission surface (queue depth / admitted / shed)."""
    now = time.monotonic()
    with _breaker_lock:
        breakers = {
            d: {"state": b.state, "trips": b.trips,
                "failures_in_window": len(b.failures),
                "open_for_ms": int((now - b.opened_at) * 1000)
                if b.state != "closed" else 0}
            for d, b in _breakers.items()}
    from . import workload
    return {"breakers": breakers,
            "active_queries": len(active_query_ids()),
            "counters": counters(),
            "workload": workload.snapshot()}


def reset_lifecycle() -> None:
    """Test isolation: drop every breaker, registered context and
    counter (the conftest tripwire resets at module boundaries, like
    faults.install(None))."""
    with _breaker_lock:
        _breakers.clear()
    with _reg_lock:
        _active.clear()
    with _counter_lock:
        for k in _counters:
            _counters[k] = 0
