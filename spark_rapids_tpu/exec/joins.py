"""Join execs — reference GpuHashJoin
(org/apache/spark/sql/rapids/execution/GpuHashJoin.scala:994, doJoin:1103),
GpuShuffledHashJoinExec, GpuBroadcastHashJoinExecBase,
GpuBroadcastNestedLoopJoinExecBase, ExistenceJoin.

One HashJoinExec covers broadcast & shuffled hash joins: in this engine a
"broadcast" build side is simply an already-materialized child (the
broadcast exchange keeps it device-resident), so both reference execs share
this operator, parameterized by build side. The probe pipeline is the
gather-map kernel stack in ops/join.py; per stream batch there is exactly
one host sync (candidate count -> capacity bucket), everything else stays
in compiled XLA.

Join-type support: inner, left/right/full outer, left semi, left anti,
cross (via NestedLoopJoinExec), existence. Extra non-equi conditions
evaluate over candidate pairs and AND into the verified mask — the analog
of the reference's AST-compiled join conditions (AstUtil.scala).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn, bucket_capacity
from ..columnar.encoded import DictionaryColumn
from ..expr.core import Expression, resolve
from ..memory.spillable import SpillableBatch
from ..ops.basic import active_mask, compaction_order, gather_column
from ..ops.strings import string_equal
from ..ops.join import (
    BuildTable, cross_pairs, expand_candidates, gather_column_indices,
    inner_gather_maps, int_key_lanes, matched_flags, outer_extend_maps,
    probe_counts, unmatched_indices, verify_pairs,
)
from ..types import BooleanType, Schema, StructField
from .base import (BUILD_TIME, DEBUG, DISPATCH_METRICS, GATHER_METRICS,
                   GATHER_TIME,
                   JOIN_TIME, NUM_GATHERS, NUM_INPUT_BATCHES, TpuExec)
from .basic import bind_projection, eval_projection, projection_schema
from .coalesce import concat_batches

INNER, LEFT_OUTER, RIGHT_OUTER, FULL_OUTER = "inner", "left_outer", \
    "right_outer", "full_outer"
LEFT_SEMI, LEFT_ANTI, EXISTENCE, CROSS = "left_semi", "left_anti", \
    "existence", "cross"


def _gather_batch(columns: Sequence[Column], idx, n,
                  byte_caps: Optional[Tuple] = None) -> List[Column]:
    """byte_caps: per-column static output byte bucket (None entries keep
    the input bucket). Joins DUPLICATE rows, so string columns must size
    their output byte bucket from the measured join byte need — the input
    bucket silently truncates payloads once output bytes exceed it.

    Fixed-width columns ride ONE packed row gather (XLA's per-gather
    loop cost dwarfs its per-byte cost on v5e), varlen columns keep the
    per-column path — both routed through the gather engine
    (ops/gather.gather_batch_columns) so the measured Pallas tier and
    the structural numGathers accounting cover every join emit."""
    from ..ops.gather import gather_batch_columns
    return gather_batch_columns(columns, idx, num_rows=n,
                                byte_caps=byte_caps)


def _is_varsize(c: Column) -> bool:
    from ..columnar.column import ArrayColumn
    return isinstance(c, (StringColumn, ArrayColumn))


def _var_lengths(c: Column):
    """Per-row payload size of a variable-size column: bytes for strings,
    elements for arrays."""
    from ..columnar.column import ArrayColumn
    from ..ops.collection import array_lengths
    from ..ops.strings import string_lengths
    if isinstance(c, ArrayColumn):
        return array_lengths(c)
    return string_lengths(c)


def _string_byte_needs(stream_columns, build: BuildTable, lo, counts, act):
    """Exact output payload requirement per variable-size column of the
    join (string bytes / array elements), all on device — fetched together
    with the candidate total in the one host sync per stream batch.

    Stream side: row i is emitted count_i times (candidates) plus at most
    once more (outer-unmatched tail). Build side: candidate payload is the
    per-row sorted-order prefix-sum ranges [lo, lo+count)."""
    cnt = counts.astype(jnp.int64)
    stream_needs = []
    for c in stream_columns:
        if _is_varsize(c):
            lens = jnp.where(act, _var_lengths(c), 0).astype(jnp.int64)
            stream_needs.append(jnp.sum(cnt * lens) + jnp.sum(lens))
    build_needs = []
    for prefix in build.payload_prefix:
        # precomputed in BuildTable.build (invariant across stream batches)
        build_needs.append(jnp.sum(prefix[lo + counts] - prefix[lo]))
    return tuple(stream_needs), tuple(build_needs)


def _byte_cap_tuple(columns, needs) -> Tuple:
    """Static per-column payload buckets from fetched needs (None = keep
    the input bucket for fixed-width columns)."""
    it = iter(needs)
    return tuple(bucket_capacity(max(int(next(it)), 8))
                 if _is_varsize(c) else None for c in columns)


class HashJoinExec(TpuExec):
    # speculative sizing-cache entries expire after this many uses so a
    # pathological batch cannot inflate candidate caps forever
    SPEC_REFRESH = 512

    def __init__(self, left: TpuExec, right: TpuExec,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = INNER,
                 build_side: str = "right",
                 condition: Optional[Expression] = None,
                 exists_name: str = "exists"):
        super().__init__(left, right)
        assert build_side in ("left", "right")
        self.join_type = join_type
        self.build_side = build_side
        self.condition = condition
        self.exists_name = exists_name
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        # semi/anti/existence joins that preserve the stream side require
        # build == non-preserved side; the planner guarantees this.
        if join_type in (LEFT_SEMI, LEFT_ANTI, EXISTENCE):
            assert build_side == "right"
        # (stream_cap, build_cap) -> (cand_cap, s_caps, b_caps): lets a
        # speculation scope skip the per-batch sizing sync (round 4)
        self._size_cache = {}
        # structural gather accounting (round 8): counts the probe's
        # materializing row gathers per iteration into numGathers /
        # gatherTimeNs (trace-time counts memoized per program key)
        from ..ops.gather import GatherTracker
        self._gather_track = GatherTracker(self.metrics[NUM_GATHERS],
                                           self.metrics[GATHER_TIME])
        # per-shape speculative-use counters driving cap decay (round 5)
        self._spec_uses = {}
        # round 5: absorb child Filters into the probe/build kernels as
        # key-validity masks — an invalid key never matches, so for join
        # shapes that emit ONLY matched rows from that side the filter's
        # compaction (sort + gather, ~40 ms per 2M-row batch on v5e) is
        # pure overhead. Build side: safe whenever unmatched build rows
        # are never emitted; stream side: inner/semi only (outer/anti
        # emit unmatched stream rows, which must already be filtered).
        from .basic import FilterExec
        self._stream_filter = None
        self._build_filter = None
        stream_idx = 0 if build_side == "right" else 1
        build_idx = 1 - stream_idx
        kids = list(self.children)
        if join_type in (INNER, LEFT_SEMI):
            preds = []
            while isinstance(kids[stream_idx], FilterExec):
                preds.append(kids[stream_idx]._bound)
                kids[stream_idx] = kids[stream_idx].child
            if preds:
                self._stream_filter = preds
        if not self._need_build_flags:
            preds = []
            while isinstance(kids[build_idx], FilterExec):
                preds.append(kids[build_idx]._bound)
                kids[build_idx] = kids[build_idx].child
            if preds:
                self._build_filter = preds
        self.children = tuple(kids)
        # compiled phases, built AFTER filter absorption (ISSUE 14):
        # the plan fingerprint keying the program-site cache must see
        # the final children + absorbed predicates. counts is sized by
        # the stream bucket; the probe body by stream + candidate
        # buckets (static per shape).
        self._jit_build = self._site(self._build_kernel,
                                     label="HashJoinExec.build")
        self._jit_counts = self._site(self._counts_kernel,
                                      label="HashJoinExec.counts")
        self._jit_probe = self._site(self._probe_kernel,
                                     label="HashJoinExec.probe",
                                     static_argnums=(5, 6, 7, 8))

    @property
    def consumes_encoded(self) -> bool:
        """Encoded inputs are fine when every key is a bare reference
        (the probe byte-compares through the dictionary spans and the
        bucket hash precomputes the dictionary's hashes once — ISSUE
        18) or string-reference-free, and the absorbed filters plus the
        residual condition pass the code-space walk."""
        from ..expr.predicates import (encoded_safe_predicate,
                                       encoded_safe_projection)
        try:
            lb = [resolve(e, self.left_schema) for e in self.left_keys]
            rb = [resolve(e, self.right_schema) for e in self.right_keys]
        except Exception:  # noqa: BLE001 — unresolvable = conservative
            return False
        if not all(encoded_safe_projection(e) for e in lb + rb):
            return False
        for preds in (self._stream_filter, self._build_filter):
            if preds and not all(encoded_safe_predicate(p) for p in preds):
                return False
        if self.condition is not None:
            pair = Schema(tuple(self.left_schema.fields)
                          + tuple(self.right_schema.fields))
            try:
                cond = resolve(self.condition, pair)
            except Exception:  # noqa: BLE001
                return False
            if not encoded_safe_predicate(cond):
                return False
        return True

    def _fingerprint_extras(self):
        # semantic_key, NOT repr (repr omits non-child expression
        # parameters — the program-cache soundness contract).
        # Non-deterministic expressions (a UDF predicate absorbed as a
        # stream/build filter keys per-INSTANCE by id, recyclable
        # after GC) opt the subtree out — see ProjectExec.
        exprs = list(self.left_keys) + list(self.right_keys) \
            + list(self._stream_filter or ()) \
            + list(self._build_filter or ())
        if self.condition is not None:
            exprs.append(self.condition)
        if not all(e.deterministic for e in exprs):
            return None

        def keys(es):
            return None if es is None else \
                tuple(e.semantic_key() for e in es)
        return (self.join_type, self.build_side, keys(self.left_keys),
                keys(self.right_keys),
                None if self.condition is None
                else self.condition.semantic_key(),
                self.exists_name,
                keys(self._stream_filter), keys(self._build_filter))

    # -- schema ------------------------------------------------------------
    @property
    def left_schema(self) -> Schema:
        return self.children[0].output_schema

    @property
    def right_schema(self) -> Schema:
        return self.children[1].output_schema

    @property
    def output_schema(self) -> Schema:
        if self.join_type in (LEFT_SEMI, LEFT_ANTI):
            return self.left_schema
        if self.join_type == EXISTENCE:
            return Schema(tuple(self.left_schema.fields) +
                          (StructField(self.exists_name, BooleanType(), False),))
        lf = [StructField(f.name, f.data_type,
                          f.nullable or self.join_type in (RIGHT_OUTER, FULL_OUTER))
              for f in self.left_schema.fields]
        rf = [StructField(f.name, f.data_type,
                          f.nullable or self.join_type in (LEFT_OUTER, FULL_OUTER))
              for f in self.right_schema.fields]
        return Schema(tuple(lf + rf))

    def additional_metrics(self):
        return (BUILD_TIME, JOIN_TIME, (NUM_INPUT_BATCHES, DEBUG)) \
            + GATHER_METRICS + DISPATCH_METRICS

    @property
    def output_grouped_by(self):
        """INNER-join output batches are emitted key-grouped (the pair
        compaction carries the packed key lanes — see _probe_kernel); one
        equivalence class per key pair, since left key == right key on
        every emitted row."""
        if self.join_type != INNER:
            return None
        out_names = [f.name for f in self.output_schema.fields]
        classes = []
        for lk, rk in zip(self.left_keys, self.right_keys):
            for e, sch in ((lk, self.left_schema), (rk, self.right_schema)):
                try:
                    dt = resolve(e, sch).data_type
                except (KeyError, TypeError):
                    return None
                from ..types import DecimalType
                if not dt.is_fixed_width or isinstance(dt, DecimalType):
                    # string/decimal keys are not in the packed lanes
                    return None
            names = set()
            for e in (lk, rk):
                n = getattr(e, "name", None)
                if n and out_names.count(n) == 1:
                    names.add(n)
            if not names:
                return None  # an unnamed key: grouping not expressible
            classes.append(frozenset(names))
        return tuple(classes)

    @staticmethod
    def _filter_mask(preds, batch: ColumnarBatch):
        keep = None
        for p in preds:
            c = p.columnar_eval(batch)
            k = c.data & c.validity  # Spark: null predicate rows drop
            keep = k if keep is None else (keep & k)
        return keep

    @staticmethod
    def _mask_keys(key_cols, keep):
        """AND an absorbed-filter mask into key validity (invalid keys
        never match; dropped rows vanish from matched-only outputs)."""
        from ..columnar.column import (ArrayColumn, MapColumn,
                                       StringColumn, StructColumn)
        out = []
        for c in key_cols:
            v = c.validity & keep
            if isinstance(c, DictionaryColumn):
                out.append(DictionaryColumn(c.codes, c.dict_data,
                                            c.dict_offsets, v, c.dtype))
            elif isinstance(c, StringColumn):
                out.append(StringColumn(c.data, c.offsets, v, c.dtype))
            elif isinstance(c, StructColumn):
                out.append(type(c)(c.children, v, c.dtype))
            elif isinstance(c, MapColumn):
                out.append(MapColumn(c.keys, c.values, c.offsets, v,
                                     c.dtype))
            elif isinstance(c, ArrayColumn):
                out.append(ArrayColumn(c.child, c.offsets, v, c.dtype))
            else:
                out.append(Column(c.data, v, c.dtype))
        return out

    # -- build -------------------------------------------------------------
    def _build_kernel(self, batch: ColumnarBatch) -> BuildTable:
        build_child = self.children[1] if self.build_side == "right" \
            else self.children[0]
        keys = self.right_keys if self.build_side == "right" else self.left_keys
        bound = bind_projection(keys, build_child.output_schema)
        key_cols = [e.columnar_eval(batch) for e in bound]
        if self._build_filter is not None:
            key_cols = self._mask_keys(
                key_cols, self._filter_mask(self._build_filter, batch))
        # prepare the fused probe's key-lane tables only when the tier
        # selector could ever pick the Pallas kernel (off / auto-without-
        # a-recorded-win joins pay nothing for them)
        from ..ops.pallas_tier import family_may_engage
        return BuildTable.build(key_cols, list(batch.columns),
                                batch.num_rows, batch.capacity,
                                with_key_lanes=family_may_engage(
                                    "join_probe"))

    def _build(self) -> Tuple[BuildTable, ColumnarBatch]:
        build_child = self.children[1] if self.build_side == "right" \
            else self.children[0]
        with self.metrics[BUILD_TIME].ns_timer():
            batches = list(build_child.execute())
            if len(batches) > 1:
                # distinct per-batch dictionaries cannot concatenate
                # shape-stably (ops/basic.concat_columns asserts) —
                # decode first; a single-batch build side (the common
                # broadcast shape) stays encoded end-to-end
                from ..columnar.encoded import materialize_batch
                batches = [materialize_batch(b, seam="concat")
                           for b in batches]
            if batches:
                batch = concat_batches(batches, build_child.output_schema)
            else:
                from ..columnar.batch import empty_batch
                batch = empty_batch(build_child.output_schema)
            return self._jit_build(batch), batch

    @property
    def _need_build_flags(self) -> bool:
        jt, bs = self.join_type, self.build_side
        return ((jt in (RIGHT_OUTER, FULL_OUTER) and bs == "right")
                or (jt in (LEFT_OUTER, FULL_OUTER) and bs == "left"))

    # -- probe -------------------------------------------------------------
    def internal_execute(self) -> Iterator[ColumnarBatch]:
        build, build_batch = self._build()
        stream_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        build_matched = jnp.zeros((build.capacity,), jnp.bool_)

        join_time = self.metrics[JOIN_TIME]
        try:
            for stream_batch in stream_child.execute():
                with join_time.ns_timer():
                    out, build_matched = self._probe_one(
                        build, build_batch, stream_batch, build_matched)
                if out is not None:
                    yield out

            if self._need_build_flags:
                with join_time.ns_timer():
                    yield self._emit_build_unmatched(build, build_batch,
                                                     build_matched)
        finally:
            # one gather_stats event per execution (the pipeline-event
            # convention): reconciles with the numGathers metric and
            # the op_close batch count
            self._gather_track.emit_event(type(self).__name__,
                                          self._op_id)

    def _counts_kernel(self, build: BuildTable, stream_batch: ColumnarBatch):
        stream_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        stream_keys = self.left_keys if self.build_side == "right" \
            else self.right_keys
        bound = bind_projection(stream_keys, stream_child.output_schema)
        skey_cols = [e.columnar_eval(stream_batch) for e in bound]
        if self._stream_filter is not None:
            skey_cols = self._mask_keys(
                skey_cols,
                self._filter_mask(self._stream_filter, stream_batch))
        lo, counts, _ = probe_counts(build, skey_cols,
                                     stream_batch.num_rows,
                                     stream_batch.capacity)
        act = active_mask(stream_batch.num_rows, stream_batch.capacity)
        needs = _string_byte_needs(stream_batch.columns, build, lo, counts,
                                   act)
        return lo, counts, skey_cols, jnp.sum(counts.astype(jnp.int64)), needs

    def _probe_kernel(self, build: BuildTable, build_batch: ColumnarBatch,
                      stream_batch: ColumnarBatch, lo_counts, build_matched,
                      cand_cap: int, s_caps: Tuple = (), b_caps: Tuple = (),
                      use_fused: bool = False):
        """Packed-row probe (round 4): the build side's fixed-width
        keys+payload live in ONE sorted u32 matrix (+ f64 matrix), so the
        whole candidate-verify-compact-emit pipeline is a handful of row
        gathers instead of 2 gathers per column (reference JoinGatherer
        gathers; measured ~20x on the q3 shape, tools/exp_gather.py).

        use_fused (static, chosen by the measured tier selector): the
        expand+verify stage runs as ONE Pallas kernel streaming candidate
        tiles through VMEM (ops/pallas_join.fused_probe_verify) instead
        of separate XLA programs with candidate-level full-width
        intermediates.

        Gather elimination (round 8): BOTH tiers now defer the payload
        to ONE output-level packed gather per side after compaction —
        the candidate level touches only key lanes (XLA tier) or
        nothing (fused tier). Per iteration the emit is one index
        materialization + one packed payload gather per side, counted
        structurally by the gather engine (ops/gather) into the
        numGathers metric."""
        from ..ops import gather as G
        from ..ops.rowpack import pack_rows, unpack_rows
        lo, counts, skey_cols = lo_counts
        s_caps = s_caps or (None,) * len(stream_batch.columns)
        b_caps = b_caps or (None,) * len(build.payload)
        scap = stream_batch.capacity

        (plan_k, kmat_b, kfmat_b, plan_p, pmat_b, pfmat_b,
         kpi, ppi, poi) = build.pack

        # structural eligibility is static per trace: integer keys on
        # both sides with matching lane widths, i32 candidate space
        sk_lanes_v = int_key_lanes(skey_cols) if use_fused else None
        fused = (use_fused and build.key_lanes is not None
                 and sk_lanes_v is not None
                 and len(sk_lanes_v[0]) == len(build.key_lanes[0])
                 and cand_cap < (1 << 31))

        if fused:
            from ..ops.pallas_join import fused_probe_verify
            from ..ops.pallas_kernels import on_tpu
            bk_lanes, bvalid = build.key_lanes
            sk_lanes, svalid = sk_lanes_v
            verified, s_idx, b_pos, b_row = fused_probe_verify(
                lo, counts, bk_lanes, bvalid, sk_lanes, svalid,
                build.perm, cand_cap, interpret=not on_tpu())
            total_dev = jnp.sum(counts.astype(jnp.int64)) \
                if counts.shape[0] else jnp.int64(0)
            pair_valid = s_idx >= 0
            b_pos_m = jnp.where(pair_valid, b_pos, -1)
            need_b_row = True  # the kernel emits it in the same pass
            ki_c = kf_c = None
        else:
            s_idx, b_pos, total_dev = expand_candidates(lo, counts,
                                                        cand_cap)
            pair_valid = s_idx >= 0
            b_pos_m = jnp.where(pair_valid, b_pos, -1)

            # --- verify: keys packable on BOTH sides compare via
            # KEY-ONLY candidate-level row gathers (the payload no
            # longer rides them), the rest via the per-column path ---
            from ..ops.rowpack import is_packable
            kpi_pos = {ki: pos for pos, ki in enumerate(kpi)}
            pk = [ki for ki in kpi if is_packable(skey_cols[ki])]

            # sorted position -> original build row; only needed for
            # varlen columns, fallback keys and residual conditions
            need_b_row = bool(poi) or self.condition is not None or \
                len(pk) < len(skey_cols)
            b_row = gather_column_indices(build.perm, b_pos_m) \
                if need_b_row else None
            ok = pair_valid
            ki_c = kf_c = None
            if pk:
                ki_c, kf_c = G.gather_rows(plan_k, kmat_b, kfmat_b,
                                           b_pos_m)
                bk_cand = unpack_rows(plan_k, ki_c, kf_c,
                                      only=[kpi_pos[ki] for ki in pk])
                plan_sk, imat_sk, fmat_sk = pack_rows(
                    [skey_cols[ki] for ki in pk])
                ski_c, skf_c = G.gather_rows(
                    plan_sk, imat_sk, fmat_sk,
                    jnp.where(pair_valid, s_idx, -1))
                sk_cand = unpack_rows(plan_sk, ski_c, skf_c)
                for b, s in zip(bk_cand, sk_cand):
                    ok = ok & (b.data == s.data) & b.validity & s.validity
            pk_set = set(pk)
            for ki in range(len(skey_cols)):
                if ki in pk_set:
                    continue
                bk = build.key_cols[ki]
                sk = skey_cols[ki]
                if isinstance(bk, DictionaryColumn) or \
                        isinstance(sk, DictionaryColumn):
                    # encoded key (ISSUE 18): byte-compare through
                    # spans into the ORIGINAL buffers — no decode, and
                    # no materialized candidate gather (whose byte
                    # bucket a join fan-out overflows)
                    from ..columnar.encoded import bytes_equal_at
                    ok = ok & bytes_equal_at(
                        bk, b_row, sk,
                        jnp.where(pair_valid, s_idx, -1))
                    continue
                b = gather_column(bk, b_row)
                s = gather_column(sk, jnp.where(pair_valid, s_idx, -1))
                if isinstance(bk, StringColumn):
                    eq = string_equal(b, s)
                    ok = ok & eq.data & eq.validity
                else:
                    from ..columnar.column import Decimal128Column
                    if isinstance(bk, Decimal128Column):
                        # two-limb equality (round 5: decimal128 keys)
                        ok = ok & (b.hi.data == s.hi.data) \
                            & (b.lo.data == s.lo.data) \
                            & b.validity & s.validity
                    else:
                        ok = ok & (b.data == s.data) \
                            & b.validity & s.validity
            verified = ok
        if self.condition is not None:
            verified = verified & self._eval_condition(
                stream_batch, build_batch, s_idx, b_row, cand_cap,
                s_caps, b_caps)

        jt, bs = self.join_type, self.build_side
        stream_preserved = (jt == LEFT_OUTER and bs == "right") or \
            (jt == RIGHT_OUTER and bs == "left") or jt == FULL_OUTER

        if self._need_build_flags:
            # flags live in SORTED build space; translated once at
            # _emit_build_unmatched
            build_matched = build_matched | matched_flags(
                verified, b_pos_m, build.capacity)

        if jt in (LEFT_SEMI, LEFT_ANTI, EXISTENCE):
            smatched = matched_flags(verified, s_idx, scap)
            if jt == EXISTENCE:
                flag = Column(smatched, jnp.ones((scap,), jnp.bool_),
                              BooleanType())
                cols = list(stream_batch.columns) + [flag]
                return (ColumnarBatch(cols, stream_batch.num_rows,
                                      self.output_schema), build_matched)
            keep = smatched if jt == LEFT_SEMI else ~smatched
            perm, n = compaction_order(keep, stream_batch.num_rows)
            cols = _gather_batch(stream_batch.columns, perm, n)
            return ColumnarBatch(cols, n, self.output_schema), build_matched

        # --- compact verified pairs ---
        # (pk == kpi whenever every key is fixed-width, the same
        # condition output_grouped_by promises grouping under)
        grouped_emit = jt == INNER and len(kpi) == len(skey_cols) \
            and (fused or len(pk) == len(kpi))
        if grouped_emit:
            # key-grouped emission (round 5): carry the packed build-key
            # lanes as extra sort keys so equal join keys land contiguous
            # in the output — a downstream group-by on the join keys then
            # skips its own sort (output_grouped_by). Extra sort lanes
            # are ~free on v5e (docs/perf.md r5). Key LANES, not b_pos:
            # the build table is hash-sorted, so two distinct keys
            # sharing a 64-bit hash could interleave by position.
            act_c = active_mask(total_dev, cand_cap)
            kflag = verified & act_c
            if fused:
                # the fused probe never materialized candidate-level key
                # gathers; the sort lanes come straight from the
                # VMEM-resident u32 key-lane tables (any consistent total
                # order over key bit patterns groups equal keys)
                safe_c = jnp.clip(b_pos_m, 0,
                                  build.key_lanes[0][0].shape[0] - 1)
                klanes = [jnp.where(kflag, ln[safe_c], jnp.uint32(0))
                          for ln in build.key_lanes[0]]
            else:
                # key lanes from the candidate-level KEY pack (already
                # gathered for the verify above)
                nvl = plan_k.n_valid_lanes
                klanes = []
                for pos in range(len(kpi)):
                    kind, lane = plan_k.kinds[pos]
                    if kind == "f64":
                        klanes.append(kf_c[:, lane])
                    elif kind == "w2":
                        klanes.append(ki_c[:, nvl + lane])
                        klanes.append(ki_c[:, nvl + lane + 1])
                    else:
                        klanes.append(ki_c[:, nvl + lane])
            iota_c = jnp.arange(cand_cap, dtype=jnp.int32)
            res = jax.lax.sort(
                ((~kflag).astype(jnp.uint32), *klanes, iota_c),
                num_keys=2 + len(klanes))
            perm_c = res[-1]
            n_pairs = jnp.sum(kflag, dtype=jnp.int32)
        else:
            perm_c, n_pairs = compaction_order(verified, total_dev)
        # compact ONLY the 2-3 index lanes (round 8, BOTH tiers); the
        # full-width payload gather happens ONCE, at output level, below
        lanes = [s_idx, b_pos_m] + ([b_row] if need_b_row else [])
        lane_mat = jnp.stack(lanes, axis=1)

        if stream_preserved:
            smatched = matched_flags(verified, s_idx, scap)
            un_idx, n_un = unmatched_indices(smatched, stream_batch.num_rows,
                                             scap)
            out_cap = bucket_capacity(cand_cap + scap)
            n_out = n_pairs + n_un
            i = jnp.arange(out_cap, dtype=jnp.int32)
            from_pairs = i < n_pairs
            perm_pad = jnp.concatenate(
                [perm_c, jnp.full((out_cap - cand_cap,), cand_cap,
                                  jnp.int32)]) if out_cap > cand_cap \
                else perm_c
            bsel = jnp.where(from_pairs, perm_pad, -1)
            tail = (~from_pairs) & (i < n_out)
            # shift the unmatched tail to start at n_pairs with a roll
            # (two dynamic slices) instead of a full-width index gather
            un_pad = jnp.concatenate(
                [un_idx, jnp.full((out_cap - scap,), -1, jnp.int32)]) \
                if out_cap > scap else un_idx[:out_cap]
            un_part = jnp.roll(un_pad, n_pairs)
        else:
            out_cap = cand_cap
            n_out = n_pairs
            i = jnp.arange(out_cap, dtype=jnp.int32)
            from_pairs = i < n_pairs
            bsel = jnp.where(from_pairs, perm_c, -1)
            tail = None
            un_part = None

        # ONE index materialization: the compacted selection reads only
        # the index lanes; out-of-range bsel rows read row 0 and are
        # masked by from_pairs
        g = G.gather_lane_matrix(lane_mat, bsel)
        s_map = jnp.where(from_pairs, g[:, 0], -1)
        if tail is not None:
            s_map = jnp.where(tail, un_part, s_map)
        b_pos_out = jnp.where(from_pairs, g[:, 1], -1)
        b_map = jnp.where(from_pairs, g[:, 2], -1) if need_b_row else None

        # build-side output columns: ONE output-level packed payload
        # gather — only SURVIVING pairs move the full payload width
        # (before round 8 the XLA tier paid it at candidate level and
        # again at output level); varlen columns ride b_map
        bcols: List[Optional[Column]] = [None] * len(build.payload)
        if ppi:
            pmat_out, pfmat_out = G.gather_rows(plan_p, pmat_b, pfmat_b,
                                                b_pos_out)
            for j, c in zip(ppi, unpack_rows(plan_p, pmat_out,
                                             pfmat_out)):
                bcols[j] = c
        for j in poi:
            bcols[j] = gather_column(build.payload[j], b_map,
                                     out_byte_capacity=b_caps[j])
        # stream-side output columns: one packed row gather by s_map
        scols = _gather_batch(stream_batch.columns, s_map, n_out, s_caps)
        bcols_f = [c for c in bcols if c is not None]
        left_cols = scols if self.build_side == "right" else bcols_f
        right_cols = bcols_f if self.build_side == "right" else scols
        return (ColumnarBatch(left_cols + right_cols, n_out,
                              self.output_schema), build_matched)

    def _probe_one(self, build: BuildTable, build_batch: ColumnarBatch,
                   stream_batch: ColumnarBatch, build_matched):
        from .speculation import current_scope, speculation_allowed
        lo, counts, skey_cols, total_dev, needs_dev = \
            self._jit_counts(build, stream_batch)
        key = (stream_batch.capacity, build.capacity)
        cached = self._size_cache.get(key)
        if cached is not None and speculation_allowed():
            # Bounded-staleness refresh (ADVICE/VERDICT r4): caps grew
            # monotonically, so one pathological batch used to inflate
            # every later probe of the shape forever. After SPEC_REFRESH
            # SPECULATIVE uses (the measured branch re-syncs exact needs
            # anyway) the entry expires and the next probe re-measures
            # FRESH (no monotone max), letting caps shrink back; stable
            # workloads re-derive the same bucket sizes so the compiled
            # kernel is reused.
            self._spec_uses[key] = self._spec_uses.get(key, 0) + 1
            if self._spec_uses[key] > self.SPEC_REFRESH:
                del self._size_cache[key]
                self._spec_uses[key] = 0
                cached = None
        if cached is not None and speculation_allowed():
            # speculative sizing (round 4): reuse the last buckets for this
            # shape and record a device overflow flag with the scope
            # instead of paying the ~100 ms tunnel round trip per stream
            # batch; a tripped scope re-runs the plan exactly (the same
            # optimistic-then-redo contract as the masked-bucket
            # aggregate, exec/speculation.py)
            cand_cap, s_caps, b_caps = cached
            flag = total_dev > cand_cap
            s_needs, b_needs = needs_dev
            # the zip below pairs byte-needs with caps positionally; if
            # _string_byte_needs and _byte_cap_tuple ever drift in column
            # order/count a silent mis-pairing could fail to trip the flag
            # and ship truncated payloads — guard the lengths
            assert len(list(s_needs)) == sum(c is not None for c in s_caps), \
                (len(list(s_needs)), s_caps)
            assert len(list(b_needs)) == sum(c is not None for c in b_caps), \
                (len(list(b_needs)), b_caps)
            for need, cap in zip(list(s_needs) + list(b_needs),
                                 [c for c in s_caps if c is not None]
                                 + [c for c in b_caps if c is not None]):
                flag = flag | (need > cap)
            current_scope().record(flag)
        else:
            # ONE host sync per stream batch sizes the candidate bucket AND
            # the string byte buckets (exact measured needs, no truncation)
            total, (s_needs, b_needs) = jax.device_get((total_dev, needs_dev))
            cand_cap = bucket_capacity(max(int(total), 1))
            s_caps = _byte_cap_tuple(stream_batch.columns, s_needs)
            b_caps = _byte_cap_tuple(build.payload, b_needs)
            if cached is not None:
                # keep buckets monotone so steady state stays compiled
                oc, os_, ob = cached
                cand_cap = max(cand_cap, oc)
                s_caps = tuple(None if c is None else max(c, o)
                               for c, o in zip(s_caps, os_))
                b_caps = tuple(None if c is None else max(c, o)
                               for c, o in zip(b_caps, ob))
            self._size_cache[key] = (cand_cap, s_caps, b_caps)
        from ..ops.pallas_tier import fused_tier_enabled
        use_fused = build.key_lanes is not None and fused_tier_enabled(
            "join_probe", (stream_batch.capacity, build.capacity))
        with self._gather_track.observe(
                (stream_batch.capacity, build.capacity, cand_cap,
                 s_caps, b_caps, use_fused)):
            return self._jit_probe(build, build_batch, stream_batch,
                                   (lo, counts, skey_cols), build_matched,
                                   cand_cap, s_caps, b_caps, use_fused)

    def _emit_build_unmatched(self, build: BuildTable,
                              build_batch: ColumnarBatch, build_matched):
        with self._gather_track.observe(("unmatched", build.capacity)):
            return self._emit_build_unmatched_inner(build, build_batch,
                                                    build_matched)

    def _emit_build_unmatched_inner(self, build: BuildTable,
                                    build_batch: ColumnarBatch,
                                    build_matched):
        # probe flags live in SORTED build space; translate to original
        # rows once per join (perm is a permutation, so the scatter is
        # exact)
        matched_orig = jnp.zeros((build.capacity,), jnp.int32).at[
            build.perm].max(build_matched.astype(jnp.int32)) > 0
        un_idx, n_un = unmatched_indices(matched_orig, build.num_rows,
                                         build.capacity)
        bcols = _gather_batch(build.payload, un_idx, n_un)
        stream_schema = self.left_schema if self.build_side == "right" \
            else self.right_schema
        null_map = jnp.full((build.capacity,), -1, jnp.int32)
        stream_child = self.children[0] if self.build_side == "right" \
            else self.children[1]
        from ..columnar.batch import empty_batch
        nulls = empty_batch(stream_schema, capacity=build.capacity)
        scols = [gather_column(c, null_map) for c in nulls.columns]
        left_cols = scols if self.build_side == "right" else bcols
        right_cols = bcols if self.build_side == "right" else scols
        return ColumnarBatch(left_cols + right_cols, n_un, self.output_schema)

    def _eval_condition(self, stream_batch, build_batch, s_idx, b_row,
                        cand_cap: int, s_caps: Tuple = (),
                        b_caps: Tuple = ()):
        """Evaluate the residual condition over candidate pairs: build a
        pair batch of gathered left+right columns in output order."""
        s_caps = s_caps or (None,) * len(stream_batch.columns)
        b_caps = b_caps or (None,) * len(build_batch.columns)
        scols = [gather_column(c, s_idx, out_byte_capacity=bc)
                 for c, bc in zip(stream_batch.columns, s_caps)]
        bcols = [gather_column(c, b_row, out_byte_capacity=bc)
                 for c, bc in zip(build_batch.columns, b_caps)]
        left_cols = scols if self.build_side == "right" else bcols
        right_cols = bcols if self.build_side == "right" else scols
        lf = list(self.left_schema.fields)
        rf = list(self.right_schema.fields)
        pair_schema = Schema(tuple(lf + rf))
        pair = ColumnarBatch(left_cols + right_cols,
                             jnp.int32(cand_cap), pair_schema)
        bound = resolve(self.condition, pair_schema)
        pred = bound.columnar_eval(pair)
        return pred.data & pred.validity

    def node_description(self):
        return (f"HashJoinExec[{self.join_type}, build={self.build_side}, "
                f"lkeys={self.left_keys!r}, rkeys={self.right_keys!r}]")


class NestedLoopJoinExec(TpuExec):
    """Broadcast nested-loop / cartesian product join (reference
    GpuBroadcastNestedLoopJoinExecBase, GpuCartesianProductExec): all pairs
    in chunks, residual condition filters. Supports inner/cross and
    stream-preserved outer/semi/anti with build == right."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 join_type: str = CROSS,
                 condition: Optional[Expression] = None,
                 chunk_rows: int = 1 << 16):
        super().__init__(left, right)
        self.join_type = join_type
        self.condition = condition
        self.chunk_rows = chunk_rows
        assert join_type in (INNER, CROSS, LEFT_OUTER, LEFT_SEMI, LEFT_ANTI,
                             EXISTENCE)

    @property
    def output_schema(self) -> Schema:
        if self.join_type in (LEFT_SEMI, LEFT_ANTI):
            return self.children[0].output_schema
        if self.join_type == EXISTENCE:
            return Schema(tuple(self.children[0].output_schema.fields) +
                          (StructField("exists", BooleanType(), False),))
        lf = list(self.children[0].output_schema.fields)
        rf = [StructField(f.name, f.data_type,
                          f.nullable or self.join_type == LEFT_OUTER)
              for f in self.children[1].output_schema.fields]
        return Schema(tuple(lf + rf))

    @staticmethod
    def _max_lens(batch: ColumnarBatch, n_rows: int) -> List[Optional[int]]:
        """Max string byte length per column (None for fixed-width); ONE
        host sync per batch (stacked fetch), hoisted out of the chunk
        loop."""
        from ..ops.strings import string_lengths
        maxes = []
        for c in batch.columns:
            if isinstance(c, StringColumn):
                act = jnp.arange(c.capacity, dtype=jnp.int32) < n_rows
                maxes.append(jnp.max(jnp.where(act, string_lengths(c), 0)))
        if not maxes:
            return [None] * len(batch.columns)
        fetched = iter(jax.device_get(jnp.stack(maxes)).tolist())
        return [int(next(fetched)) if isinstance(c, StringColumn) else None
                for c in batch.columns]

    @staticmethod
    def _chunk_byte_caps(max_lens: List[Optional[int]], chunk_cap: int
                         ) -> Tuple:
        """Cross joins duplicate every row: size each string column's
        output byte bucket from its max row length × chunk capacity (the
        input bucket truncates once duplicated bytes exceed it)."""
        return tuple(None if ml is None
                     else bucket_capacity(max(chunk_cap * ml, 8))
                     for ml in max_lens)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        right_batches = list(self.children[1].execute())
        if right_batches:
            build = concat_batches(right_batches,
                                   self.children[1].output_schema)
        else:
            from ..columnar.batch import empty_batch
            build = empty_batch(self.children[1].output_schema)
        b_rows = build.num_rows_host
        b_lens = self._max_lens(build, b_rows)

        for stream in self.children[0].execute():
            s_rows = stream.num_rows_host
            s_lens = self._max_lens(stream, s_rows)
            total = s_rows * b_rows
            jt = self.join_type
            smatched = jnp.zeros((stream.capacity,), jnp.bool_)
            start = 0
            while start < total:
                chunk = min(self.chunk_rows, total - start)
                cap = bucket_capacity(max(chunk, 1))
                # the capacity bucket may exceed the nominal chunk; emit a
                # full bucket's worth and advance by what was emitted
                chunk = min(total - start, cap)
                s_idx, b_idx, n = cross_pairs(
                    jnp.int32(s_rows), jnp.int32(b_rows), jnp.int32(start), cap)
                s_caps = self._chunk_byte_caps(s_lens, cap)
                b_caps = self._chunk_byte_caps(b_lens, cap)
                verified = (s_idx >= 0)
                if self.condition is not None:
                    verified = verified & self._condition_mask(
                        stream, build, s_idx, b_idx, cap, s_caps, b_caps)
                if jt in (LEFT_SEMI, LEFT_ANTI, EXISTENCE, LEFT_OUTER):
                    smatched = smatched | matched_flags(
                        verified, s_idx, stream.capacity)
                if jt in (INNER, CROSS, LEFT_OUTER):
                    s_map, b_map, n_pairs = inner_gather_maps(
                        verified, s_idx, b_idx, n)
                    scols = _gather_batch(stream.columns, s_map, n_pairs,
                                          s_caps)
                    bcols = _gather_batch(build.columns, b_map, n_pairs,
                                          b_caps)
                    yield ColumnarBatch(scols + bcols, n_pairs,
                                        self.output_schema)
                start += chunk
            # stream-preserved tails
            if jt == LEFT_OUTER:
                un_idx, n_un = unmatched_indices(smatched, stream.num_rows,
                                                 stream.capacity)
                scols = _gather_batch(stream.columns, un_idx, n_un)
                null_map = jnp.full((stream.capacity,), -1, jnp.int32)
                bcols = [gather_column(c, null_map) for c in build.columns]
                yield ColumnarBatch(scols + bcols, n_un, self.output_schema)
            elif jt in (LEFT_SEMI, LEFT_ANTI):
                keep = smatched if jt == LEFT_SEMI else ~smatched
                perm, n_keep = compaction_order(keep, stream.num_rows)
                cols = [gather_column(
                    c, jnp.where(active_mask(n_keep, stream.capacity), perm, -1))
                    for c in stream.columns]
                yield ColumnarBatch(cols, n_keep, self.output_schema)
            elif jt == EXISTENCE:
                flag = Column(smatched, jnp.ones((stream.capacity,), jnp.bool_),
                              BooleanType())
                yield ColumnarBatch(list(stream.columns) + [flag],
                                    stream.num_rows, self.output_schema)

    def _condition_mask(self, stream, build, s_idx, b_idx, cap: int,
                        s_caps: Tuple = (), b_caps: Tuple = ()):
        s_caps = s_caps or (None,) * len(stream.columns)
        b_caps = b_caps or (None,) * len(build.columns)
        scols = [gather_column(c, s_idx, out_byte_capacity=bc)
                 for c, bc in zip(stream.columns, s_caps)]
        bcols = [gather_column(c, b_idx, out_byte_capacity=bc)
                 for c, bc in zip(build.columns, b_caps)]
        pair_schema = Schema(tuple(self.children[0].output_schema.fields) +
                             tuple(self.children[1].output_schema.fields))
        pair = ColumnarBatch(scols + bcols, jnp.int32(cap), pair_schema)
        bound = resolve(self.condition, pair_schema)
        pred = bound.columnar_eval(pair)
        return pred.data & pred.validity


class AdaptiveJoinExec(TpuExec):
    """AQE-lite join (VERDICT r2 item 10): when plan-time size estimation
    returns unknown, materialize the build side FIRST (a hash join would
    anyway), measure its real padded device bytes with no host sync, and
    pick the strategy at runtime — broadcast-style single-build when it
    fits the broadcast threshold, sub-partitioned when it exceeds the
    sub-partition threshold (MULTITHREADED mode), plain hash join
    otherwise. The reference reaches the same decision through AQE
    query-stage statistics; standalone, the exec measures its own child."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str, condition: Optional[Expression],
                 conf):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition
        self._conf = conf
        # schema comes from the plain-shape join (all strategies agree)
        from .basic import InMemoryScanExec
        self._template = HashJoinExec(
            InMemoryScanExec([], left.output_schema),
            InMemoryScanExec([], right.output_schema),
            left_keys, right_keys, join_type, condition=condition)

    @property
    def output_schema(self) -> Schema:
        return self._template.output_schema

    def _materialize(self, side: TpuExec):
        """Drain a side into SPILLABLE batches + its padded byte size
        (reference GpuShuffledSymmetricHashJoinExec holds both sides
        spillable while deciding)."""
        sps, size = [], 0
        for b in side.execute():
            size += b.device_size_bytes()
            sps.append(SpillableBatch.from_batch(b))
        return sps, size

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        from ..config import (BROADCAST_SIZE_THRESHOLD,
                              JOIN_SUBPARTITION_THRESHOLD, SHUFFLE_MODE,
                              SHUFFLE_PARTITIONS)
        thr_b = self._conf.get(BROADCAST_SIZE_THRESHOLD)
        thr_sub = self._conf.get(JOIN_SUBPARTITION_THRESHOLD)
        multithreaded = self._conf.get(SHUFFLE_MODE).upper() \
            == "MULTITHREADED"
        left, right = self.children
        # quota-aware broadcast demotion (ISSUE 19 decision 2): the
        # measured build side must also fit the adaptive cap — the
        # tighter of adaptive.autoBroadcastMaxBytes and the admitting
        # ticket's workload quota share. A single-build plan whose
        # build MEASURES over the cap demotes to the sub-partitioned
        # strategy BEFORE the first OOM retry fires.
        from . import adaptive
        from ..config import ADAPTIVE_ENABLED
        cap_basis = None
        if self._conf.get(ADAPTIVE_ENABLED) and adaptive.consult(
                self._conf, op=type(self).__name__, op_id=self._op_id):
            cap_basis = adaptive.demote_cap(self._conf)
        r_sps, size_r = self._materialize(right)
        r_scan = _SpillableScanExec(r_sps, right.output_schema)
        swappable = self.join_type == "inner" and not self.condition
        demoted = False
        if thr_b >= 0 and size_r <= thr_b:
            if cap_basis is not None and size_r > cap_basis[0]:
                demoted = True
                adaptive.note_demote(
                    "broadcast_demote", op=type(self).__name__,
                    op_id=self._op_id, measured_bytes=size_r,
                    threshold=cap_basis[0], basis=cap_basis[1],
                    planned="build_right")
            else:
                # small build: stream the left side straight through
                self._measured = (None, size_r)
                self._choice = "build_right"
                join: TpuExec = HashJoinExec(
                    left, r_scan, self.left_keys, self.right_keys,
                    self.join_type, build_side="right",
                    condition=self.condition)
                yield from join.execute()
                return
        # symmetric: hold BOTH sides spillable, measure, decide
        l_sps, size_l = self._materialize(left)
        l_scan = _SpillableScanExec(l_sps, left.output_schema)
        self._measured = (size_l, size_r)
        # the side that would actually be BUILT must fit: only inner
        # joins without a condition may swap build sides
        build_size = min(size_l, size_r) if swappable else size_r
        # a demoted join sub-partitions when the to-be-built side still
        # exceeds the cap; the effective threshold is the tighter of
        # the static conf and the measured cap
        over_cap = (demoted and cap_basis is not None
                    and build_size > cap_basis[0])
        eff_sub = thr_sub
        if over_cap:
            eff_sub = cap_basis[0] if thr_sub < 0 \
                else min(thr_sub, cap_basis[0])
        if multithreaded and ((thr_sub >= 0 and build_size > thr_sub)
                              or over_cap):
            from .exchange import (HostShuffleExchangeExec,
                                   ShuffledHashJoinExec)
            # size k from the side that will actually be BUILT (build is
            # forced right for non-swappable joins — ADVICE r3 #4)
            k = min(256, max(self._conf.get(SHUFFLE_PARTITIONS),
                             -(-build_size // max(eff_sub, 1))))
            lex = HostShuffleExchangeExec(self.left_keys, l_scan,
                                          int(k), self._conf)
            rex = HostShuffleExchangeExec(self.right_keys, r_scan, int(k),
                                          self._conf)
            self._choice = "subpartition"
            join = ShuffledHashJoinExec(
                lex, rex, self.left_keys, self.right_keys,
                self.join_type, condition=self.condition)
        else:
            # build the measured-smaller side (runtime build-side choice;
            # only swap when semantics allow)
            build_left = swappable and size_l < size_r
            self._choice = "build_left" if build_left else "build_right"
            join = HashJoinExec(
                l_scan, r_scan, self.left_keys, self.right_keys,
                self.join_type,
                build_side="left" if build_left else "right",
                condition=self.condition)
        yield from join.execute()

    @property
    def child(self) -> TpuExec:
        return self.children[0]

    def node_description(self):
        return f"AdaptiveJoinExec {self.join_type}"


class _SpillableScanExec(TpuExec):
    """Leaf replaying spillable batches (unspilling on demand); each
    batch releases its pin after the downstream consumes it."""

    def __init__(self, sps, schema: Schema):
        super().__init__()
        self._sps = sps
        self._schema = schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        # single-consumption scan: handles free eagerly as consumed
        for sp in self._sps:
            b = sp.get_batch()
            sp.release()
            sp.close()
            yield b


