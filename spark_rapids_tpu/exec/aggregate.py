"""HashAggregateExec — reference GpuHashAggregateExec
(GpuAggregateExec.scala:1711) + GpuMergeAggregateIterator:711 rebuilt around
the sort-based segment-reduce kernel (ops/aggregate.py).

Flow (complete mode):
  1. per input batch: pre-project [group keys..., agg inputs...]
  2. update group-by -> batch of [keys..., buffer cols...] (first-pass agg)
  3. aggregated batches accumulate as SpillableBatch
  4. merge: concat + re-aggregate with merge ops (reference
     tryMergeAggregatedBatches:803; our kernel IS the sort fallback :909,
     so the two reference paths collapse into one here)
  5. evaluate buffers -> output projection

`partial` mode stops after 4 and emits keys+buffers (feeds a shuffle);
`final` consumes keys+buffers batches and runs 4-5. This mirrors Spark's
partial/final split so distributed aggregation reuses the same exec.
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn
from ..expr.aggexprs import AggregateFunction
from ..expr.core import Expression, output_name, resolve
from ..memory.retry import (
    TpuSplitAndRetryOOM, split_in_half_by_rows, with_retry,
)
from ..memory.spillable import SpillableBatch
from ..ops.aggregate import (
    groupby_aggregate, groupby_aggregate_hash, reduce_no_keys,
)
from ..ops.basic import active_mask, sanitize
from ..ops.sort import string_words_for
from ..types import DataType, LongType, Schema, StructField
from .base import AGG_TIME, CONCAT_TIME, NUM_INPUT_BATCHES, NUM_INPUT_ROWS, TpuExec
from .basic import bind_projection, eval_projection
from .coalesce import concat_batches


@partial(jax.jit, static_argnums=(1,))
def _shrink_batch(batch: ColumnarBatch, cap: int) -> ColumnarBatch:
    """Move the active prefix into a smaller capacity bucket: aggregated
    partials carry few groups in huge input-sized buckets; merging at input
    size would sort mostly-padding (the dominant waste in a groupby)."""
    from ..ops.basic import slice_rows
    cols = [slice_rows(c, jnp.int32(0), batch.num_rows, cap)
            for c in batch.columns]
    return ColumnarBatch(cols, batch.num_rows, batch.schema)


class AggregateExec(TpuExec):
    def __init__(self, group_exprs: Sequence[Expression],
                 aggregates: Sequence[Tuple[AggregateFunction, str]],
                 child: TpuExec, mode: str = "complete"):
        super().__init__(child)
        assert mode in ("complete", "partial", "final")
        self.mode = mode
        self.group_exprs = list(group_exprs)
        self.aggregates = list(aggregates)
        in_schema = child.output_schema

        # compiled kernels (cache keyed by capacity bucket + string words)
        self._jit_update = jax.jit(self._update_batch, static_argnums=(1,))
        self._jit_merge = jax.jit(self._merge_batch, static_argnums=(1,))
        # hash-path tiers: cheap 2-round first, 6-round escalation for
        # mid-cardinality, exact sort as the last resort
        self._jit_update_hash = {
            r: jax.jit(partial(self._update_batch, hash_path=True,
                               hash_rounds=r)) for r in (2, 6)}
        self._jit_merge_hash = {
            r: jax.jit(partial(self._merge_batch, hash_path=True,
                               hash_rounds=r)) for r in (2, 6)}
        self._jit_pre = jax.jit(self._pre_project)

        if mode == "final":
            # input is keys+buffers produced by a partial instance
            self._key_count = len(group_exprs)
            self._input_types = None
            self._buffer_schema = in_schema
        else:
            # pre-projection: keys then the union of agg inputs
            self._pre_exprs = list(self.group_exprs)
            self._input_slots: List[List[int]] = []
            for fn, _ in self.aggregates:
                slots = []
                for e in fn.inputs:
                    slot = len(self._pre_exprs)
                    self._pre_exprs.append(e.alias(f"_aggin{slot}"))
                    slots.append(slot)
                self._input_slots.append(slots)
            self._pre_bound = bind_projection(self._pre_exprs, in_schema)
            from .basic import projection_schema
            self._pre_schema = projection_schema(self._pre_exprs, in_schema)
            self._key_count = len(group_exprs)
            self._input_types = [
                [self._pre_schema.fields[s].data_type for s in slots]
                for slots in self._input_slots]
            self._buffer_schema = self._make_buffer_schema()

    # -- schemas -----------------------------------------------------------
    def _make_buffer_schema(self) -> Schema:
        fields = list(self._pre_schema.fields[: self._key_count])
        for i, (fn, name) in enumerate(self.aggregates):
            for j, bt in enumerate(fn.buffer_types(self._input_types[i])):
                fields.append(StructField(f"{name}#buf{j}", bt, True))
        return Schema(tuple(fields))

    @property
    def output_schema(self) -> Schema:
        if self.mode == "partial":
            return self._buffer_schema
        key_fields = list(self._buffer_schema.fields[: self._key_count])
        agg_fields = []
        bufs = self._buffer_schema.fields[self._key_count:]
        # result types: derive from buffer types for final mode
        pos = 0
        for i, (fn, name) in enumerate(self.aggregates):
            n_buf = len(fn.merge_ops())
            input_types = self._input_types[i] if self._input_types else \
                [bufs[pos].data_type]
            agg_fields.append(StructField(name, fn.result_type(input_types)))
            pos += n_buf
        return Schema(tuple(key_fields + agg_fields))

    def additional_metrics(self):
        return (AGG_TIME, CONCAT_TIME, NUM_INPUT_ROWS, NUM_INPUT_BATCHES)

    # -- kernels -----------------------------------------------------------
    def _pre_project(self, batch: ColumnarBatch) -> ColumnarBatch:
        return eval_projection(self._pre_bound, batch, self._pre_schema)

    def _update_batch(self, batch: ColumnarBatch, words: int = 4,
                      hash_path: bool = False, hash_rounds: int = 2):
        """First-pass aggregation of one pre-projected batch."""
        keys = list(batch.columns[: self._key_count])
        agg_inputs = []
        for i, (fn, _) in enumerate(self.aggregates):
            for (op, slot) in fn.update_ops():
                col = batch.columns[self._input_slots[i][slot]] \
                    if slot is not None else None
                agg_inputs.append((op, col))
        return self._run_groupby(keys, agg_inputs, batch,
                                 self._buffer_schema, words, hash_path,
                                 hash_rounds)

    def _merge_batch(self, batch: ColumnarBatch, words: int = 4,
                     hash_path: bool = False, hash_rounds: int = 2):
        """Re-aggregate a keys+buffers batch with merge ops."""
        keys = list(batch.columns[: self._key_count])
        agg_inputs = []
        pos = self._key_count
        for fn, _ in self.aggregates:
            for op in fn.merge_ops():
                agg_inputs.append((op, batch.columns[pos]))
                pos += 1
        return self._run_groupby(keys, agg_inputs, batch,
                                 self._buffer_schema, words, hash_path,
                                 hash_rounds)

    def _run_groupby(self, keys, agg_inputs, batch, out_schema, words: int,
                     hash_path: bool = False, hash_rounds: int = 2):
        cap = batch.capacity
        if not keys:
            # a count(*)-only aggregate has no input columns at all; give the
            # one-row output a real capacity bucket
            cap = max(cap, 128)
            results = reduce_no_keys(agg_inputs, batch.num_rows, cap)
            cols = []
            fields = out_schema.fields
            for (data, valid), f in zip(results, fields):
                act1 = active_mask(jnp.int32(1), cap)
                cols.append(Column(
                    jnp.where(act1, data.astype(f.data_type.jnp_dtype), 0),
                    valid & act1, f.data_type))
            out = ColumnarBatch(cols, 1, out_schema)
            return (out, jnp.asarray(False)) if hash_path else out
        leftover = None
        if hash_path:
            out_keys, results, num_groups, leftover = groupby_aggregate_hash(
                keys, agg_inputs, batch.num_rows, cap, rounds=hash_rounds)
        else:
            out_keys, results, num_groups = groupby_aggregate(
                keys, agg_inputs, batch.num_rows, cap, words)
        cols = list(out_keys)
        buf_fields = out_schema.fields[self._key_count:]
        for r, f in zip(results, buf_fields):
            if r[0] == "col":
                cols.append(r[1])
            else:
                data, valid = r[1]
                cols.append(Column(data.astype(f.data_type.jnp_dtype),
                                   valid, f.data_type))
        out = ColumnarBatch(cols, num_groups, out_schema)
        return (out, leftover) if hash_path else out

    def _evaluate(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Final projection buffers -> results."""
        out_schema = self.output_schema
        cols = list(batch.columns[: self._key_count])
        pos = self._key_count
        for i, (fn, _) in enumerate(self.aggregates):
            n_buf = len(fn.merge_ops())
            bufs = list(batch.columns[pos: pos + n_buf])
            input_types = self._input_types[i] if self._input_types else \
                [b.dtype for b in bufs]
            col = fn.evaluate(bufs, input_types)
            cols.append(sanitize(col, batch.num_rows))
            pos += n_buf
        return ColumnarBatch(cols, batch.num_rows, out_schema,
                             batch._host_rows)

    # -- drive -------------------------------------------------------------
    def internal_execute(self) -> Iterator[ColumnarBatch]:
        agg_time = self.metrics[AGG_TIME]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        aggregated: List[SpillableBatch] = []

        with agg_time.ns_timer():
            first_pass = self._merge_jitted if self.mode == "final" \
                else self._update_and_aggregate
            for batch in self.child.execute():
                in_batches.add(1)
                in_rows.add(batch.num_rows_host)
                spillable = SpillableBatch.from_batch(batch)
                try:
                    for out in with_retry(spillable,
                                          self._spill_wrap(first_pass),
                                          split_policy=split_in_half_by_rows):
                        from ..columnar.column import bucket_capacity
                        rows = out.num_rows_host
                        small_cap = bucket_capacity(max(rows, 1))
                        if small_cap < out.capacity:
                            shrunk = _shrink_batch(out, small_cap)
                            out = ColumnarBatch(shrunk.columns, rows,
                                                out.schema)
                        aggregated.append(SpillableBatch.from_batch(out))
                finally:
                    spillable.close()

            if not aggregated:
                if not self.group_exprs and self.mode != "partial":
                    # grand aggregate over empty input: one row (count=0 ...)
                    from .basic import InMemoryScanExec
                    from ..columnar.batch import empty_batch
                    empty = empty_batch(self._pre_schema
                                        if self.mode != "final"
                                        else self._buffer_schema)
                    merged = self._update_batch(empty) \
                        if self.mode != "final" else self._merge_batch(empty)
                    yield self._evaluate(merged)
                return

            if len(aggregated) == 1:
                # a single partial already has unique keys: no merge needed
                only = aggregated[0]
                merged = only.get_batch()
                only.release()
                only.close()
            else:
                merged = self._merge_all(aggregated)
            if self.mode == "partial":
                yield merged
            else:
                yield self._evaluate(merged)

    def _key_words(self, batch: ColumnarBatch) -> int:
        """String-lane width for exact key ordering (host sync, pre-jit)."""
        return string_words_for(batch.columns, range(self._key_count))

    @property
    def _hash_path_ok(self) -> bool:
        """Hash group-by handles everything except ordering aggs (min/max)
        over strings — those need sort lanes. Both update and merge passes
        see them as min/max over a string buffer, so checking the buffer
        schema covers every mode."""
        from ..types import BinaryType, StringType
        pos = self._key_count
        for fn, _ in self.aggregates:
            for op in fn.merge_ops():
                bt = self._buffer_schema.fields[pos].data_type
                if op in ("min", "max") and isinstance(
                        bt, (StringType, BinaryType)):
                    return False
                pos += 1
        return True

    def _update_and_aggregate(self, batch: ColumnarBatch) -> ColumnarBatch:
        pre = self._jit_pre(batch)
        if self._hash_path_ok:
            for rounds in (2, 6):
                out, leftover = self._jit_update_hash[rounds](pre)
                if not bool(leftover):
                    return out
            # unresolved hash collisions: exact sort fallback (reference
            # duality: hash primary, sort fallback)
        return self._jit_update(pre, self._key_words(pre))

    def _merge_jitted(self, batch: ColumnarBatch) -> ColumnarBatch:
        if self._hash_path_ok:
            for rounds in (2, 6):
                out, leftover = self._jit_merge_hash[rounds](batch)
                if not bool(leftover):
                    return out
        return self._jit_merge(batch, self._key_words(batch))

    def _spill_wrap(self, fn):
        def run(s: SpillableBatch):
            b = s.get_batch()
            try:
                return fn(b)
            finally:
                s.release()
        return run

    def _merge_all(self, aggregated: List[SpillableBatch]) -> ColumnarBatch:
        """Concat + re-aggregate; under OOM the retry framework splits the
        set of partial batches and re-merges the halves (always correct:
        merge ops are associative & commutative)."""
        extra_owned: List[SpillableBatch] = []

        def split_set(items: List[SpillableBatch]):
            if len(items) < 2:
                halves = split_in_half_by_rows(items[0])
                extra_owned.extend(halves)
                return [[h] for h in halves]
            half = len(items) // 2
            return [items[:half], items[half:]]

        def do(items: List[SpillableBatch]) -> ColumnarBatch:
            batches = [s.get_batch() for s in items]
            try:
                merged = concat_batches(batches, self._buffer_schema)
                return self._merge_jitted(merged)
            finally:
                for s in items:
                    s.release()

        try:
            outs = list(with_retry(aggregated, do, split_policy=split_set))
        finally:
            for s in aggregated + extra_owned:
                s.close()
        if len(outs) == 1:
            return outs[0]
        # split path produced several partials: re-merge them
        spill = [SpillableBatch.from_batch(b) for b in outs]
        return self._merge_all(spill)

    def node_description(self):
        aggs = ", ".join(f"{fn!r} AS {name}" for fn, name in self.aggregates)
        return (f"AggregateExec[{self.mode}, keys={self.group_exprs!r}, "
                f"aggs=[{aggs}]]")
