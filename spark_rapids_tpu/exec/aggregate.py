"""HashAggregateExec — reference GpuHashAggregateExec
(GpuAggregateExec.scala:1711) + GpuMergeAggregateIterator:711 rebuilt around
the sort-based segment-reduce kernel (ops/aggregate.py).

Flow (complete mode):
  1. per input batch: pre-project [group keys..., agg inputs...]
  2. update group-by -> batch of [keys..., buffer cols...] (first-pass agg)
  3. aggregated batches accumulate as SpillableBatch
  4. merge: concat + re-aggregate with merge ops (reference
     tryMergeAggregatedBatches:803; our kernel IS the sort fallback :909,
     so the two reference paths collapse into one here)
  5. evaluate buffers -> output projection

`partial` mode stops after 4 and emits keys+buffers (feeds a shuffle);
`final` consumes keys+buffers batches and runs 4-5. This mirrors Spark's
partial/final split so distributed aggregation reuses the same exec.
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn
from ..expr.aggexprs import AggregateFunction
from ..expr.core import Expression, output_name, resolve
from ..memory.retry import (
    TpuSplitAndRetryOOM, split_in_half_by_rows, with_retry,
)
from ..memory.spillable import SpillableBatch
from ..ops.aggregate import groupby_aggregate, groupby_aggregate_hash
from ..ops.basic import active_mask, sanitize
from ..ops.sort import string_words_for
from ..types import DataType, LongType, Schema, StructField
from ..obs.dispatch import instrument
from .base import (AGG_TIME, CONCAT_TIME, DEBUG, DISPATCH_METRICS,
                   NUM_INPUT_BATCHES, NUM_INPUT_ROWS, TpuExec)
from .basic import bind_projection, eval_projection
from .coalesce import concat_batches


@partial(instrument, label="aggregate.shrink_batch",
         static_argnums=(1,))
def _shrink_batch(batch: ColumnarBatch, cap: int) -> ColumnarBatch:
    """Move the active prefix into a smaller capacity bucket: aggregated
    partials carry few groups in huge input-sized buckets; merging at input
    size would sort mostly-padding (the dominant waste in a groupby)."""
    from ..ops.basic import slice_rows
    cols = [slice_rows(c, jnp.int32(0), batch.num_rows, cap)
            for c in batch.columns]
    return ColumnarBatch(cols, batch.num_rows, batch.schema)



def _result_column(data, valid, dtype) -> Column:
    """Aggregate result (data, valid) -> Column; decimal128 sums arrive
    as (hi, lo) limb tuples and build a Decimal128Column (or fold back
    to one limb when the buffer type fits 18 digits)."""
    import jax.numpy as jnp

    from ..columnar.column import Decimal128Column
    from ..types import DecimalType
    if isinstance(data, tuple):
        hi, lo = data
        if isinstance(dtype, DecimalType) and dtype.is_decimal128:
            return Decimal128Column.from_limbs(hi, lo, valid, dtype)
        from ..ops import decimal128 as D
        bound = 10 ** min(dtype.precision, 18)
        ok = D.fits_i64(hi, lo) & (lo < bound) & (lo > -bound)
        valid = valid & ok
        return Column(jnp.where(valid, lo, 0), valid, dtype)
    return Column(data.astype(dtype.jnp_dtype), valid, dtype)


class AggregateExec(TpuExec):
    def __init__(self, group_exprs: Sequence[Expression],
                 aggregates: Sequence[Tuple[AggregateFunction, str]],
                 child: TpuExec, mode: str = "complete",
                 input_types: Optional[List[List["DataType"]]] = None):
        """input_types: per-aggregate original INPUT types, passed by the
        planner to final-mode instances so result types (e.g. decimal sum
        precision) match the single-stage plan instead of being derived
        from the widened buffer types (ADVICE r3 #3)."""
        super().__init__(child)
        assert mode in ("complete", "partial", "final")
        self._final_input_types = input_types
        self.mode = mode
        self.group_exprs = list(group_exprs)
        self.aggregates = list(aggregates)
        in_schema = child.output_schema

        from ..config import (
            AGG_GROUP_SLOTS, AGG_ROUNDS, AGG_SPECULATIVE, FUSION_ENABLED,
            active_conf,
        )
        conf = active_conf()
        self._slots = max(8, min(64, conf.get(AGG_GROUP_SLOTS)))
        self._rounds = max(1, conf.get(AGG_ROUNDS))
        self._spec_enabled = conf.get(AGG_SPECULATIVE)

        self._fusion_enabled = conf.get(FUSION_ENABLED)
        self._fused_steps: list = []
        self._source: TpuExec = child

        if mode == "final":
            # input is keys+buffers produced by a partial instance; the
            # planner's input_types hint restores original result types
            self._key_count = len(group_exprs)
            self._input_types = input_types
            self._buffer_schema = in_schema
        else:
            # pre-projection: keys then the union of agg inputs
            self._pre_exprs = list(self.group_exprs)
            self._input_slots: List[List[int]] = []
            for fn, _ in self.aggregates:
                slots = []
                for e in fn.inputs:
                    slot = len(self._pre_exprs)
                    self._pre_exprs.append(e.alias(f"_aggin{slot}"))
                    slots.append(slot)
                self._input_slots.append(slots)
            self._pre_bound = bind_projection(self._pre_exprs, in_schema)
            from .basic import projection_schema
            self._pre_schema = projection_schema(self._pre_exprs, in_schema)
            self._key_count = len(group_exprs)
            self._input_types = [
                [self._pre_schema.fields[s].data_type for s in slots]
                for slots in self._input_slots]
            self._buffer_schema = self._make_buffer_schema()

        # whole-stage fusion: inline upstream filter/project chains into
        # this operator's program (one XLA program per source batch; the
        # reference's analog is whole-stage codegen — XLA is the codegen).
        # Only for the masked tier: the string tiers consume child batches.
        if self._fusion_enabled and mode != "final" and self._masked_ok:
            steps, node = [], child
            while hasattr(node, "fused_step"):
                steps.append(node.fused_step())
                node = node.child
            self._fused_steps = list(reversed(steps))
            self._source = node

        # fused Pallas tier (ISSUE 1): compile the absorbed operator
        # chain for the one-kernel scan-filter-project-partial-aggregate
        # when every expression is in the whitelisted elementwise subset;
        # the measured tier selector decides per shape at trace time
        self._pallas_agg_spec = None
        if mode != "final" and self._masked_ok and self.group_exprs:
            try:
                from ..ops.pallas_fused import compile_scan_agg_spec
                agg_op_slots = []
                for i, (fn, _) in enumerate(self.aggregates):
                    for (op, slot) in fn.update_ops():
                        agg_op_slots.append(
                            (op, self._input_slots[i][slot]
                             if slot is not None else None))
                self._pallas_agg_spec = compile_scan_agg_spec(
                    self._fused_steps, self._pre_bound, self._pre_schema,
                    self._key_count, agg_op_slots,
                    self._source.output_schema)
            except Exception:  # noqa: BLE001 — tier is best-effort
                self._pallas_agg_spec = None

        # round 5: when the child contract (output_grouped_by) already
        # groups rows by this aggregate's keys — e.g. the inner join's
        # key-grouped emission — the exact tier skips its batch sort
        self._pre_grouped = mode != "final" and self._input_pre_grouped()
        self._initial_state_cache = None

        # program sites, built LAST (ISSUE 14): the plan fingerprint
        # the site cache keys on must see the final semantic fields
        # (fused steps, pallas spec, pre-grouped contract) — a site
        # built earlier would fingerprint a half-constructed node.
        # Compiled-kernel jit caches key on capacity bucket + string
        # words; the site cache keys whole instances across collects.
        self._jit_update = self._site(self._update_batch,
                                      label="AggregateExec.update",
                                      static_argnums=(1,))
        self._jit_merge = self._site(self._merge_batch,
                                     label="AggregateExec.merge",
                                     static_argnums=(1,))
        # hash-path tiers: cheap 2-round first, 6-round escalation for
        # mid-cardinality, exact sort as the last resort
        self._jit_update_hash = {
            r: self._site(partial(self._update_batch, hash_path=True,
                                  hash_rounds=r),
                          label="AggregateExec.update_hash", key_salt=r)
            for r in (2, 6)}
        self._jit_merge_hash = {
            r: self._site(partial(self._merge_batch, hash_path=True,
                                  hash_rounds=r),
                          label="AggregateExec.merge_hash", key_salt=r)
            for r in (2, 6)}
        # sync-free exact merge: masked buckets + in-program sort fallback
        self._jit_merge_auto = self._site(
            partial(self._merge_batch, auto_path=True),
            label="AggregateExec.merge_auto")
        self._jit_pre = self._site(self._pre_project,
                                   label="AggregateExec.pre_project")
        self._jit_concat_merge = self._site(
            self._concat_merge_pair,
            label="AggregateExec.concat_merge", static_argnums=(2,))
        # streaming speculative kernel: fused steps + masked-bucket update
        # + fold into the O(1) device state — ONE program per source batch
        self._jit_step_spec = self._site(
            self._streaming_step,
            label="AggregateExec.streaming_step")
        self._jit_step_exact = self._site(
            self._fused_update_exact,
            label="AggregateExec.fused_update_exact")
        self._jit_evaluate = self._site(self._evaluate,
                                        label="AggregateExec.evaluate")

    def _fingerprint_extras(self):
        # semantic_key throughout, NOT repr: repr is display-only and
        # omits non-child parameters (a percentile's percentage, a
        # first()'s ignore_nulls) — a lossy key hands one aggregate
        # another's compiled programs (caught live)
        from .stage_compiler import schema_sig
        exprs = list(self.group_exprs) + [
            e for fn, _ in self.aggregates for e in fn.inputs]
        for s in self._fused_steps:
            exprs.extend(s[1] if s[0] == "project" else [s[1]])
        if not all(e.deterministic for e in exprs):
            return None  # see ProjectExec._fingerprint_extras

        def step_key(s):
            if s[0] == "filter":
                return ("filter", s[1].semantic_key())
            return ("project",
                    tuple(b.semantic_key() for b in s[1]),
                    schema_sig(s[2]))

        return (self.mode,
                tuple(e.semantic_key() for e in self.group_exprs),
                tuple((fn.semantic_key(), name)
                      for fn, name in self.aggregates),
                repr(self._final_input_types),
                self._slots, self._rounds, self._spec_enabled,
                self._fusion_enabled,
                tuple(step_key(s) for s in self._fused_steps),
                self._pallas_agg_spec is not None, self._pre_grouped)

    def _input_pre_grouped(self) -> bool:
        from ..expr.core import UnresolvedAttribute
        hint = self.children[0].output_grouped_by
        if not hint or not self.group_exprs:
            return False
        names = set()
        for e in self.group_exprs:
            if not isinstance(e, UnresolvedAttribute):
                return False
            names.add(e.name)
        all_names = set().union(*hint)
        # every key must belong to a grouping class, and every class must
        # be represented (otherwise joint-tuple contiguity doesn't hold)
        return names <= all_names and all(cls & names for cls in hint)

    # -- schemas -----------------------------------------------------------
    def _make_buffer_schema(self) -> Schema:
        fields = list(self._pre_schema.fields[: self._key_count])
        for i, (fn, name) in enumerate(self.aggregates):
            for j, bt in enumerate(fn.buffer_types(self._input_types[i])):
                fields.append(StructField(f"{name}#buf{j}", bt, True))
        return Schema(tuple(fields))

    @property
    def output_schema(self) -> Schema:
        if self.mode == "partial":
            return self._buffer_schema
        key_fields = list(self._buffer_schema.fields[: self._key_count])
        agg_fields = []
        bufs = self._buffer_schema.fields[self._key_count:]
        # result types: derive from buffer types for final mode
        pos = 0
        for i, (fn, name) in enumerate(self.aggregates):
            n_buf = len(fn.merge_ops())
            if self._input_types is not None:
                rt = fn.result_type(self._input_types[i])
            else:  # final mode: derive from buffer types explicitly
                rt = fn.result_type_from_buffer(
                    [f.data_type for f in bufs[pos:pos + n_buf]])
            agg_fields.append(StructField(name, rt))
            pos += n_buf
        return Schema(tuple(key_fields + agg_fields))

    def additional_metrics(self):
        return (AGG_TIME, CONCAT_TIME, (NUM_INPUT_ROWS, DEBUG),
                (NUM_INPUT_BATCHES, DEBUG)) + DISPATCH_METRICS

    # -- kernels -----------------------------------------------------------
    def _pre_project(self, batch: ColumnarBatch) -> ColumnarBatch:
        return eval_projection(self._pre_bound, batch, self._pre_schema)

    def _update_inputs(self, batch: ColumnarBatch):
        keys = list(batch.columns[: self._key_count])
        agg_inputs = []
        for i, (fn, _) in enumerate(self.aggregates):
            for (op, slot) in fn.update_ops():
                col = batch.columns[self._input_slots[i][slot]] \
                    if slot is not None else None
                agg_inputs.append((op, col))
        return keys, agg_inputs

    def _merge_inputs(self, batch: ColumnarBatch):
        keys = list(batch.columns[: self._key_count])
        agg_inputs = []
        pos = self._key_count
        for fn, _ in self.aggregates:
            for op in fn.merge_ops():
                agg_inputs.append((op, batch.columns[pos]))
                pos += 1
        return keys, agg_inputs

    def _update_batch(self, batch: ColumnarBatch, words: int = 4,
                      hash_path: bool = False, hash_rounds: int = 2,
                      auto_path: bool = False, row_mask=None):
        """First-pass aggregation of one pre-projected batch."""
        keys, agg_inputs = self._update_inputs(batch)
        return self._run_groupby(keys, agg_inputs, batch,
                                 self._buffer_schema, words, hash_path,
                                 hash_rounds, auto_path, row_mask,
                                 is_update=True)

    def _merge_batch(self, batch: ColumnarBatch, words: int = 4,
                     hash_path: bool = False, hash_rounds: int = 2,
                     auto_path: bool = False, row_mask=None):
        """Re-aggregate a keys+buffers batch with merge ops."""
        keys, agg_inputs = self._merge_inputs(batch)
        return self._run_groupby(keys, agg_inputs, batch,
                                 self._buffer_schema, words, hash_path,
                                 hash_rounds, auto_path, row_mask)

    # -- fused + speculative streaming kernels -----------------------------
    def _apply_fused(self, batch: ColumnarBatch):
        """Traced: run the inlined filter/project chain. Filters become a
        row MASK (no compaction gather — gathers are slow on TPU; masked
        reductions ignore dead rows for free)."""
        mask = None
        cur = batch
        for step in self._fused_steps:
            if step[0] == "filter":
                pred = step[1].columnar_eval(cur)
                m = pred.data & pred.validity
                mask = m if mask is None else (mask & m)
            else:
                _, bound, schema = step
                cur = eval_projection(bound, cur, schema)
        return cur, mask

    def _fused_update_exact(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Exact tier, one program: fused steps -> pre-project -> masked
        bucket group-by with in-program lax.cond sort fallback."""
        assert self.mode != "final", "final mode merges via _merge_jitted"
        cur, mask = self._apply_fused(batch)
        pre = eval_projection(self._pre_bound, cur, self._pre_schema)
        return self._update_batch(pre, auto_path=True, row_mask=mask)

    def _small_cap(self) -> int:
        from ..columnar.column import bucket_capacity
        return bucket_capacity(self._slots * self._rounds)

    def _build_small_batch(self, out_keys, results, num_groups
                           ) -> ColumnarBatch:
        cols = list(out_keys)
        buf_fields = self._buffer_schema.fields[self._key_count:]
        for r, f in zip(results, buf_fields):
            data, valid = r[1]
            cols.append(_result_column(data, valid, f.data_type))
        return ColumnarBatch(cols, num_groups, self._buffer_schema)

    def _streaming_step(self, batch: ColumnarBatch, state: ColumnarBatch,
                        flag):
        """Speculative tier, ONE program per source batch: fused steps ->
        masked-bucket update into a SMALL partial -> fold into the O(1)
        running state. Overflow/collision leftovers only raise the device
        flag; the plan re-runs exactly if it ever trips (speculation.py)."""
        from ..ops.basic import concat_columns
        from ..ops.maskedagg import masked_groupby, masked_reduce
        out_cap = self._small_cap()

        use_pallas = False
        if self.mode != "final" and self._pallas_agg_spec is not None:
            from ..ops.pallas_tier import fused_tier_enabled
            use_pallas = fused_tier_enabled("scan_agg", (batch.capacity,))

        if use_pallas:
            # ONE Pallas kernel: scan tiles -> filter -> project ->
            # masked-bucket partials, no intermediate column in HBM
            # (ops/pallas_fused.py); dirty buckets raise the same
            # speculation flag as the XLA masked tier
            from ..ops.pallas_fused import fused_scan_agg_update
            from ..ops.pallas_kernels import on_tpu
            out_keys, results, num_groups, leftover = \
                fused_scan_agg_update(
                    self._pallas_agg_spec, batch,
                    min(32, self._slots), out_cap,
                    interpret=not on_tpu())
            flag = flag | leftover
            part = self._build_small_batch(out_keys, results, num_groups)
        elif self.mode == "final":
            cur, mask = batch, None
            keys, agg_inputs = self._merge_inputs(batch)
        else:
            cur, mask = self._apply_fused(batch)
            pre = eval_projection(self._pre_bound, cur, self._pre_schema)
            keys, agg_inputs = self._update_inputs(pre)
            cur = pre

        if use_pallas:
            pass
        elif not keys:
            results = [("raw", r) for r in masked_reduce(
                agg_inputs, cur.num_rows, mask, out_cap)]
            part = self._build_small_batch([], results, jnp.int32(1))
        else:
            out_keys, results, num_groups, leftover = masked_groupby(
                keys, agg_inputs, cur.num_rows, cur.capacity, mask,
                self._slots, self._rounds)
            flag = flag | leftover
            part = self._build_small_batch(out_keys, results, num_groups)

        # fold: concat state + part, re-aggregate with merge ops
        cat_cap = 2 * out_cap
        cols = [concat_columns(a, b, state.num_rows, part.num_rows, cat_cap)
                for a, b in zip(state.columns, part.columns)]
        both = ColumnarBatch(cols, state.num_rows + part.num_rows,
                             self._buffer_schema)
        mkeys, minputs = self._merge_inputs(both)
        if not mkeys:
            mres = [("raw", r) for r in masked_reduce(
                minputs, both.num_rows, None, out_cap)]
            new_state = self._build_small_batch([], mres, jnp.int32(1))
        else:
            mk, mres, mgroups, mleft = masked_groupby(
                mkeys, minputs, both.num_rows, cat_cap, None,
                self._slots, self._rounds)
            flag = flag | mleft
            new_state = self._build_small_batch(mk, mres, mgroups)
        # evaluate the (tiny) state inside the SAME program: the final
        # result is then a step output and no separate evaluate program
        # has to launch — per-program launch latency is milliseconds on
        # the tunnel-attached chip, comparable to a whole 16M-row sweep
        ev = None if self.mode == "partial" else self._evaluate(new_state)
        return new_state, flag, ev

    def _initial_state(self) -> ColumnarBatch:
        """Empty small state (built once; reused across executions)."""
        if self._initial_state_cache is None:
            from ..columnar.batch import empty_batch
            self._initial_state_cache = (
                empty_batch(self._buffer_schema, capacity=self._small_cap()),
                jnp.asarray(False))
        return self._initial_state_cache

    def _concat_merge_pair(self, a: ColumnarBatch, b: ColumnarBatch,
                           cap: int) -> ColumnarBatch:
        """Device-only merge of two keys+buffers partials: concat into one
        `cap`-capacity batch, then re-aggregate with merge ops. Output
        groups <= a_groups + b_groups <= cap always, so this is exact with
        no host involvement."""
        from ..ops.basic import concat_columns
        cols = [concat_columns(ca, cb, a.num_rows, b.num_rows, cap)
                for ca, cb in zip(a.columns, b.columns)]
        both = ColumnarBatch(cols, a.num_rows + b.num_rows,
                             self._buffer_schema)
        return self._merge_batch(both, auto_path=True)

    def _run_groupby(self, keys, agg_inputs, batch, out_schema, words: int,
                     hash_path: bool = False, hash_rounds: int = 2,
                     auto_path: bool = False, row_mask=None,
                     is_update: bool = False):
        from ..ops.maskedagg import masked_groupby_exact, masked_reduce
        cap = batch.capacity
        if not keys:
            if any(op.startswith(("collect", "psketch"))
                   for op, _ in agg_inputs):
                # grand collect_list/set: one-row array outputs
                from ..ops.aggregate import collect_all
                cols = []
                fields = out_schema.fields
                plain = [(op, c) for op, c in agg_inputs
                         if not op.startswith(("collect", "psketch"))]
                plain_res = iter(masked_reduce(
                    plain, batch.num_rows, row_mask, cap)) if plain else \
                    iter(())
                for (op, c), f in zip(agg_inputs, fields):
                    if op.startswith(("collect", "psketch")):
                        cols.append(collect_all(op, c, batch.num_rows, cap))
                    else:
                        data, valid = next(plain_res)
                        cols.append(_result_column(data, valid,
                                                   f.data_type))
                out = ColumnarBatch(cols, 1, out_schema)
                return (out, jnp.asarray(False)) if hash_path else out
            # a count(*)-only aggregate has no input columns at all; give
            # the one-row output a real capacity bucket. Scatter-free
            # masked reductions (scatters are the slowest TPU op family).
            out_cap = 128
            results = masked_reduce(agg_inputs, batch.num_rows,
                                    row_mask, out_cap)
            cols = []
            fields = out_schema.fields
            for (data, valid), f in zip(results, fields):
                cols.append(_result_column(data, valid, f.data_type))
            out = ColumnarBatch(cols, 1, out_schema)
            return (out, jnp.asarray(False)) if hash_path else out
        leftover = None
        if auto_path:
            out_keys, results, num_groups = masked_groupby_exact(
                keys, agg_inputs, batch.num_rows, cap, row_mask,
                string_words=words, group_slots=self._slots,
                rounds=self._rounds)
        elif hash_path:
            out_keys, results, num_groups, leftover = groupby_aggregate_hash(
                keys, agg_inputs, batch.num_rows, cap, rounds=hash_rounds)
        else:
            # pre_grouped only holds for SOURCE batches (the child's
            # grouping contract); merge inputs are concatenated partials
            out_keys, results, num_groups = groupby_aggregate(
                keys, agg_inputs, batch.num_rows, cap, words,
                pre_grouped=self._pre_grouped and is_update)
        cols = list(out_keys)
        buf_fields = out_schema.fields[self._key_count:]
        for r, f in zip(results, buf_fields):
            if r[0] == "col":
                cols.append(r[1])
            else:
                data, valid = r[1]
                cols.append(_result_column(data, valid, f.data_type))
        out = ColumnarBatch(cols, num_groups, out_schema)
        return (out, leftover) if hash_path else out

    def _evaluate(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Final projection buffers -> results."""
        out_schema = self.output_schema
        cols = list(batch.columns[: self._key_count])
        pos = self._key_count
        for i, (fn, _) in enumerate(self.aggregates):
            n_buf = len(fn.merge_ops())
            bufs = list(batch.columns[pos: pos + n_buf])
            input_types = self._input_types[i] if self._input_types else \
                [b.dtype for b in bufs]
            col = fn.evaluate(bufs, input_types)
            cols.append(sanitize(col, batch.num_rows))
            pos += n_buf
        return ColumnarBatch(cols, batch.num_rows, out_schema,
                             batch._host_rows)

    # -- drive -------------------------------------------------------------

    #: merge this many partials device-side before one amortized host sync
    #: shrinks the running result into a tight capacity bucket
    MERGE_FAN_IN = 8

    #: exact-tier partials at or above this capacity are shrunk eagerly
    #: (one host sync each) instead of holding full-size buckets in HBM
    SHRINK_THRESHOLD_CAP = 1 << 16

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        from .speculation import speculation_allowed
        if (self._masked_ok and self._spec_enabled
                and speculation_allowed()):
            yield from self._execute_speculative()
            return
        yield from self._execute_exact()

    def _execute_speculative(self) -> Iterator[ColumnarBatch]:
        """Streaming speculative drive: ONE program per source batch folds
        into an O(1)-size device state; the overflow flag is recorded with
        the active speculation scope and never read here."""
        from .speculation import current_scope
        agg_time = self.metrics[AGG_TIME]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        state, flag = self._initial_state()
        evaluated = None
        saw_input = False
        with agg_time.ns_timer():
            for batch in self._source.execute():
                in_batches.add(1)
                if batch._host_rows is not None:
                    in_rows.add(batch._host_rows)
                else:
                    in_rows.add_device(batch.num_rows)
                saw_input = True
                spillable = SpillableBatch.from_batch(batch)
                box = [state, flag, None]
                try:
                    def run(s: SpillableBatch):
                        b = s.get_batch()
                        try:
                            return self._jit_step_spec(b, box[0], box[1])
                        finally:
                            s.release()
                    for out in with_retry(spillable, run,
                                          split_policy=split_in_half_by_rows):
                        box[0], box[1], box[2] = out
                finally:
                    spillable.close()
                state, flag, evaluated = box
        if not saw_input:
            if self.group_exprs or self.mode == "partial":
                return  # no output rows (matches the exact path)
            # grand aggregate over empty input still emits one row
            from ..columnar.batch import empty_batch
            src_schema = (self._buffer_schema if self.mode == "final"
                          else self._source.output_schema)
            state, flag, evaluated = self._jit_step_spec(
                empty_batch(src_schema), state, flag)
        scope = current_scope()
        if scope is not None:
            scope.record(flag)
        if self.mode == "partial":
            yield state
        else:
            # the last step already evaluated its state in-program
            yield evaluated if evaluated is not None \
                else self._jit_evaluate(state)

    def _absorb_partial(self, aggregated: List[SpillableBatch],
                        out: ColumnarBatch) -> None:
        """Partial-accumulation discipline shared by the per-op exact
        drive and the fused stage's exact flavor (ISSUE 14): eager
        shrink of big partials past SHRINK_THRESHOLD_CAP, then
        MERGE_FAN_IN windowing so live partials stay BOUNDED — a
        forced-spill budget survives an arbitrarily long stream."""
        if (out.capacity >= self.SHRINK_THRESHOLD_CAP
                and aggregated):
            # the FIRST partial is held unshrunken: for the
            # (common) single-batch pipeline the shrink's
            # d2h sync (~100 ms on the tunnel) buys nothing
            # — one full-size partial costs what the input
            # batch already cost, and it is spillable
            # big-batch partials keep the input capacity
            # (groups are usually few): pay ONE host sync
            # to shrink rather than hold MERGE_FAN_IN
            # full-size partials in HBM
            from ..columnar.column import bucket_capacity
            rows = out.num_rows_host
            small = bucket_capacity(max(rows, 1))
            if small < out.capacity:
                shrunk = _shrink_batch(out, small)
                out = ColumnarBatch(shrunk.columns, rows,
                                    out.schema)
        aggregated.append(SpillableBatch.from_batch(out))
        if len(aggregated) >= self.MERGE_FAN_IN:
            # bound live partials: merge the window device-side,
            # then ONE host sync shrinks the result into a tight
            # bucket (amortized over MERGE_FAN_IN batches).
            merged = self._merge_all(list(aggregated))
            from ..columnar.column import bucket_capacity
            rows = merged.num_rows_host
            small_cap = bucket_capacity(max(rows, 1))
            if small_cap < merged.capacity:
                shrunk = _shrink_batch(merged, small_cap)
                merged = ColumnarBatch(shrunk.columns, rows,
                                       merged.schema)
            aggregated[:] = [SpillableBatch.from_batch(merged)]

    def _execute_exact(self) -> Iterator[ColumnarBatch]:
        agg_time = self.metrics[AGG_TIME]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        aggregated: List[SpillableBatch] = []

        with agg_time.ns_timer():
            first_pass = self._merge_jitted if self.mode == "final" \
                else self._update_and_aggregate
            for batch in self._source.execute():
                in_batches.add(1)
                if batch._host_rows is not None:
                    in_rows.add(batch._host_rows)
                else:
                    in_rows.add_device(batch.num_rows)
                spillable = SpillableBatch.from_batch(batch)
                try:
                    for out in with_retry(spillable,
                                          self._spill_wrap(first_pass),
                                          split_policy=split_in_half_by_rows):
                        self._absorb_partial(aggregated, out)
                finally:
                    spillable.close()

            if not aggregated:
                if not self.group_exprs and self.mode != "partial":
                    # grand aggregate over empty input: one row (count=0 ...)
                    from .basic import InMemoryScanExec
                    from ..columnar.batch import empty_batch
                    empty = empty_batch(self._pre_schema
                                        if self.mode != "final"
                                        else self._buffer_schema)
                    merged = self._update_batch(empty) \
                        if self.mode != "final" else self._merge_batch(empty)
                    yield self._jit_evaluate(merged)
                return

            if len(aggregated) == 1:
                # a single partial already has unique keys: no merge needed
                only = aggregated[0]
                merged = only.get_batch()
                only.release()
                only.close()
            else:
                merged = self._merge_all(aggregated)
            if self.mode == "partial":
                yield merged
            else:
                yield self._jit_evaluate(merged)

    def _key_words(self, batch: ColumnarBatch) -> int:
        """String-lane width for exact key ordering (host sync, pre-jit)."""
        return string_words_for(batch.columns, range(self._key_count))

    @property
    def _hash_path_ok(self) -> bool:
        """Hash group-by handles everything except ordering aggs (min/max)
        over strings — those need sort lanes. Both update and merge passes
        see them as min/max over a string buffer, so checking the buffer
        schema covers every mode."""
        from ..types import ArrayType, BinaryType, StringType
        pos = self._key_count
        for fn, _ in self.aggregates:
            for op in fn.merge_ops():
                bt = self._buffer_schema.fields[pos].data_type
                if op in ("min", "max") and isinstance(
                        bt, (StringType, BinaryType)):
                    return False
                if isinstance(bt, ArrayType):  # collect_* need sort order
                    return False
                pos += 1
        return True

    @property
    def _masked_ok(self) -> bool:
        """True when the masked-bucket kernels apply: every key and buffer
        column is fixed-width (strings have no static order lanes for the
        in-program exact fallback and no masked min/max encoding)."""
        from ..types import (ArrayType, BinaryType, DecimalType, StringType,
                             StructType)
        return not any(
            isinstance(f.data_type,
                       (StringType, BinaryType, StructType, ArrayType))
            or (isinstance(f.data_type, DecimalType)
                and f.data_type.is_decimal128)
            for f in self._buffer_schema.fields)

    @property
    def _sync_free(self) -> bool:
        return self._masked_ok

    def _update_and_aggregate(self, batch: ColumnarBatch) -> ColumnarBatch:
        if self._masked_ok:
            # one program: fused steps + masked buckets + lax.cond exact
            # sort fallback; the host never reads any flag (no round trip)
            return self._jit_step_exact(batch)
        pre = self._jit_pre(batch)
        if self._hash_path_ok:
            for rounds in (2, 6):
                out, leftover = self._jit_update_hash[rounds](pre)
                if not bool(leftover):
                    return out
            # unresolved hash collisions: exact sort fallback (reference
            # duality: hash primary, sort fallback)
        return self._jit_update(pre, self._key_words(pre))

    def _merge_jitted(self, batch: ColumnarBatch) -> ColumnarBatch:
        if self._masked_ok:
            return self._jit_merge_auto(batch)
        if self._hash_path_ok:
            for rounds in (2, 6):
                out, leftover = self._jit_merge_hash[rounds](batch)
                if not bool(leftover):
                    return out
        return self._jit_merge(batch, self._key_words(batch))

    def _spill_wrap(self, fn):
        def run(s: SpillableBatch):
            b = s.get_batch()
            try:
                return fn(b)
            finally:
                s.release()
        return run

    def _merge_all(self, aggregated: List[SpillableBatch]) -> ColumnarBatch:
        """Concat + re-aggregate; under OOM the retry framework splits the
        set of partial batches and re-merges the halves (always correct:
        merge ops are associative & commutative)."""
        extra_owned: List[SpillableBatch] = []

        def split_set(items: List[SpillableBatch]):
            if len(items) < 2:
                halves = split_in_half_by_rows(items[0])
                extra_owned.extend(halves)
                return [[h] for h in halves]
            half = len(items) // 2
            return [items[:half], items[half:]]

        def do(items: List[SpillableBatch]) -> ColumnarBatch:
            batches = [s.get_batch() for s in items]
            try:
                if self._sync_free:
                    return self._tree_merge_device(batches)
                merged = concat_batches(batches, self._buffer_schema)
                return self._merge_jitted(merged)
            finally:
                for s in items:
                    s.release()

        try:
            outs = list(with_retry(aggregated, do, split_policy=split_set))
        finally:
            for s in aggregated + extra_owned:
                s.close()
        if len(outs) == 1:
            return outs[0]
        # split path produced several partials: re-merge them
        spill = [SpillableBatch.from_batch(b) for b in outs]
        return self._merge_all(spill)

    def _tree_merge_device(self, batches: List[ColumnarBatch]
                           ) -> ColumnarBatch:
        """Pairwise device-only merge: every level concats pairs into the
        capacity bucket of the pair and re-aggregates — no host syncs, no
        row-count reads. Peak capacity is the bucket of the total, same as
        the concat-all path, but each level shrinks live groups."""
        from ..columnar.column import bucket_capacity
        level = list(batches)
        while len(level) > 1:
            nxt: List[ColumnarBatch] = []
            for i in range(0, len(level) - 1, 2):
                a, b = level[i], level[i + 1]
                cap = bucket_capacity(a.capacity + b.capacity)
                nxt.append(self._jit_concat_merge(a, b, cap))
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
        return level[0]

    def node_description(self):
        aggs = ", ".join(f"{fn!r} AS {name}" for fn, name in self.aggregates)
        return (f"AggregateExec[{self.mode}, keys={self.group_exprs!r}, "
                f"aggs=[{aggs}]]")
