"""GenerateExec — explode/posexplode over array columns (reference
GpuGenerateExec.scala:829: GpuExplode/GpuPosExplode generators with
outer/position variants).

TPU shape strategy: the output capacity is the array child's static
capacity bucket (every element becomes at most one row) plus the input
capacity for the outer variant — so the whole generate is ONE compiled
program per batch shape with no host sync at all."""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import (ArrayColumn, Column, MapColumn,
                               bucket_capacity)
from ..expr.core import Expression, resolve
from ..ops.basic import active_mask, compaction_order, gather_column
from ..types import ArrayType, IntegerType, Schema, StructField
from ..obs.dispatch import instrument
from .base import (DEBUG, DISPATCH_METRICS, NUM_INPUT_BATCHES, OP_TIME,
                   TpuExec)


class GenerateExec(TpuExec):
    def __init__(self, generator: Expression, child: TpuExec,
                 outer: bool = False, position: bool = False,
                 elem_name: str = "col", pos_name: str = "pos"):
        super().__init__(child)
        self.generator = generator
        self.outer = outer
        self.position = position
        self.elem_name = elem_name
        self.pos_name = pos_name
        self._bound = resolve(generator, child.output_schema)
        arr_t = self._bound.data_type
        from ..types import MapType
        self._is_map = isinstance(arr_t, MapType)
        if self._is_map:
            # explode(map) emits (key, value) pairs (reference
            # GpuGenerateExec.scala:829 map explode)
            self._key_type = arr_t.key_type
            self._elem_type = arr_t.value_type
        else:
            assert isinstance(arr_t, ArrayType), \
                f"explode needs an ARRAY or MAP input, got {arr_t}"
            self._elem_type = arr_t.element_type
        self._jit = instrument(self._kernel,
                               label="GenerateExec.explode", owner=self,
                               static_argnums=(1,))
        self._jit_measure = instrument(self._measure_kernel,
                                       label="GenerateExec.measure",
                                       owner=self)

    @property
    def output_schema(self) -> Schema:
        fields = list(self.child.output_schema.fields)
        if self.position:
            fields.append(StructField(self.pos_name, IntegerType(),
                                      self.outer))
        if self._is_map:
            fields.append(StructField("key", self._key_type, self.outer))
            fields.append(StructField("value", self._elem_type, True))
        else:
            fields.append(StructField(self.elem_name, self._elem_type,
                                      True))
        return Schema(tuple(fields))

    def additional_metrics(self):
        return ((NUM_INPUT_BATCHES, DEBUG),) + DISPATCH_METRICS

    def _measure_kernel(self, batch: ColumnarBatch):
        """Exact output payload need per variable-size payload column
        (explode DUPLICATES each row once per array element — the input's
        static byte bucket overflows silently otherwise, same hazard the
        joins measure away). One host sync per batch."""
        from ..columnar.column import StringColumn
        from ..ops.collection import array_lengths
        from ..ops.strings import string_lengths
        arr = self._bound.columnar_eval(batch)
        lens = array_lengths(arr).astype(jnp.int64)
        act = active_mask(batch.num_rows, batch.capacity)
        copies = jnp.where(act & arr.validity, lens, 0)
        if self.outer:
            empty = act & ((lens == 0) | ~arr.validity)
            copies = copies + jnp.where(empty, 1, 0)
        needs = []
        for c in batch.columns:
            if isinstance(c, StringColumn):
                sl = jnp.where(act, string_lengths(c), 0).astype(jnp.int64)
                needs.append(jnp.sum(copies * sl))
            elif isinstance(c, ArrayColumn):
                al = jnp.where(act, array_lengths(c), 0).astype(jnp.int64)
                needs.append(jnp.sum(copies * al))
                if isinstance(c.child, StringColumn):
                    # per-row child BYTE span for nested sizing
                    row_bytes = (c.child.offsets[c.offsets[1:]]
                                 - c.child.offsets[c.offsets[:-1]]
                                 ).astype(jnp.int64)
                    needs.append(jnp.sum(
                        copies * jnp.where(act, row_bytes, 0)))
            elif isinstance(c, MapColumn):
                el = jnp.where(act, c.offsets[1:] - c.offsets[:-1],
                               0).astype(jnp.int64)
                needs.append(jnp.sum(copies * el))
                for side in (c.keys, c.values):
                    if isinstance(side, StringColumn):
                        row_bytes = (side.offsets[c.offsets[1:]]
                                     - side.offsets[c.offsets[:-1]]
                                     ).astype(jnp.int64)
                        needs.append(jnp.sum(
                            copies * jnp.where(act, row_bytes, 0)))
        return tuple(needs)

    def _payload_caps(self, batch: ColumnarBatch) -> tuple:
        from ..columnar.column import StringColumn
        if not any(isinstance(c, (StringColumn, ArrayColumn, MapColumn))
                   for c in batch.columns):
            return (None,) * len(batch.columns)
        needs = iter(int(n) for n in jax.device_get(
            self._jit_measure(batch)))
        caps = []
        for c in batch.columns:
            if isinstance(c, StringColumn):
                caps.append(bucket_capacity(max(next(needs), 8)))
            elif isinstance(c, ArrayColumn):
                elems = bucket_capacity(max(next(needs), 8))
                if isinstance(c.child, StringColumn):
                    caps.append((elems,
                                 bucket_capacity(max(next(needs), 8))))
                else:
                    caps.append(elems)
            elif isinstance(c, MapColumn):
                elems = bucket_capacity(max(next(needs), 8))
                kb = bucket_capacity(max(next(needs), 8)) \
                    if isinstance(c.keys, StringColumn) else None
                vb = bucket_capacity(max(next(needs), 8)) \
                    if isinstance(c.values, StringColumn) else None
                caps.append((elems, kb, vb))
            else:
                caps.append(None)
        return tuple(caps)

    def _kernel(self, batch: ColumnarBatch, payload_caps: tuple = ()
                ) -> ColumnarBatch:
        arr = self._bound.columnar_eval(batch)
        from ..columnar.column import MapColumn
        if isinstance(arr, MapColumn):
            from ..ops.maps import map_keys
            map_col, arr = arr, map_keys(arr)  # offsets/validity vehicle
        else:
            map_col = None
            assert isinstance(arr, ArrayColumn)
        cap = batch.capacity
        child_cap = arr.child_capacity
        lens = arr.offsets[1:] - arr.offsets[:-1]
        act_rows = active_mask(batch.num_rows, cap)

        # elements to emit: inside the byte span of an ACTIVE, NON-NULL
        # row (computed arrays — e.g. CreateArray — carry element slots
        # for inactive/null rows too; compact those away)
        e_all = jnp.arange(child_cap, dtype=jnp.int32)
        row_all = jnp.clip(
            jnp.searchsorted(arr.offsets, e_all, side="right")
            .astype(jnp.int32) - 1, 0, cap - 1)
        keep = (e_all < arr.offsets[-1]) & act_rows[row_all] \
            & arr.validity[row_all]
        perm, total = compaction_order(keep, jnp.int32(child_cap))

        out_cap = bucket_capacity(child_cap + (cap if self.outer else 0))
        slots = jnp.arange(out_cap, dtype=jnp.int32)
        e = perm[jnp.clip(slots, 0, child_cap - 1)]
        e = jnp.clip(e, 0, child_cap - 1)
        src_row_of_elem = row_all[e]
        intra = e - arr.offsets[src_row_of_elem]
        is_elem = slots < total

        if self.outer:
            empty = act_rows & ((lens == 0) | ~arr.validity)
            empty_perm, n_empty = compaction_order(empty, batch.num_rows)
            k = jnp.clip(slots - total, 0, cap - 1)
            outer_row = jnp.where((slots >= total)
                                  & (slots < total + n_empty),
                                  empty_perm[k], -1)
            n_out = total + n_empty
        else:
            outer_row = jnp.full((out_cap,), -1, jnp.int32)
            n_out = total

        src_row = jnp.where(is_elem, src_row_of_elem, outer_row)
        act_out = active_mask(n_out, out_cap)
        src_row = jnp.where(act_out, src_row, -1)
        caps = payload_caps or (None,) * len(batch.columns)
        cols = [gather_column(c, src_row, out_byte_capacity=bc)
                for c, bc in zip(batch.columns, caps)]
        if self.position:
            pos_valid = is_elem & act_out
            cols.append(Column(jnp.where(pos_valid, intra, 0),
                               pos_valid if self.outer
                               else jnp.where(act_out, True, False),
                               IntegerType()))
        elem_idx = jnp.where(is_elem & act_out, e, -1)
        if map_col is not None:
            cols.append(gather_column(map_col.keys, elem_idx))
            cols.append(gather_column(map_col.values, elem_idx))
        else:
            cols.append(gather_column(arr.child, elem_idx))
        return ColumnarBatch(cols, n_out, self.output_schema)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        op_time = self.metrics[OP_TIME]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        for batch in self.child.execute():
            in_batches.add(1)
            with op_time.ns_timer():
                yield self._jit(batch, self._payload_caps(batch))

    def node_description(self):
        kind = "PosExplode" if self.position else "Explode"
        return (f"GenerateExec[{kind}{'Outer' if self.outer else ''}"
                f"({self.generator!r})]")
