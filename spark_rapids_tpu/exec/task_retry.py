"""Task-attempt re-execution (ISSUE 4 tentpole part 2) — the engine
analog of Spark's task scheduler retrying a failed task attempt.

A "task" here is one driven query (DataFrame.collect / a bench lane):
when an attempt dies with a *transient* failure — TpuTaskRetryError, an
injected device fault, a non-RESOURCE_EXHAUSTED XLA runtime error, a
checksum-quarantined spill file or shuffle block — the attempt's outputs
are discarded and the plan re-executes from the sources, up to
`spark.rapids.tpu.task.maxAttempts` attempts with capped exponential
backoff. OOM stays on the with_retry spill/split lane (memory/retry.py);
everything classified "fatal" surfaces immediately.

Attempt isolation: `task_attempt()` exposes the current attempt number
thread-locally; the shuffle writer tags its temp files with it and
commits atomically (write-then-rename, index last), so a failed
attempt's partial shards are never visible to readers — the reference's
shuffle commit protocol, single-process edition.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, TypeVar

from ..config import (TASK_MAX_ATTEMPTS, TASK_RETRY_BACKOFF_MS, RapidsConf,
                      active_conf)
from .. import faults
from ..faults import TpuTaskRetryError, classify  # noqa: F401 — re-export

T = TypeVar("T")

_BACKOFF_CAP_MS = 5000

_tls = threading.local()

#: total task re-executions this process (bench chaos record)
_retry_count = 0
_retry_lock = threading.Lock()


def task_attempt() -> int:
    """The current task attempt number (1-based; 1 outside any
    with_task_retry scope). Consumed by the shuffle writer's
    attempt-tagged commit protocol."""
    return getattr(_tls, "attempt", 1)


def capture_attempt() -> Optional[int]:
    """The raw attempt thread-local (None outside a retry scope) — the
    pipeline stage boundary captures it on the consumer and adopts it in
    the producer thread, like conf/query-id/speculation context: an
    exchange write driven from a producer must tag its shuffle files
    with the REAL attempt."""
    return getattr(_tls, "attempt", None)


def adopt_attempt(attempt: Optional[int]) -> None:
    """Install a captured attempt on this (producer) thread."""
    if attempt is None:
        if hasattr(_tls, "attempt"):
            del _tls.attempt
    else:
        _tls.attempt = attempt


def task_retry_total() -> int:
    return _retry_count


def _backoff_s(attempt: int, base_ms: int, label: str) -> float:
    # label in the jitter key: concurrent tasks retrying at the same
    # attempt number spread out instead of re-herding in lockstep
    return faults.backoff_s(attempt, base_ms, _BACKOFF_CAP_MS,
                            f"task:{label}:{attempt}")


def _settle_between_attempts() -> None:
    """Let the failed attempt's async machinery land before re-running:
    in-flight spill writebacks finish (their budget releases land), so
    the fresh attempt starts from settled accounting. Pipeline producer
    threads were already joined by the exception's finally chain."""
    from ..memory.catalog import buffer_catalog
    try:
        buffer_catalog().drain_writeback()
    except Exception:  # noqa: BLE001 — settling is best-effort; the
        pass           # retry itself decides whether the state is usable


def with_task_retry(run: Callable[[int], T],
                    conf: Optional[RapidsConf] = None,
                    label: str = "query") -> T:
    """Execute `run(attempt)` with bounded task-level re-execution.

    `run` must be restartable from the sources (every attempt rebuilds
    its exec tree / re-reads its inputs — exactly what DataFrame.collect
    does). Non-transient errors and exhausted attempts propagate with
    the original traceback."""
    global _retry_count
    conf = conf if conf is not None else active_conf()
    max_attempts = max(1, conf.get(TASK_MAX_ATTEMPTS))
    base_ms = max(1, conf.get(TASK_RETRY_BACKOFF_MS))
    prev = getattr(_tls, "attempt", None)
    try:
        attempt = 0
        while True:
            attempt += 1
            _tls.attempt = attempt
            try:
                return run(attempt)
            except Exception as e:  # noqa: BLE001 — classified below
                if classify(e) != "task" or attempt >= max_attempts:
                    raise
                with _retry_lock:
                    _retry_count += 1
                backoff = _backoff_s(attempt, base_ms, label)
                from ..obs import events as obs_events
                obs_events.emit(
                    "task_retry", label=label, attempt=attempt,
                    max_attempts=max_attempts,
                    backoff_ns=int(backoff * 1e9),
                    error=f"{type(e).__name__}: {e}"[:200])
                _settle_between_attempts()
                time.sleep(backoff)
    finally:
        if prev is None:
            if hasattr(_tls, "attempt"):
                del _tls.attempt
        else:
            _tls.attempt = prev
