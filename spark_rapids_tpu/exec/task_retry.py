"""Task-attempt re-execution (ISSUE 4 tentpole part 2) — the engine
analog of Spark's task scheduler retrying a failed task attempt.

A "task" here is one driven query (DataFrame.collect / a bench lane):
when an attempt dies with a *transient* failure — TpuTaskRetryError, an
injected device fault, a non-RESOURCE_EXHAUSTED XLA runtime error, a
checksum-quarantined spill file or shuffle block — the attempt's outputs
are discarded and the plan re-executes from the sources, up to
`spark.rapids.tpu.task.maxAttempts` attempts with capped exponential
backoff. OOM stays on the with_retry spill/split lane (memory/retry.py);
everything classified "fatal" surfaces immediately.

Attempt isolation: `task_attempt()` exposes the current attempt number
thread-locally; the shuffle writer tags its temp files with it and
commits atomically (write-then-rename, index last), so a failed
attempt's partial shards are never visible to readers — the reference's
shuffle commit protocol, single-process edition.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, TypeVar

from ..config import (TASK_MAX_ATTEMPTS, TASK_RETRY_BACKOFF_MS, RapidsConf,
                      active_conf)
from .. import faults
from ..faults import TpuTaskRetryError, classify  # noqa: F401 — re-export

T = TypeVar("T")

_BACKOFF_CAP_MS = 5000

_tls = threading.local()

#: total task re-executions this process (bench chaos record)
_retry_count = 0
_retry_lock = threading.Lock()


def task_attempt() -> int:
    """The current task attempt number (1-based; 1 outside any
    with_task_retry scope). Consumed by the shuffle writer's
    attempt-tagged commit protocol."""
    return getattr(_tls, "attempt", 1)


def capture_attempt() -> Optional[int]:
    """The raw attempt thread-local (None outside a retry scope) — the
    pipeline stage boundary captures it on the consumer and adopts it in
    the producer thread, like conf/query-id/speculation context: an
    exchange write driven from a producer must tag its shuffle files
    with the REAL attempt."""
    return getattr(_tls, "attempt", None)


def adopt_attempt(attempt: Optional[int]) -> None:
    """Install a captured attempt on this (producer) thread."""
    if attempt is None:
        if hasattr(_tls, "attempt"):
            del _tls.attempt
    else:
        _tls.attempt = attempt


def task_retry_total() -> int:
    return _retry_count


def _backoff_s(attempt: int, base_ms: int, label: str) -> float:
    # label in the jitter key: concurrent tasks retrying at the same
    # attempt number spread out instead of re-herding in lockstep
    return faults.backoff_s(attempt, base_ms, _BACKOFF_CAP_MS,
                            f"task:{label}:{attempt}")


def _settle_between_attempts() -> None:
    """Let the failed attempt's async machinery land before re-running:
    in-flight spill writebacks finish (their budget releases land), so
    the fresh attempt starts from settled accounting. Pipeline producer
    threads were already joined by the exception's finally chain.
    Settling stays best-effort — the retry itself decides whether the
    state is usable — but a settling failure is no longer silent: a
    catalog wedged between attempts is exactly what an operator
    debugging a non-converging retry loop needs to see."""
    from ..memory.catalog import buffer_catalog
    try:
        buffer_catalog().drain_writeback()
    except Exception as e:  # noqa: BLE001 — settling is best-effort
        from ..obs import events as obs_events
        obs_events.emit("task_retry_settle_error",
                        error=f"{type(e).__name__}: {e}"[:200])


def with_task_retry(run: Callable[[int], T],
                    conf: Optional[RapidsConf] = None,
                    label: str = "query") -> T:
    """Execute `run(attempt)` with bounded task-level re-execution.

    `run` must be restartable from the sources (every attempt rebuilds
    its exec tree / re-reads its inputs — exactly what DataFrame.collect
    does). Non-transient errors and exhausted attempts propagate with
    the original traceback."""
    global _retry_count
    conf = conf if conf is not None else active_conf()
    max_attempts = max(1, conf.get(TASK_MAX_ATTEMPTS))
    base_ms = max(1, conf.get(TASK_RETRY_BACKOFF_MS))
    prev = getattr(_tls, "attempt", None)
    from . import lifecycle
    try:
        attempt = 0
        while True:
            attempt += 1
            _tls.attempt = attempt
            lifecycle.begin_attempt(attempt)
            try:
                result = run(attempt)
                # a half-open breaker whose domain this attempt engaged
                # (probed) closes on success (exec/lifecycle.py)
                lifecycle.attempt_succeeded()
                return result
            except Exception as e:  # noqa: BLE001 — classified below
                # degradation breakers FIRST: every classified-
                # transient failure counts, INCLUDING the final
                # exhausted attempt (the strongest persistence signal —
                # and with maxAttempts=1 it is the only one; review r2)
                transient = classify(e) == "task"
                if transient:
                    lifecycle.attempt_failed(e)
                if not transient or attempt >= max_attempts:
                    raise
                # a cancelled/expired governed query must not burn
                # further attempts (or sleep a backoff past its
                # deadline): surface the cancellation instead
                lifecycle.check_current("task-retry")
                with _retry_lock:
                    _retry_count += 1
                backoff = _backoff_s(attempt, base_ms, label)
                from ..obs import events as obs_events
                # provenance travels into the event (ISSUE 6): shuffle
                # blocks with captured lineage recover on the
                # partition-granular lane in shuffle/manager.py and
                # never reach here; everything landing on THIS lane is
                # a whole-plan re-execution (provenance ambiguous or
                # absent — docs/robustness.md)
                prov = getattr(e, "provenance", None)
                extra = {"provenance": prov} if prov else {}
                obs_events.emit(
                    "task_retry", label=label, attempt=attempt,
                    max_attempts=max_attempts,
                    backoff_ns=int(backoff * 1e9), lane="whole_plan",
                    error=f"{type(e).__name__}: {e}"[:200], **extra)
                # active_queries() shows the backoff/settle window as
                # "retrying"; begin_attempt flips it back to executing
                lifecycle.set_phase("retrying")
                _settle_between_attempts()
                # deadline-aware backoff (review r4): a governed
                # query's deadline can expire mid-sleep — a blind
                # time.sleep(capped at 5s) would overshoot the
                # documented wall-clock bound by the whole backoff
                end = time.monotonic() + backoff
                # phase attribution (ISSUE 17): the settle + backoff
                # window between attempts, accrued even when the
                # deadline check raises mid-sleep
                from ..obs import phase as obs_phase
                t0b = time.perf_counter_ns()
                try:
                    while True:
                        lifecycle.check_current("task-retry")
                        remaining = end - time.monotonic()
                        if remaining <= 0:
                            break
                        time.sleep(min(0.05, remaining))
                finally:
                    obs_phase.add("retry-backoff",
                                  time.perf_counter_ns() - t0b)
    finally:
        if prev is None:
            if hasattr(_tls, "attempt"):
                del _tls.attempt
        else:
            _tls.attempt = prev
