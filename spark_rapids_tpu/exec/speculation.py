"""Plan-level speculative execution scope.

The masked-bucket aggregation kernel (ops/maskedagg.py) emits SMALL
partials plus a device `leftover` flag instead of paying for a
full-capacity exact fallback on every batch. Inside a speculation scope
the flag is never read per batch (a d2h sync costs more than the kernel);
it is recorded as a device scalar and checked ONCE when results are
materialized. If any flag tripped, the scope owner re-runs the plan with
speculation disabled (every aggregate takes its exact sync-free tier).

This is the engine's analog of the reference's optimistic
hash-aggregate-then-sort-fallback duality (GpuAggregateExec.scala:909),
lifted from per-batch to per-plan granularity because TPU host round
trips, not device memory, are the scarce resource.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List, Optional

import numpy as np


class SpeculationScope:
    def __init__(self):
        self.flags: List = []  # device bool scalars

    def record(self, flag) -> None:
        self.flags.append(flag)

    def drain(self) -> List:
        out, self.flags = self.flags, []
        return out

    def tripped(self) -> bool:
        """ONE host sync over all recorded flags."""
        if not self.flags:
            return False
        import jax.numpy as jnp
        flags = self.drain()
        return bool(np.asarray(jnp.any(jnp.stack(flags))))


class _State(threading.local):
    def __init__(self):
        self.scope: Optional[SpeculationScope] = None
        self.forced_exact = False


_state = _State()


def current_scope() -> Optional[SpeculationScope]:
    return _state.scope


def capture_context():
    """(scope, forced_exact) of this thread — captured at a pipeline
    stage boundary so the producer thread inherits it."""
    return _state.scope, _state.forced_exact


def adopt_context(scope, forced_exact: bool) -> None:
    """Install a captured speculation context on this (producer)
    thread: aggregates running behind the boundary record their
    overflow flags into the CONSUMER's scope."""
    _state.scope = scope
    _state.forced_exact = forced_exact


def speculation_allowed() -> bool:
    return _state.scope is not None and not _state.forced_exact


@contextmanager
def speculation_scope():
    prev = _state.scope
    scope = SpeculationScope()
    _state.scope = scope
    try:
        yield scope
    finally:
        _state.scope = prev


@contextmanager
def force_exact():
    prev = _state.forced_exact
    _state.forced_exact = True
    try:
        yield
    finally:
        _state.forced_exact = prev
