"""Basic execs: scan, project (tiered/CSE), filter, range, expand, union,
limits — reference basicPhysicalOperators.scala (GpuProjectExec:350,
GpuTieredProject:507, GpuFilterExec:783, GpuRangeExec:1116), limit.scala,
GpuExpandExec.scala.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, bucket_capacity
from ..expr.core import Alias, BoundReference, Expression, output_name, resolve
from ..memory.retry import split_in_half_by_rows, with_retry
from ..memory.spillable import SpillableBatch
from ..ops.basic import active_mask, compact_columns, sanitize, slice_rows
from ..types import LongType, Schema, StructField
from .base import (COMPILE_TIME, DISPATCH_METRICS, GATHER_METRICS,
                   GATHER_TIME, NUM_DISPATCHES, NUM_GATHERS,
                   NUM_INPUT_BATCHES, NUM_INPUT_ROWS, NUM_UPLOADS,
                   OP_TIME, PIPELINE_STAGE_METRICS, UPLOAD_METRICS,
                   UPLOAD_PACK_TIME, TpuExec)


class InMemoryScanExec(TpuExec):
    """Leaf feeding pre-built device batches (tests, broadcast relations,
    shuffle reads). File-format scans live in the io/ package."""

    def __init__(self, batches: Sequence[ColumnarBatch], schema: Schema):
        super().__init__()
        self._batches = list(batches)
        self._schema = schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def _fingerprint_extras(self):
        # programs depend on the schema (in the fingerprint already)
        # and batch SHAPES (jit arg keys) — never on the data values
        return ()

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        yield from self._batches


class SourceScanExec(TpuExec):
    """Leaf driving an io/ source's `batches()` stream (ISSUE 3: the
    scan -> first-device-op pipeline boundary). With pipelining enabled
    the file decode + host->device upload of batch N+1 runs on a
    background producer thread while downstream operators compute batch
    N — the engine analog of the reference's multithreaded cloud reader
    overlapping S3 fetch + decode with kernels. The producer holds the
    TPU admission semaphore across its uploads (one permit per scan,
    re-entrant with its consumer's task), so prefetch respects
    spark.rapids.sql.concurrentGpuTasks; its `semaphore_acquire` event
    is attributed to the producer. Disabled (pipeline.enabled=false /
    depth=0) this is a plain synchronous drive of the same iterator —
    bit-identical output either way."""

    def __init__(self, source, schema: Schema):
        super().__init__()
        self._source = source
        self._schema = schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def additional_metrics(self):
        return PIPELINE_STAGE_METRICS + UPLOAD_METRICS + DISPATCH_METRICS

    def _fingerprint_extras(self):
        # the source's class scopes the fingerprint; its data never
        # shapes a program (shapes ride the jit arg keys)
        return (type(self._source).__name__,)

    @property
    def runs_own_pipeline_stage(self) -> bool:
        return True

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        stage = self.pipeline_stage(self._produce(), "scan")
        try:
            yield from stage
        finally:
            stage.close()

    def _produce(self) -> Iterator[ColumnarBatch]:
        """Runs on the pipeline producer thread when enabled: decode +
        upload happen here, gated by the admission semaphore. The permit
        is held only around ONE batch's decode+upload — while this scan
        idles on a full prefetch queue it owes the device nothing, so
        concurrent queries' scans aren't starved for the stream's
        lifetime (the reference holds per active device work, not per
        stream)."""
        from ..columnar.upload import metric_sink
        from ..memory.semaphore import tpu_semaphore
        from ..obs import dispatch as obs_dispatch
        from .pipeline import cancelled
        sem = tpu_semaphore()
        # a source that drives a child exec plan to build its data (e.g.
        # CachedRelation materialization) must do so BEFORE we hold the
        # admission permit: the inner plan's scan takes its own permit,
        # and nesting that acquire under ours deadlocks when the
        # semaphore has one permit
        prepare = getattr(self._source, "ensure_materialized", None)
        if prepare is not None:
            prepare()
        it = iter(self._source.batches())
        try:
            while True:
                if not sem.acquire_if_necessary(self._op_id,
                                                cancel=cancelled):
                    return  # consumer closed the stage while we waited
                try:
                    # the decode + packed device upload of this batch
                    # happen inside next(it) on THIS (producer) thread:
                    # the sink attributes them to this scan's
                    # numUploads/uploadPackTimeNs (ISSUE 10)
                    # the upload's device unpack program is a module-
                    # level dispatch site — the dispatch metric scope
                    # attributes it here, like the upload sink
                    with metric_sink(self.metrics[NUM_UPLOADS],
                                     self.metrics[UPLOAD_PACK_TIME]), \
                            obs_dispatch.metric_scope(
                                self.metrics[NUM_DISPATCHES],
                                self.metrics[COMPILE_TIME]):
                        batch = next(it)
                except StopIteration:
                    return
                finally:
                    sem.release_if_necessary(self._op_id)
                yield batch
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def node_description(self):
        return f"SourceScanExec[{type(self._source).__name__}]"


def bind_projection(exprs: Sequence[Expression], schema: Schema
                    ) -> List[Expression]:
    return [resolve(e, schema) for e in exprs]


def projection_schema(exprs: Sequence[Expression], schema: Schema) -> Schema:
    bound = bind_projection(exprs, schema)
    fields = []
    for i, e in enumerate(bound):
        fields.append(StructField(output_name(exprs[i], f"col{i}"),
                                  e.data_type, e.nullable))
    return Schema(tuple(fields))


class _CSECache:
    """Common-subexpression cache shared across one projection evaluation —
    the effect of the reference's GpuTieredProject
    (basicPhysicalOperators.scala:507) without explicit tiers: XLA fusion
    already dedupes device work; this dedupes *tracing* work."""

    def __init__(self):
        self._cache: Dict[tuple, Column] = {}

    def eval(self, expr: Expression, batch: ColumnarBatch) -> Column:
        key = expr.semantic_key()
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        col = expr.columnar_eval(batch)
        self._cache[key] = col
        return col


def eval_projection(bound: Sequence[Expression], batch: ColumnarBatch,
                    schema: Schema) -> ColumnarBatch:
    cse = _CSECache()
    cols = [sanitize(cse.eval(e, batch), batch.num_rows) for e in bound]
    return batch.with_columns(cols, schema)


class ProjectExec(TpuExec):
    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        super().__init__(child)
        self.exprs = list(exprs)
        self._schema = projection_schema(self.exprs, child.output_schema)
        self._bound = bind_projection(self.exprs, child.output_schema)
        self._jit = self._site(
            lambda b: eval_projection(self._bound, b, self._schema),
            label="ProjectExec.project")

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def additional_metrics(self):
        return DISPATCH_METRICS

    def _fingerprint_extras(self):
        # semantic_key, NOT repr: repr is display-only and omits
        # non-child parameters (a trim set, a pad char) — the CSE
        # identity is the value-complete one (caught live: two trims
        # differing only in trim set shared one cached program).
        # Non-deterministic expressions (UDFs key per-INSTANCE by id,
        # recyclable after GC) opt the subtree out of the cache.
        if not all(e.deterministic for e in self._bound):
            return None
        return tuple(e.semantic_key() for e in self._bound)

    @property
    def consumes_encoded(self) -> bool:
        # encoded input is fine when every projection either passes the
        # column through untouched or never touches a string reference
        # outside a code-space position (ISSUE 18)
        from ..expr.predicates import encoded_safe_projection
        return all(encoded_safe_projection(e) for e in self._bound)

    @property
    def output_grouped_by(self):
        """Projection preserves row order: the child's grouping contract
        carries through for columns projected as bare references."""
        child_hint = self.child.output_grouped_by
        if not child_hint:
            return None
        from ..expr.core import Alias, UnresolvedAttribute
        renames = {}  # child name -> set of output names
        for e in self.exprs:
            out_name = None
            src = e
            if isinstance(e, Alias):
                out_name = e.name
                src = e.children[0]
            src_name = getattr(src, "name", None) \
                if isinstance(src, UnresolvedAttribute) else None
            if src_name is not None:
                renames.setdefault(src_name, set()).add(
                    out_name or src_name)
        classes = []
        for cls in child_hint:
            mapped = frozenset(n for c in cls for n in renames.get(c, ()))
            if not mapped:
                return None  # a grouping class vanished from the output
            classes.append(mapped)
        return tuple(classes)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        op_time = self.metrics[OP_TIME]
        for batch in self.child.execute():
            spillable = SpillableBatch.from_batch(batch)
            try:
                with op_time.ns_timer():
                    yield from with_retry(
                        spillable,
                        lambda s: self._project_spillable(s),
                        split_policy=split_in_half_by_rows)
            finally:
                spillable.close()

    def _project_spillable(self, s: SpillableBatch) -> ColumnarBatch:
        batch = s.get_batch()
        try:
            return self._jit(batch)
        finally:
            s.release()

    def fused_step(self):
        """Whole-stage fusion hook: this operator as a pure traced step a
        consumer can inline into its own program (the reference's analog is
        whole-stage codegen; XLA is the codegen)."""
        return ("project", self._bound, self._schema)

    #: stage-compiler step protocol (ISSUE 14): same pure step, but a
    #: SEPARATE name — fused_step is the AggregateExec absorption
    #: protocol, and growing it (ExpandExec) would silently change
    #: which operators aggregates swallow
    stage_step = fused_step

    def node_description(self):
        return f"ProjectExec[{', '.join(map(repr, self.exprs))}]"


class FilterExec(TpuExec):
    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__(child)
        self.condition = condition
        self._bound = resolve(condition, child.output_schema)
        self._jit = self._site(self._kernel, label="FilterExec.filter")
        from ..ops.gather import GatherTracker
        self._gather_track = GatherTracker(self.metrics[NUM_GATHERS],
                                           self.metrics[GATHER_TIME])

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return GATHER_METRICS + DISPATCH_METRICS

    def _fingerprint_extras(self):
        if not self._bound.deterministic:
            return None  # see ProjectExec._fingerprint_extras
        return (self._bound.semantic_key(),)

    @property
    def consumes_encoded(self) -> bool:
        # equality / IN / null predicates evaluate in code space
        # (expr/predicates.EqualTo code-space lane); the compaction
        # gather handles DictionaryColumn natively (ops/basic.py)
        from ..expr.predicates import encoded_safe_predicate
        return encoded_safe_predicate(self._bound)

    def _kernel(self, batch: ColumnarBatch) -> ColumnarBatch:
        pred = self._bound.columnar_eval(batch)
        # Spark: null predicate rows are dropped
        keep = pred.data & pred.validity
        cols, n = compact_columns(batch.columns, keep, batch.num_rows)
        return ColumnarBatch(cols, n, batch.schema)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        op_time = self.metrics[OP_TIME]
        try:
            for batch in self.child.execute():
                spillable = SpillableBatch.from_batch(batch)
                try:
                    with op_time.ns_timer():
                        yield from with_retry(
                            spillable,
                            lambda s: self._filter_spillable(s),
                            split_policy=split_in_half_by_rows)
                finally:
                    spillable.close()
        finally:
            self._gather_track.emit_event(type(self).__name__,
                                          self._op_id)

    def _filter_spillable(self, s: SpillableBatch) -> ColumnarBatch:
        batch = s.get_batch()
        try:
            # stage-boundary harness (ISSUE 14): the governance hooks
            # (gather accounting here) bind AROUND the one program
            # call — the kernel itself stays pure traced dataflow
            with self.batch_harness(gather_shape=(batch.capacity,)):
                return self._jit(batch)
        finally:
            s.release()

    def fused_step(self):
        """Fusion hook: in a fused stage the filter contributes a row MASK
        (ANDed into the consumer's reductions) instead of a compaction
        gather — gathers are among the slowest ops on TPU, masks are free."""
        return ("filter", self._bound)

    #: stage-compiler step protocol (see ProjectExec.stage_step)
    stage_step = fused_step

    def node_description(self):
        return f"FilterExec[{self.condition!r}]"


class RangeExec(TpuExec):
    """GpuRangeExec (basicPhysicalOperators.scala:1116): generates id ranges
    directly on device in target-sized batches."""

    def __init__(self, start: int, end: int, step: int = 1,
                 batch_rows: int = 1 << 20, name: str = "id"):
        super().__init__()
        assert step != 0
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows
        self._schema = Schema((StructField(name, LongType(), False),))

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def _fingerprint_extras(self):
        return (self.start, self.end, self.step, self.batch_rows)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        total = max(0, -(-(self.end - self.start) // self.step))
        emitted = 0
        while emitted < total:
            n = min(self.batch_rows, total - emitted)
            cap = bucket_capacity(n)
            base = self.start + emitted * self.step
            data = base + jnp.arange(cap, dtype=jnp.int64) * self.step
            act = jnp.arange(cap, dtype=jnp.int32) < n
            col = Column(jnp.where(act, data, 0), act, LongType())
            yield ColumnarBatch([col], n, self._schema)
            emitted += n


class UnionExec(TpuExec):
    """GpuUnionExec: concatenation of children outputs (schemas align)."""

    #: batches pass through untouched (ISSUE 18)
    consumes_encoded = True

    def __init__(self, *children: TpuExec):
        super().__init__(*children)

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def _fingerprint_extras(self):
        return ()

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        for c in self.children:
            for batch in c.execute():
                yield ColumnarBatch(batch.columns, batch.num_rows,
                                    self.output_schema,
                                    batch._host_rows)


class LocalLimitExec(TpuExec):
    """GpuLocalLimitExec (limit.scala:168): per-partition row cap."""

    #: row slicing routes through the dict-aware gather (ISSUE 18)
    consumes_encoded = True

    def __init__(self, limit: int, child: TpuExec):
        super().__init__(child)
        self.limit = limit

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def _fingerprint_extras(self):
        return (self.limit, getattr(self, "offset", 0))

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        remaining = self.limit
        for batch in self.child.execute():
            if remaining <= 0:
                break
            n = batch.num_rows_host
            if n <= remaining:
                remaining -= n
                yield batch
            else:
                cols = [slice_rows(c, jnp.int32(0), jnp.int32(remaining),
                                   batch.capacity)
                        for c in batch.columns]
                yield ColumnarBatch(cols, remaining, batch.schema)
                remaining = 0


class GlobalLimitExec(LocalLimitExec):
    """Single-partition engine: same row cap with optional offset."""

    def __init__(self, limit: int, child: TpuExec, offset: int = 0):
        super().__init__(limit, child)
        self.offset = offset

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        to_skip = self.offset
        inner = super().internal_execute() if self.offset == 0 else \
            self.child.execute()
        if self.offset == 0:
            yield from inner
            return
        remaining = self.limit
        for batch in inner:
            n = batch.num_rows_host
            if to_skip >= n:
                to_skip -= n
                continue
            start = to_skip
            to_skip = 0
            take = min(n - start, remaining)
            if take <= 0:
                break
            cols = [slice_rows(c, jnp.int32(start), jnp.int32(take),
                               batch.capacity) for c in batch.columns]
            yield ColumnarBatch(cols, take, batch.schema)
            remaining -= take
            if remaining <= 0:
                break


class ExpandExec(TpuExec):
    """GpuExpandExec: N projections per input batch (GROUPING SETS/rollup).

    Emits one batch per projection rather than interleaving rows — same
    multiset of rows, better shapes for XLA."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 child: TpuExec):
        super().__init__(child)
        self.projections = [list(p) for p in projections]
        self._schema = projection_schema(self.projections[0],
                                         child.output_schema)
        self._bound = [bind_projection(p, child.output_schema)
                       for p in self.projections]
        self._jits = [
            self._site(
                lambda b, bp=bp: eval_projection(bp, b, self._schema),
                label="ExpandExec.project", key_salt=i)
            for i, bp in enumerate(self._bound)]

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def additional_metrics(self):
        return DISPATCH_METRICS

    def _fingerprint_extras(self):
        if not all(e.deterministic for bp in self._bound for e in bp):
            return None  # see ProjectExec._fingerprint_extras
        return tuple(tuple(e.semantic_key() for e in bp)
                     for bp in self._bound)

    def stage_step(self):
        """Stage-compiler step (ISSUE 14): all projections emitted from
        ONE fused program per input batch. NOT a fused_step — the
        AggregateExec absorption protocol must not swallow expands."""
        return ("expand", self._bound, self._schema)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        for batch in self.child.execute():
            for jitfn in self._jits:
                yield jitfn(batch)


class SampleExec(TpuExec):
    """Bernoulli row sampling (reference GpuSampleExec /
    GpuPartitionwiseSampledRDD + GpuPoissonSampler,
    basicPhysicalOperators.scala): each row survives with probability
    `fraction`, decided by the threefry counter RNG on device — fold_in
    of the batch index keeps every batch's draw independent AND the whole
    sample reproducible for a given seed."""

    #: compaction routes through the dict-aware gather (ISSUE 18)
    consumes_encoded = True

    def __init__(self, fraction: float, seed: int, child: TpuExec):
        super().__init__(child)
        self.fraction = float(fraction)
        self.seed = int(seed)
        self._jit = self._site(self._kernel, label="SampleExec.sample",
                               static_argnums=(2,))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return DISPATCH_METRICS

    def _fingerprint_extras(self):
        return (self.fraction, self.seed)

    def _kernel(self, batch: ColumnarBatch, batch_idx, fraction: float):
        import jax as _jax
        key = _jax.random.fold_in(_jax.random.key(self.seed), batch_idx)
        u = _jax.random.uniform(key, (batch.capacity,), jnp.float32)
        keep = (u < fraction) & active_mask(batch.num_rows, batch.capacity)
        cols, n = compact_columns(batch.columns, keep, batch.num_rows)
        return ColumnarBatch(cols, n, batch.schema)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        op_time = self.metrics[OP_TIME]
        for i, batch in enumerate(self.child.execute()):
            with op_time.ns_timer():
                yield self._jit(batch, jnp.uint32(i), self.fraction)

    def node_description(self):
        return f"SampleExec[fraction={self.fraction}, seed={self.seed}]"
