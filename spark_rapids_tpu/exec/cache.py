"""Columnar in-memory table cache — the reference's
ParquetCachedBatchSerializer + GpuInMemoryTableScanExec (SURVEY §2.6): a
cached DataFrame materializes ONCE into compressed host frames (the same
LZ4 wire format the shuffle uses — the analog of the reference caching
parquet-encoded buffers instead of raw device memory) and every re-scan
rebuilds device batches from those frames.

Host-resident by design: HBM stays free for the running query, re-scan
cost is one decompress+upload per batch, and the cache survives device
OOM/spill cycles untouched.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional

from ..columnar.batch import ColumnarBatch
from ..types import Schema


class CachedRelation:
    """Materialize-once scan source (plugs into LogicalScan like any
    other source)."""

    def __init__(self, child_exec_factory, schema: Schema):
        self._factory = child_exec_factory
        self.schema = schema
        self._lock = threading.Lock()
        self._frames: Optional[List[bytes]] = None
        self.compressed_bytes = 0
        self.raw_bytes = 0

    @property
    def is_materialized(self) -> bool:
        return self._frames is not None

    def _materialize(self) -> None:
        from ..shuffle.serializer import serialize_batch
        frames: List[bytes] = []
        raw = 0
        for b in self._factory().execute():
            frames.append(serialize_batch(b))
            raw += b.device_size_bytes()
        self._frames = frames
        self.compressed_bytes = sum(map(len, frames))
        self.raw_bytes = raw

    def ensure_materialized(self) -> None:
        """SourceScanExec calls this BEFORE taking the admission permit:
        materialization drives a full child plan whose own scan needs a
        permit — running it under the outer scan's permit deadlocks at
        spark.rapids.sql.concurrentGpuTasks=1 (the inner acquire waits
        forever on the permit the outer producer holds)."""
        with self._lock:
            if self._frames is None:
                self._materialize()

    def batches(self) -> Iterator[ColumnarBatch]:
        from ..shuffle.serializer import deserialize_batch
        with self._lock:
            if self._frames is None:
                self._materialize()
            frames = self._frames  # snapshot: concurrent unpersist-safe
        for i, fr in enumerate(frames):
            # the frame ordinal keys the decode's packed-upload chaos
            # draws: concurrent producer threads replaying a cached
            # relation must not let OS scheduling permute which batch
            # draws a seeded fault (the shuffle.decode key discipline)
            yield deserialize_batch(fr, self.schema,
                                    fault_key=f"cache:{i}")

    def estimated_size_bytes(self) -> int:
        if self._frames is not None:
            return self.compressed_bytes
        return 1 << 62  # unknown until materialized; never broadcast

    def unpersist(self) -> None:
        with self._lock:
            self._frames = None
            self.compressed_bytes = 0
            self.raw_bytes = 0
