"""Adaptive runtime replanner (ISSUE 19) — act on the MEASURED exchange
statistics the obs subsystem already records, so adversarial data shapes
degrade gracefully instead of OOMing or livelocking.

The engine has recorded exact per-partition map-output rows/bytes at
every host exchange since ISSUE 11 (`obs/stats.ExchangeRecorder`), and
the advisor can *diagnose* partition skew (ISSUE 17) — but nothing
*acted*. This module is the control plane: consulted at exchange-read
boundaries (after the write phase, before any reader stream exists), it
makes four decisions, every one from measured bytes, never estimates:

``skew_split``
    A reducer partition over ``skewedPartitionFactor x median`` (and the
    min-bytes floor) is read as K map-output-granular sub-reads
    (`shuffle/manager.HostShuffleReader.plan_map_groups`), each its own
    probe stream against the replicated build side — no single
    hash-join window ever holds the whole hot key. Per-map lineage
    recovery (ISSUE 6) still works under a split read, and the ICI
    all-to-all lane stands down for the exchange (uneven splits don't
    fit the static device collective).
``broadcast_demote``
    A planned broadcast/single-build join whose build side MEASURES
    larger than ``autoBroadcastMaxBytes`` — or the admitting ticket's
    workload-governor quota share — demotes to the sub-partitioned
    strategy BEFORE the first OOM retry fires.
``single_build_convert``
    The converse: a shuffled hash join whose build side measured small
    at exchange-write time collapses back to one single-build probe
    pass, skipping the probe side's exchange entirely.
``partition_coalesce``
    Adjacent reducer partitions under ``coalesceTargetBytes`` merge
    into one read on flat (partition-oblivious) consumers only —
    partition-aware consumers (shuffled joins, partition-wise sort)
    always see the static boundaries.
``batch_right_size``
    After `with_retry` resorts to an OOM split, the query's
    QueryContext carries a halved batch target consumed by
    CoalesceBatchesExec, so later batches of the same query stop
    re-triggering the retry lane.

Every applied decision emits an ``adaptive_replan`` event carrying its
evidence (measured bytes, threshold, chosen action); refusals and
strategy demotions emit ``adaptive_demote``. The lane registers the
``adaptive`` breaker domain: decisions engage it for the attempt, and a
consult-path error records a domain failure, so a misfiring replanner
demotes itself to the static plan instead of flapping.

Results are unchanged on CPU: integer paths stay byte-exact (splits and
coalesces regroup the same decoded blocks in the same order); float
deltas are limited to the documented OOM-split reduction-order class.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: decision slug -> what it does / its evidence. The docs/robustness.md
#: "Adaptive execution" table is lint-checked against this registry
#: (tests/test_docs_lint.py), like the breaker-domain table.
DECISIONS: Dict[str, str] = {
    "skew_split": "reducer partition over factor x median bytes read "
                  "as map-granular sub-reads, one probe stream each",
    "broadcast_demote": "measured-oversized build side (conf cap or "
                        "quota share) demoted to sub-partitioned "
                        "strategy before any OOM retry",
    "single_build_convert": "shuffle join whose build side measured "
                            "small converted to a single-build probe "
                            "pass (probe-side exchange skipped)",
    "partition_coalesce": "adjacent reducer partitions under the "
                          "target merged into one read (flat "
                          "consumers only)",
    "batch_right_size": "query batch target halved after an OOM "
                        "split, consumed by CoalesceBatchesExec",
}

#: decision slug -> counter key (the "adaptive" counter family bench /
#: history / profile_report roll up)
_DECISION_COUNTER = {
    "skew_split": "skew_splits",
    "broadcast_demote": "broadcast_demotes",
    "single_build_convert": "single_build_converts",
    "partition_coalesce": "partition_coalesces",
    "batch_right_size": "batch_right_sizes",
}

_COUNTER_LOCK = threading.Lock()
_COUNTERS: Dict[str, int] = {
    "consults": 0,
    "skew_splits": 0,
    "broadcast_demotes": 0,
    "single_build_converts": 0,
    "partition_coalesces": 0,
    "batch_right_sizes": 0,
    "breaker_demotions": 0,
    "errors": 0,
}


def _note(**deltas: int) -> None:
    with _COUNTER_LOCK:
        for k, v in deltas.items():
            _COUNTERS[k] = _COUNTERS.get(k, 0) + v


def counters() -> Dict[str, int]:
    """Cumulative process-wide decision counters (the chaos-counters
    snapshot pattern: bench and history diff these per record)."""
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def reset_adaptive() -> None:
    """Zero the counters (test isolation)."""
    with _COUNTER_LOCK:
        for k in _COUNTERS:
            _COUNTERS[k] = 0


# -- gate --------------------------------------------------------------------

def consult(conf, op: str = "", op_id: int = -1) -> bool:
    """May adaptive decisions apply here? Conf on AND the `adaptive`
    breaker closed. A breaker refusal is itself a demotion decision:
    counted and emitted (ESSENTIAL) so operators see the lane stand
    down, exactly the ICI degradation-seam discipline."""
    from ..config import ADAPTIVE_ENABLED
    if not conf.get(ADAPTIVE_ENABLED):
        return False
    from . import lifecycle
    if not lifecycle.breaker_allows("adaptive"):
        _note(breaker_demotions=1)
        from ..obs import events as obs_events
        obs_events.emit("adaptive_demote", exec=op, op_id=op_id,
                        decision="lane", reason="breaker_open")
        return False
    _note(consults=1)
    return True


def note_error(op: str = "", op_id: int = -1, error: str = "") -> None:
    """A consult-path failure: the replanner must never take a query
    down, so callers catch, fall back to the static plan, and record
    the failure against the `adaptive` breaker domain here — repeated
    misfires open the breaker and the lane stands down."""
    _note(errors=1)
    from . import lifecycle
    lifecycle.record_domain_failure("adaptive")
    from ..obs import events as obs_events
    obs_events.emit("adaptive_demote", exec=op, op_id=op_id,
                    decision="lane", reason="error",
                    error=str(error)[:200])


def note_decision(decision: str, op: str = "", op_id: int = -1,
                  **evidence) -> None:
    """One applied decision: count it, emit the evidence-carrying
    `adaptive_replan` event, and engage the breaker domain for the
    attempt so a downstream transient failure is attributed here."""
    _note(**{_DECISION_COUNTER[decision]: 1})
    from ..obs import events as obs_events
    obs_events.emit("adaptive_replan", exec=op, op_id=op_id,
                    decision=decision, **evidence)
    from . import lifecycle
    lifecycle.engage_domain("adaptive")


def note_demote(decision: str, op: str = "", op_id: int = -1,
                **evidence) -> None:
    """A strategy demotion (ESSENTIAL visibility): a planned cheap
    strategy measured unaffordable and the safe one was chosen."""
    _note(**{_DECISION_COUNTER[decision]: 1})
    from ..obs import events as obs_events
    obs_events.emit("adaptive_demote", exec=op, op_id=op_id,
                    decision=decision, **evidence)
    from . import lifecycle
    lifecycle.engage_domain("adaptive")


# -- decision 1: skewed-reducer splitting ------------------------------------

def skew_threshold(per_part_bytes: Sequence[int],
                   conf) -> Optional[Tuple[int, int]]:
    """(threshold_bytes, median_bytes) above which a partition is
    skewed, or None when splitting is off / undecidable. Median over
    the NONZERO partitions (the ExchangeStats.skew basis: empty
    partitions of a sparse key space would drag the median to zero and
    flag everything)."""
    from ..config import ADAPTIVE_SKEW_FACTOR, ADAPTIVE_SKEW_MIN_BYTES
    factor = conf.get(ADAPTIVE_SKEW_FACTOR)
    if factor <= 0:
        return None
    nz = sorted(b for b in per_part_bytes if b > 0)
    if len(nz) < 2:
        return None
    median = nz[len(nz) // 2]
    floor = max(0, conf.get(ADAPTIVE_SKEW_MIN_BYTES))
    return max(int(factor * median), floor), median


# -- decision 2: measured build-side caps ------------------------------------

def auto_broadcast_max(conf) -> int:
    """The conf cap for measured single-build/broadcast decisions
    (-1 = conversions off)."""
    from ..config import ADAPTIVE_AUTO_BROADCAST_MAX_BYTES
    return conf.get(ADAPTIVE_AUTO_BROADCAST_MAX_BYTES)


def demote_cap(conf) -> Optional[Tuple[int, str]]:
    """(cap_bytes, basis) a measured build side must stay under to keep
    a single-build plan: the tighter of adaptive.autoBroadcastMaxBytes
    and the admitting ticket's workload quota share (basis "conf" /
    "quota"). None when neither bound applies."""
    cap = auto_broadcast_max(conf)
    bound = (cap, "conf") if cap >= 0 else None
    try:
        from ..memory.budget import memory_budget
        from . import workload
        share = workload.quota_bytes(memory_budget().limit)
    except Exception:  # noqa: BLE001 — governor off / no budget
        share = None
    if share is not None and (bound is None or share < bound[0]):
        bound = (share, "quota")
    return bound


# -- decision 3: tiny-partition coalescing -----------------------------------

def coalesce_groups(per_part_bytes: Sequence[int], target: int,
                    exclude: Optional[Set[int]] = None,
                    ) -> Optional[List[List[int]]]:
    """Greedy adjacent grouping of reducer partitions whose measured
    bytes fit `target` together; `exclude`d partitions (e.g. ones being
    skew-split) always stand alone. Returns the full partition cover in
    order, or None when no group would merge anything."""
    exclude = exclude or set()
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_b = 0
    for p, b in enumerate(per_part_bytes):
        if p in exclude or b > target:
            if cur:
                groups.append(cur)
                cur, cur_b = [], 0
            groups.append([p])
            continue
        if cur and cur_b + b > target:
            groups.append(cur)
            cur, cur_b = [], 0
        cur.append(p)
        cur_b += b
    if cur:
        groups.append(cur)
    if all(len(g) == 1 for g in groups):
        return None
    return groups


# -- decision 4: OOM-feedback batch right-sizing -----------------------------

#: never shrink the batch target below this — a 4 KiB floor keeps a
#: pathological split cascade from degenerating to row-at-a-time
MIN_BATCH_TARGET = 4 * 1024


def note_oom_split() -> None:
    """Called from with_retry's SPLIT branch: halve the governed
    query's effective batch target (floored) so CoalesceBatchesExec
    stops assembling batches the device just proved it cannot hold.
    Outside a governed query, or with adaptive off, this is a no-op."""
    from . import lifecycle
    ctx = lifecycle.current_context()
    if ctx is None:
        return
    from ..config import ADAPTIVE_ENABLED, BATCH_SIZE_BYTES, active_conf
    conf = active_conf()
    if not conf.get(ADAPTIVE_ENABLED):
        return
    cur = ctx.adaptive_batch_target
    if cur is None:
        cur = conf.get(BATCH_SIZE_BYTES)
    new = max(MIN_BATCH_TARGET, cur // 2)
    if new >= cur:
        return
    ctx.adaptive_batch_target = new
    note_decision("batch_right_size", op="with_retry",
                  prev_target=cur, new_target=new)


def batch_target_override() -> Optional[int]:
    """The governed query's shrunken batch target, or None — ONE
    context-pointer read plus one attribute read on the hot path, no
    conf access (CoalesceBatchesExec consults this per flush check)."""
    from . import lifecycle
    ctx = lifecycle.current_context()
    if ctx is None:
        return None
    return ctx.adaptive_batch_target
