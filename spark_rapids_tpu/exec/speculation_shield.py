"""Straggler & stall shield (ISSUE 20 tentpole): the tail-latency
control loop that turns the heartbeat, stats and phase planes from
passive reporting into active mitigation.

The reference stack's answers to the tail are Spark's speculative
execution (a task running past `spark.speculation.multiplier` x the
median gets a duplicate attempt, first result wins) and fetch-failure
handling (a dead executor's map outputs are invalidated and recomputed
from lineage, not re-fetched forever). Theseus (PAPERS.md) shows
distributed accelerator pipelines gate on their slowest data-movement
participant; this module rebuilds the mitigation loop for the
single-process multi-thread engine, in four conf-gated pieces:

* **Progress watchdog** (`ProgressWatchdog`) — distinct from the
  total-wall `query.timeoutMs` deadline: a governed query whose driving
  seam advances no root batches/rows for
  `spark.rapids.tpu.stall.timeoutMs` emits ONE `query_stalled` event
  (ESSENTIAL — with the stalled operator and the dominant phase from
  the PR 17 ledger, read mid-flight without closing its books) and
  takes `stall.action`: `report` | `retry-seam` (fail the attempt with
  a transient error at its next cancellation checkpoint, onto the
  bounded task-retry lane) | `cancel`. Re-arms after each episode.

* **Speculative shuffle sub-reads** (`ReadSpeculation`) — a
  fetch/decode future that exceeds a latency bound derived from the
  reader's OWN measured distribution (Log2Hist p95 x
  `speculation.multiplier`, floored at `speculation.minMs`) gets ONE
  duplicate attempt under a `spec:` work-item key; first result wins,
  the loser is cancelled or discarded. In-flight speculations are
  bounded per query (`speculation.maxInFlight`) — a denied straggler
  keeps waiting on its primary. Duplicates ride the bounded reader
  pool: never free admission-path work.

* **Dispatch hang bound** (`timed_call`) — a watchdog-timed
  block-until-ready wrapper at the dispatch-ledger chokepoint and the
  ICI collective seam: a wedged device program classifies as a
  transient task error after `dispatch.timeoutMs` (breaker domain
  `device_dispatch` / `ici_exchange`), instead of hanging the process.

* **Dead-peer invalidation glue** (`on_peer_dead`) — the
  HeartbeatManager's `peer_dead` transition invalidates that peer's
  registered map outputs in the shuffle registry
  (shuffle/manager.HostShuffleManager.invalidate_peer_outputs), so the
  next read routes through the PR 5 partition-granular recompute lane;
  the peer's slot stays blacklisted until it re-registers.

Cost discipline: every capability defaults off (the dead-peer lane
defaults on but requires an installed heartbeat manager, absent in the
default single-process session) and costs one conf/pointer check when
off. Counters are process-cumulative (`counters()`), deltaed per bench
record and rolled into the history capsule `speculation` family.
"""

from __future__ import annotations

import contextlib
import threading
import time
from concurrent.futures import FIRST_COMPLETED
from concurrent.futures import TimeoutError as FuturesTimeout
from concurrent.futures import wait as futures_wait
from typing import Callable, Dict, Optional

#: what the progress watchdog may do on a stall —
#: docs/robustness.md's STALL_ACTIONS table is lint-checked against
#: this tuple (tests/test_docs_lint.py), like BREAKER_DOMAINS
STALL_ACTIONS = ("report", "retry-seam", "cancel")


# ---------------------------------------------------------------------------
# counters (bench.py {"speculation": ...} deltas + profile_report roll-up)
# ---------------------------------------------------------------------------

_counter_lock = threading.Lock()
_counters: Dict[str, int] = {
    "stalls": 0,
    "stall_retries": 0,
    "stall_cancels": 0,
    "spec_launched": 0,
    "spec_wins": 0,
    "spec_primary_wins": 0,
    "spec_denied": 0,
    "spec_wait_ns": 0,
    "dispatch_timeouts": 0,
    "peer_invalidations": 0,
    "outputs_invalidated": 0,
}


def _count(key: str, n: int = 1) -> None:
    with _counter_lock:
        _counters[key] += n


def counters() -> Dict[str, int]:
    """Snapshot of the process-cumulative shield counters — one dict so
    bench.py can delta it per record (chaos-delta pattern)."""
    with _counter_lock:
        return dict(_counters)


def reset_shield() -> None:
    """Test isolation: zero the counters and drop per-query speculation
    slots (the conftest reset companion)."""
    with _counter_lock:
        for k in _counters:
            _counters[k] = 0
    with _slot_lock:
        _slots.clear()


# ---------------------------------------------------------------------------
# progress watchdog
# ---------------------------------------------------------------------------

class ProgressWatchdog:
    """One daemon monitor per governed query (armed by
    `TpuSession.collect` when `stall.timeoutMs` > 0). Polls the
    QueryContext's root-output progress counters — the note_batch
    attribute writes the governor already pays for — and fires when
    they freeze for the configured window. Always stop()ed by the
    collect finally; a leaked thread still dies with the process
    (daemon) and goes quiet as soon as the poll sees the stop flag."""

    def __init__(self, ctx, timeout_ms: int, action: str):
        self.ctx = ctx
        self.timeout_s = max(1, int(timeout_ms)) / 1000.0
        self.action = action if action in STALL_ACTIONS else "report"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # poll a few times per window so a stall is noticed within
        # ~1.25x the timeout, capped at 1s so short windows stay sharp
        interval = min(max(self.timeout_s / 4.0, 0.005), 1.0)
        # contract: ok thread-adopt — the watchdog observes ONE query's
        # context (held directly, not via thread-locals) and attributes
        # its event through with_query_id at emit time
        self._thread = threading.Thread(
            target=self._loop, args=(interval,), daemon=True,
            name=f"stall-watchdog-{self.ctx.ctx_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)

    def _progress(self) -> tuple:
        c = self.ctx
        # attempt_no participates: a task retry resets batch counts to
        # zero, which must read as progress (the retry lane is moving),
        # not as a frozen seam
        return (c.attempt_no, c.batches_produced, c.rows_produced)

    def _loop(self, interval: float) -> None:
        last = self._progress()
        last_t = time.monotonic()
        while not self._stop.wait(interval):
            cur = self._progress()
            now = time.monotonic()
            if cur != last:
                last, last_t = cur, now
                continue
            if now - last_t < self.timeout_s:
                continue
            self._fire(now - last_t)
            # re-arm: a query still frozen fires again only after
            # another FULL window (one event per stall episode)
            last_t = now

    def _fire(self, stalled_s: float) -> None:
        ctx = self.ctx
        _count("stalls")
        led = ctx.phase_ledger
        phase = led.dominant_phase() if led is not None else None
        from ..obs import events as obs_events
        obs_events.with_query_id(
            ctx.events_qid, obs_events.emit, "query_stalled",
            stalled_ms=int(stalled_s * 1000),
            timeout_ms=int(self.timeout_s * 1000),
            action=self.action, seam=ctx.current_op, phase=phase,
            attempt=ctx.attempt_no, batches=ctx.batches_produced,
            rows=ctx.rows_produced)
        if self.action == "cancel":
            _count("stall_cancels")
            ctx.cancel("stalled")
        elif self.action == "retry-seam":
            _count("stall_retries")
            # consumed (and cleared) by QueryContext.check at the
            # stalled attempt's next cancellation checkpoint: the seam
            # raises a transient error onto the task-retry lane
            ctx.stall_retry = True


def watchdog_for(ctx, conf) -> Optional[ProgressWatchdog]:
    """The collect()-seam constructor: a started watchdog when
    `stall.timeoutMs` > 0, else None (one conf read — the entire
    disabled-mode cost)."""
    from ..config import STALL_ACTION, STALL_TIMEOUT_MS
    timeout_ms = conf.get(STALL_TIMEOUT_MS)
    if not timeout_ms or timeout_ms <= 0:
        return None
    dog = ProgressWatchdog(ctx, timeout_ms, conf.get(STALL_ACTION))
    dog.start()
    return dog


# ---------------------------------------------------------------------------
# speculative shuffle sub-reads
# ---------------------------------------------------------------------------

#: per-query in-flight speculation slots (key: governed ctx_id, or None
#: for ungoverned readers — still bounded, process-wide)
_slot_lock = threading.Lock()
_slots: Dict[Optional[int], int] = {}


def _slot_key() -> Optional[int]:
    from . import lifecycle
    ctx = lifecycle.current_context()
    return ctx.ctx_id if ctx is not None else None


def _take_slot(max_inflight: int) -> bool:
    key = _slot_key()
    with _slot_lock:
        n = _slots.get(key, 0)
        if n >= max_inflight:
            return False
        _slots[key] = n + 1
        return True


def _release_slot() -> None:
    key = _slot_key()
    with _slot_lock:
        n = _slots.get(key, 0) - 1
        if n <= 0:
            _slots.pop(key, None)
        else:
            _slots[key] = n


class ReadSpeculation:
    """Per-reader speculative sub-read policy: measured fetch/decode
    latency histograms (ms), the derived straggler bound, and the
    first-result-wins race. One instance per HostShuffleReader when
    `shuffle.speculation.enabled`; the reader keeps its plain
    unbounded-wait path untouched when off."""

    __slots__ = ("multiplier", "min_ms", "max_inflight", "_hists",
                 "_lock")

    def __init__(self, multiplier: float, min_ms: int,
                 max_inflight: int):
        from ..obs.stats import Log2Hist
        self.multiplier = max(1.0, float(multiplier))
        self.min_ms = max(1, int(min_ms))
        self.max_inflight = max(1, int(max_inflight))
        self._hists = {"fetch": Log2Hist(), "decode": Log2Hist()}
        self._lock = threading.Lock()

    def timed(self, stage: str, fn, *args):
        """Pool-side wrapper: run the fetch/decode and record its
        latency into the stage's histogram — the distribution the
        straggler bound derives from."""
        t0 = time.perf_counter_ns()
        out = fn(*args)
        ms = (time.perf_counter_ns() - t0) // 1_000_000
        with self._lock:
            self._hists[stage].add(int(ms))
        return out

    def bound_ms(self, stage: str) -> int:
        """The straggler bound for `stage`: measured p95 x multiplier,
        floored at min_ms (a cold histogram or microsecond-fast local
        reads must not trigger duplicate work)."""
        with self._lock:
            p95 = self._hists[stage].percentile(95)
        return max(int(p95 * self.multiplier), self.min_ms)

    def resolve(self, stage: str, primary, launch: Callable[[], object],
                key: str):
        """Wait on `primary` up to the stage's straggler bound; past it,
        take an in-flight slot and launch ONE duplicate via `launch()`
        (a zero-arg returning a Future keyed `spec:<key>`). First
        successful result wins; the loser is cancelled (a running loser
        is discarded when its pool slot drains). A denied straggler —
        no free slot — keeps waiting on its primary. Failure semantics:
        a failed loser is ignored while the other attempt is pending;
        both failing surfaces the primary's error (it carries the real
        fault identity)."""
        bound_s = self.bound_ms(stage) / 1000.0
        try:
            return primary.result(timeout=bound_s)
        except FuturesTimeout:
            pass
        t0 = time.perf_counter_ns()
        if not _take_slot(self.max_inflight):
            _count("spec_denied")
            try:
                return self._await(primary)
            finally:
                self._note_wait(t0)
        _count("spec_launched")
        spec = None
        try:
            spec = launch()
            winner, out, err = self._race(primary, spec)
        except BaseException:
            # cancelled mid-race (deadline / user): drop both attempts
            primary.cancel()
            if spec is not None:
                spec.cancel()
            raise
        finally:
            _release_slot()
        wait_ns = self._note_wait(t0)
        if winner == "spec":
            _count("spec_wins")
        elif winner == "primary":
            _count("spec_primary_wins")
        from ..obs import events as obs_events
        obs_events.emit("speculative_fetch", stage=stage, key=key,
                        winner=winner, bound_ms=int(bound_s * 1000),
                        wait_ms=wait_ns // 1_000_000)
        if err is not None:
            raise err
        return out

    def _note_wait(self, t0: int) -> int:
        """Accrue the post-bound wait (straggler exposure the shield
        raced against) into the shield counters and the PR 17 phase
        ledger's `spec-wait` phase. This runs on a pipeline
        producer/consumer thread: a producer-side accrual lands in the
        ledger's folded map and re-attributes pipeline-stall budget, so
        `sum(phases) == wall_ns` holds unchanged."""
        ns = time.perf_counter_ns() - t0
        _count("spec_wait_ns", int(ns))
        from ..obs import phase as obs_phase
        obs_phase.add("spec-wait", int(ns))
        return int(ns)

    def _await(self, fut):
        """Bounded-poll wait on one future, honoring cooperative
        cancellation between polls (the denied-slot path)."""
        from . import lifecycle
        while True:
            try:
                return fut.result(timeout=0.05)
            except FuturesTimeout:
                lifecycle.check_current("pipeline-wait")

    def _race(self, primary, spec):
        """First successful result of the two attempts. Returns
        (winner, result, error): error is set only when BOTH failed."""
        pending = {primary: "primary", spec: "spec"}
        errs: Dict[str, BaseException] = {}
        from . import lifecycle
        while pending:
            done, _ = futures_wait(list(pending), timeout=0.05,
                                   return_when=FIRST_COMPLETED)
            if not done:
                lifecycle.check_current("pipeline-wait")
                continue
            for fut in done:
                who = pending.pop(fut)
                err = fut.exception()
                if err is None:
                    for loser in pending:
                        loser.cancel()
                    # contract: ok bounded-wait — fut came from the
                    # FIRST_COMPLETED done set: already resolved,
                    # result() returns without blocking
                    return who, fut.result(), None
                errs[who] = err
        return "none", None, errs.get("primary") or errs.get("spec")


def reader_speculation(conf) -> Optional[ReadSpeculation]:
    """The HostShuffleReader constructor hook: a ReadSpeculation when
    `shuffle.speculation.enabled`, else None (one conf read — the
    entire disabled-mode cost; the reader's plain path is untouched)."""
    from ..config import (SHUFFLE_SPECULATION_ENABLED,
                          SHUFFLE_SPECULATION_MAX_INFLIGHT,
                          SHUFFLE_SPECULATION_MIN_MS,
                          SHUFFLE_SPECULATION_MULTIPLIER)
    if not conf.get(SHUFFLE_SPECULATION_ENABLED):
        return None
    return ReadSpeculation(conf.get(SHUFFLE_SPECULATION_MULTIPLIER),
                           conf.get(SHUFFLE_SPECULATION_MIN_MS),
                           conf.get(SHUFFLE_SPECULATION_MAX_INFLIGHT))


# ---------------------------------------------------------------------------
# dispatch hang bound
# ---------------------------------------------------------------------------

def timed_call(fn: Callable[[], object], timeout_ms: int, domain: str,
               what: str):
    """Run the zero-arg `fn` (a device dispatch + block-until-ready)
    under a hang bound: past `timeout_ms` the call is abandoned on its
    daemon helper thread, a `dispatch_timeout` event fires, the breaker
    domain records a failure, and a transient DispatchTimeoutError
    routes the attempt onto the task-retry lane — the process never
    wedges behind a hung device program. One thread spawn per call: the
    bound is an opt-in diagnostic (`dispatch.timeoutMs`, default 0 =
    this function is never reached)."""
    box: Dict[str, object] = {}
    done = threading.Event()

    def run():
        try:
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed below
            box["err"] = e
        finally:
            done.set()

    # contract: ok thread-adopt — the caller's closure carries every
    # thread-local it needs (the dispatch ledger adopts its pending
    # frame inside fn); nothing else on this helper emits or reads
    # query state
    t = threading.Thread(target=run, daemon=True,
                         name=f"dispatch-shield-{domain}")
    t.start()
    if not done.wait(max(1, int(timeout_ms)) / 1000.0):
        _count("dispatch_timeouts")
        from ..obs import events as obs_events
        obs_events.emit("dispatch_timeout", domain=domain, what=what,
                        timeout_ms=int(timeout_ms))
        from . import lifecycle
        lifecycle.record_domain_failure(domain)
        from ..faults import DispatchTimeoutError
        raise DispatchTimeoutError(
            f"{what}: device program not ready after {timeout_ms}ms "
            f"(domain {domain}); abandoning the dispatch to the "
            f"task-retry lane")
    err = box.get("err")
    if err is not None:
        raise err
    return box.get("out")


def dispatch_timeout_ms(conf=None) -> int:
    """The configured hang bound (0 = off) — read by the dispatch
    ledger's configure() and the ICI seam, never per dispatch."""
    from ..config import DISPATCH_TIMEOUT_MS, active_conf
    conf = conf if conf is not None else active_conf()
    return max(0, int(conf.get(DISPATCH_TIMEOUT_MS)))


#: breaker-domain override for hang-bounded dispatches: the ICI
#: exchange round sets "ici_exchange" so a wedged collective records
#: against the breaker that already owns host-lane degradation, not the
#: generic device_dispatch domain
_domain_tls = threading.local()


@contextlib.contextmanager
def dispatch_domain(domain: str):
    """Dispatches hang-bounded inside this block attribute their
    timeout to `domain` (see `_domain_tls`). Nests; restores on exit."""
    prev = getattr(_domain_tls, "domain", None)
    _domain_tls.domain = domain
    try:
        yield
    finally:
        _domain_tls.domain = prev


def current_dispatch_domain() -> str:
    return getattr(_domain_tls, "domain", None) or "device_dispatch"


# ---------------------------------------------------------------------------
# dead-peer map-output invalidation glue
# ---------------------------------------------------------------------------

def on_peer_dead(executor_id: str) -> None:
    """The HeartbeatManager.on_peer_dead callback (wired by
    parallel.heartbeat.install): invalidate the dead peer's registered
    map outputs so the next read recovers through the
    partition-granular lane. Conf-gated; runs outside the heartbeat
    lock, on whatever thread noticed the transition."""
    from ..config import DEAD_PEER_INVALIDATION_ENABLED, active_conf
    if not active_conf().get(DEAD_PEER_INVALIDATION_ENABLED):
        return
    from ..shuffle.manager import shuffle_manager
    n = shuffle_manager().invalidate_peer_outputs(executor_id)
    if n:
        _count("peer_invalidations")
        _count("outputs_invalidated", n)
