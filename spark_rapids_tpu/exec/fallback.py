"""Per-operator CPU fallback: row↔columnar transitions + a host row
interpreter (the reference's convertToCpu path — GpuOverrides.scala:4427
converts unsupported nodes back to Spark's CPU operators node-by-node, with
GpuColumnarToRowExec.scala:335 / GpuRowToColumnarExec.scala:861 transition
nodes at the boundary).

Standalone difference: the reference hands unsupported operators to
Spark's JVM row engine; this engine ships its OWN host row engine — a
Python interpreter over the same expression tree, registered per
expression class. Only expressions with a registered (or derivable) host
evaluator may fall back; everything else still fails loudly at plan time
with the full explain report, so fallback never silently changes
semantics it cannot honor.

Transitions mirror the reference's node structure so plans read the same
way in tree_string():

    RowToColumnarExec
      HostProjectExec / HostFilterExec      (CPU row engine)
        ColumnarToRowExec
          ... TPU plan ...
"""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, Iterator, List, Sequence, Type

from ..columnar.batch import ColumnarBatch
from ..expr import arithmetic as A
from ..expr import conditional as C
from ..expr import predicates as P
from ..expr import stringexprs as S
from ..expr.cast import Cast
from ..expr.core import (Alias, BoundReference, Expression, Literal,
                         UnresolvedAttribute, output_name, resolve)
from ..types import (BooleanType, ByteType, DataType, DoubleType, FloatType,
                     IntegerType, LongType, Schema, ShortType, StringType,
                     StructField, TimestampType)
from .base import DEBUG, NUM_INPUT_BATCHES, OP_TIME, TpuExec

_I64 = (1 << 64)


def _wrap64(v: int) -> int:
    """Java long overflow semantics (the device lanes wrap the same way)."""
    v &= _I64 - 1
    return v - _I64 if v >= (1 << 63) else v


class HostEvalUnsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# host evaluator registry
# ---------------------------------------------------------------------------

_EVALS: Dict[Type[Expression], Callable] = {}


def _reg(cls, fn: Callable, null_intolerant: bool = True):
    if null_intolerant:
        def wrapped(expr, *vals, _fn=fn):
            if any(v is None for v in vals):
                return None
            return _fn(expr, *vals)
        _EVALS[cls] = wrapped
    else:
        _EVALS[cls] = fn


_INT_TYPES = (ByteType, ShortType, IntegerType, LongType)


def _is_int_expr(expr) -> bool:
    try:
        return isinstance(expr.data_type, _INT_TYPES)
    except (TypeError, NotImplementedError):
        return False


def _arith(op):
    def fn(expr, a, b):
        r = op(a, b)
        return _wrap64(r) if _is_int_expr(expr) and isinstance(r, int) \
            and not isinstance(r, bool) else r
    return fn


_reg(A.Add, _arith(lambda a, b: a + b))
_reg(A.Subtract, _arith(lambda a, b: a - b))
_reg(A.Multiply, _arith(lambda a, b: a * b))
_reg(A.Divide, lambda e, a, b: None if b == 0 else a / b)
_reg(A.IntegralDivide,
     lambda e, a, b: None if b == 0 else _wrap64(int(a // b)
                                                 if (a < 0) == (b < 0)
                                                 else -(-a // b if a < 0
                                                        else a // -b)))
def _java_rem(a, b):
    """Java % (sign of the dividend). Integers use exact integer
    truncated division — float division would corrupt longs > 2^53."""
    if isinstance(a, float) or isinstance(b, float):
        return math.fmod(a, b)
    q = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        q = -q
    return a - q * b


def _pmod(e, a, b):
    """Spark Pmod (arithmetic.scala): r = a % n; r < 0 ? (r + n) % n : r
    with Java remainder semantics — matches the device kernel."""
    if b == 0:
        return None
    r = _java_rem(a, b)
    return _java_rem(r + b, b) if r < 0 else r


_reg(A.Remainder, lambda e, a, b: None if b == 0 else _java_rem(a, b))
_reg(A.Pmod, _pmod)
_reg(A.UnaryMinus, lambda e, a: _wrap64(-a) if _is_int_expr(e) else -a)
_reg(A.Abs, lambda e, a: _wrap64(abs(a)) if _is_int_expr(e) else abs(a))
_reg(A.Least, lambda e, *vs: min(vs), null_intolerant=False)
_reg(A.Greatest, lambda e, *vs: max(vs), null_intolerant=False)


def _ignore_null_minmax(fn):
    def out(expr, *vals):
        vs = [v for v in vals if v is not None]
        return fn(vs) if vs else None
    return out


_EVALS[A.Least] = _ignore_null_minmax(min)
_EVALS[A.Greatest] = _ignore_null_minmax(max)

_reg(P.EqualTo, lambda e, a, b: a == b)
_reg(P.LessThan, lambda e, a, b: a < b)
_reg(P.LessThanOrEqual, lambda e, a, b: a <= b)
_reg(P.GreaterThan, lambda e, a, b: a > b)
_reg(P.GreaterThanOrEqual, lambda e, a, b: a >= b)
_reg(P.EqualNullSafe,
     lambda e, a, b: (a is None and b is None)
     or (a is not None and b is not None and a == b),
     null_intolerant=False)


def _and3(expr, a, b):
    if a is False or b is False:
        return False
    if a is None or b is None:
        return None
    return True


def _or3(expr, a, b):
    if a is True or b is True:
        return True
    if a is None or b is None:
        return None
    return False


_reg(P.And, _and3, null_intolerant=False)
_reg(P.Or, _or3, null_intolerant=False)
_reg(P.Not, lambda e, a: not a)
_reg(P.IsNull, lambda e, a: a is None, null_intolerant=False)
_reg(P.IsNotNull, lambda e, a: a is not None, null_intolerant=False)

_reg(C.If, lambda e, p, t, f: t if p is True else f, null_intolerant=False)
_reg(C.Coalesce,
     lambda e, *vs: next((v for v in vs if v is not None), None),
     null_intolerant=False)
_reg(C.Nvl,
     lambda e, *vs: next((v for v in vs if v is not None), None),
     null_intolerant=False)
_reg(C.Nvl2, lambda e, a, b, c: b if a is not None else c,
     null_intolerant=False)
_reg(C.NullIf, lambda e, a, b: None
     if a is not None and b is not None and a == b else a,
     null_intolerant=False)
_reg(C.IsNaN, lambda e, a: isinstance(a, float) and math.isnan(a))
_reg(C.NaNvl, lambda e, a, b: b
     if isinstance(a, float) and math.isnan(a) else a)


# collection family (device kernels exist; host evals let them ride the
# fallback tier when they appear beside host-only expressions) ------------

def _reg_collections():
    from ..expr import collectionexprs as ce

    def _contains(e, a):
        # the needle is an expression ATTRIBUTE (e.value), not a child
        v = e.value
        if v is None:
            return None
        if any(x == v for x in a if x is not None):
            return True
        return None if None in a else False

    def _sort_array(e, a):
        # Spark/device kernel (ops/collection.py): asc => nulls FIRST,
        # desc => nulls LAST
        nulls = [None] * sum(1 for x in a if x is None)
        vals = [x for x in a if x is not None]
        if getattr(e, "ascending", True):
            return nulls + sorted(vals)
        return sorted(vals, reverse=True) + nulls

    _reg(ce.CreateArray, lambda e, *vs: list(vs), null_intolerant=False)
    _reg(ce.Size, lambda e, a: len(a))
    _reg(ce.ArrayContains, _contains)
    _reg(ce.SortArray, _sort_array)
    _reg(ce.ArrayMin, lambda e, a: min(
        (x for x in a if x is not None), default=None))
    _reg(ce.ArrayMax, lambda e, a: max(
        (x for x in a if x is not None), default=None))


_reg_collections()


# string family ------------------------------------------------------------

_reg(S.Length, lambda e, s: len(s))
_reg(S.OctetLength, lambda e, s: len(s.encode("utf-8")))
_reg(S.BitLength, lambda e, s: 8 * len(s.encode("utf-8")))
_reg(S.Upper, lambda e, s: s.upper())
_reg(S.Lower, lambda e, s: s.lower())
_reg(S.Reverse, lambda e, s: s[::-1])
_reg(S.InitCap, lambda e, s: " ".join(
    w[:1].upper() + w[1:].lower() if w else w for w in s.split(" ")))
_reg(S.Concat, lambda e, *vs: "".join(vs))
_reg(S.ConcatWs,
     lambda e, *vs: e.sep.decode("utf-8").join(
         v for v in vs if v is not None),
     null_intolerant=False)
_reg(S.Ascii, lambda e, s: ord(s[0]) if s else 0)
_reg(S.Chr, lambda e, v: "" if v <= 0 else chr(v % 256))


def _substring(expr, *vals):
    s = vals[0]
    if s is None:
        return None
    pos = getattr(expr, "pos", 1)
    length = getattr(expr, "length", None)
    if pos > 0:
        start = pos - 1
    elif pos == 0:
        start = 0
    else:
        start = max(len(s) + pos, 0)
    end = len(s) if length is None else min(start + max(length, 0), len(s))
    return s[start:end]


# ---------------------------------------------------------------------------
# evaluation entry points
# ---------------------------------------------------------------------------

def _sql_like_to_re(pattern: str, escape: str) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("(?s)^" + "".join(out) + "$")


def _host_eval_special(expr: Expression, row) -> object:
    """Expressions whose semantics need fields beyond child values."""
    t = type(expr)
    if t is S.Substring:
        return _substring(expr, row_eval(expr.children[0], row))
    if t in (S.StartsWith, S.EndsWith, S.Contains):
        s = row_eval(expr.children[0], row)
        if s is None:
            return None
        needle = expr.needle  # stored utf-8 encoded
        needle = needle.decode("utf-8") if isinstance(needle, bytes) \
            else needle
        if t is S.StartsWith:
            return s.startswith(needle)
        if t is S.EndsWith:
            return s.endswith(needle)
        return needle in s
    if t is S.RLike:
        s = row_eval(expr.children[0], row)
        if s is None:
            return None
        return re.search(expr.pattern, s) is not None
    if t is S.Like:
        s = row_eval(expr.children[0], row)
        if s is None:
            return None
        return _sql_like_to_re(expr.pattern,
                               expr.escape_char).match(s) is not None
    if t is C.CaseWhen:
        n = expr.n_branches
        for i in range(n):
            if row_eval(expr.children[2 * i], row) is True:
                return row_eval(expr.children[2 * i + 1], row)
        if expr.has_else:
            return row_eval(expr.children[-1], row)
        return None
    if t is P.In:
        v = row_eval(expr.children[0], row)
        if v is None:
            return None
        items = expr.items
        if any(x == v for x in items if x is not None):
            return True
        return None if any(x is None for x in items) else False
    if t is Cast:
        return _host_cast(expr, row_eval(expr.children[0], row))
    raise HostEvalUnsupported(type(expr).__name__)


def _java_double_str(v: float, repr_fn=repr) -> str:
    """Java Double.toString rendering (what Spark's double→string cast
    emits): plain decimal for 1e-3 <= |v| < 1e7, otherwise d.dddE±n
    scientific notation; shortest round-trip mantissa; always at least one
    fraction digit ('1.0', '1.0E-4')."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    neg = math.copysign(1.0, v) < 0
    a = abs(v)
    if a == 0.0:
        return "-0.0" if neg else "0.0"
    if a == 5e-324:
        return "-4.9E-324" if neg else "4.9E-324"  # Java's MIN_VALUE digits
    s = repr_fn(a)  # shortest round-trip decimal
    mant, _, es = s.partition("e")
    exp = int(es) if es else 0
    ip, _, fp = mant.partition(".")
    digits = (ip + fp).lstrip("0")
    if ip.strip("0"):
        dec_exp = len(ip) + exp          # value = 0.<digits> * 10**dec_exp
    else:
        lead_zeros = len(fp) - len(fp.lstrip("0"))
        dec_exp = -lead_zeros + exp
    digits = digits.rstrip("0") or "0"
    if 1e-3 <= a < 1e7:
        if dec_exp <= 0:
            body = "0." + "0" * (-dec_exp) + digits
        elif dec_exp >= len(digits):
            body = digits + "0" * (dec_exp - len(digits)) + ".0"
        else:
            body = digits[:dec_exp] + "." + digits[dec_exp:]
    else:
        body = digits[0] + "." + (digits[1:] or "0") + "E" + str(dec_exp - 1)
    return ("-" if neg else "") + body


def _java_float_str(v: float) -> str:
    """Java Float.toString: same rules as Double.toString but with the
    shortest decimal that round-trips at FLOAT precision ('0.1', not
    '0.10000000149011612')."""
    import numpy as np
    v = float(np.float32(v))  # snap first: thresholds act on the f32 value
    if abs(v) == 1.401298464324817e-45:  # Float.MIN_VALUE digits in Java
        return "-1.4E-45" if v < 0 else "1.4E-45"
    return _java_double_str(v, repr_fn=lambda a: str(np.float32(a)))


def _timestamp_str(micros: int) -> str:
    """Spark's timestamp→string: 'yyyy-MM-dd HH:mm:ss' plus fractional
    seconds with trailing zeros trimmed (no trailing dot)."""
    import datetime as _dt
    d = _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(micros))
    base = d.strftime("%Y-%m-%d %H:%M:%S")
    if d.microsecond:
        base += (".%06d" % d.microsecond).rstrip("0")
    return base


def _host_cast(expr: Cast, v):
    if v is None:
        return None
    to = expr.data_type
    if isinstance(to, StringType):
        if isinstance(v, bool):
            return "true" if v else "false"
        try:
            src = expr.children[0].data_type
        except (TypeError, NotImplementedError):
            src = None
        if isinstance(v, float):
            if isinstance(src, FloatType):
                return _java_float_str(v)
            return _java_double_str(v)
        if isinstance(src, TimestampType):
            return _timestamp_str(v)
        return str(v)
    if isinstance(to, _INT_TYPES):
        bits = {ByteType: 8, ShortType: 16, IntegerType: 32,
                LongType: 64}[type(to)]
        if isinstance(v, str):
            try:
                v = int(v.strip())
            except ValueError:
                return None
        elif isinstance(v, float):
            if math.isnan(v) or math.isinf(v):
                return None
            v = int(v)
        elif isinstance(v, bool):
            v = int(v)
        v &= (1 << bits) - 1
        return v - (1 << bits) if v >= (1 << (bits - 1)) else v
    if isinstance(to, (DoubleType, FloatType)):
        if isinstance(v, str):
            try:
                return float(v.strip())
            except ValueError:
                return None
        return float(v)
    if isinstance(to, BooleanType):
        if isinstance(v, str):
            lv = v.strip().lower()
            if lv in ("t", "true", "y", "yes", "1"):
                return True
            if lv in ("f", "false", "n", "no", "0"):
                return False
            return None
        return bool(v)
    raise HostEvalUnsupported(f"host cast to {to.simple_name()}")


_SPECIAL = (S.Substring, S.StartsWith, S.EndsWith, S.Contains, S.RLike,
            S.Like, C.CaseWhen, P.In, Cast)


def row_eval(expr: Expression, row) -> object:
    """Evaluate one expression against a host row (tuple of logical
    values, indexed by BoundReference ordinal)."""
    if isinstance(expr, Literal):
        return expr.value
    if isinstance(expr, BoundReference):
        return row[expr.ordinal]
    if isinstance(expr, Alias):
        return row_eval(expr.children[0], row)
    if isinstance(expr, _SPECIAL):
        return _host_eval_special(expr, row)
    # extension points: host-tier expressions implement their own scalar
    # semantics (expr/jsonexprs.py etc. — families the reference keeps
    # off-GPU or that have no device kernel yet). The _with_row variant
    # drives sub-evaluation itself (higher-order functions binding
    # lambda variables per element).
    rich_fn = getattr(expr, "host_eval_with_row", None)
    if rich_fn is not None:
        return rich_fn(row, row_eval)
    host_fn = getattr(expr, "host_eval_row", None)
    if host_fn is not None:
        return host_fn(*[row_eval(c, row) for c in expr.children])
    fn = _EVALS.get(type(expr))
    if fn is None:
        raise HostEvalUnsupported(type(expr).__name__)
    vals = [row_eval(c, row) for c in expr.children]
    return fn(expr, *vals)


_HOST_CASTABLE = (StringType, ByteType, ShortType, IntegerType, LongType,
                  DoubleType, FloatType, BooleanType)


def _decimal_typed(expr: Expression) -> bool:
    """Decimal expressions must NEVER host-fall-back: host rows carry the
    raw unscaled ints, and plain Python arithmetic would ignore Spark's
    rescale rules (expr/decimal_rules.py)."""
    from ..types import DecimalType
    try:
        if isinstance(expr.data_type, DecimalType):
            return True
    except (TypeError, NotImplementedError):
        pass
    return any(_decimal_typed(c) for c in expr.children
               if isinstance(c, Expression))


def supports_host_eval(expr: Expression) -> bool:
    """Plan-time check: can the host row engine evaluate this tree?
    Must be EXACT for the _SPECIAL forms (pattern compiles, cast target
    implemented) — an over-approximation here would crash mid-query
    instead of failing loudly at plan time."""
    if isinstance(expr, (Literal, BoundReference, UnresolvedAttribute)):
        return True
    if isinstance(expr, Alias):
        return supports_host_eval(expr.children[0])
    if _decimal_typed(expr):
        return False
    if isinstance(expr, (S.RLike, S.Like)):
        if not isinstance(expr.pattern, str):
            return False
        if isinstance(expr, S.RLike):
            try:
                re.compile(expr.pattern)
            except re.error:
                return False
        return supports_host_eval(expr.children[0])
    if isinstance(expr, Cast):
        if not isinstance(expr.data_type, _HOST_CASTABLE):
            return False
        return supports_host_eval(expr.children[0])
    if isinstance(expr, (S.StringSplit, S.RegExpExtract, S.RegExpReplace)):
        # regex-bearing host-tier expressions: the pattern must compile
        # under Python re, or the fallback would crash mid-query
        if not isinstance(expr.pattern, str):
            return False
        try:
            re.compile(expr.pattern)
        except re.error:
            return False
        return all(supports_host_eval(c) for c in expr.children)
    from ..expr.collectionexprs import LambdaVar, _HostHOF, ArrayAggregate
    if isinstance(expr, LambdaVar):
        return True  # bound per element by the enclosing HOF
    if isinstance(expr, _HostHOF):
        return supports_host_eval(expr.children[0]) \
            and supports_host_eval(expr.body)
    if isinstance(expr, ArrayAggregate):
        return all(supports_host_eval(c) for c in expr.children) \
            and supports_host_eval(expr.merge) \
            and (expr.finish is None or supports_host_eval(expr.finish))
    if isinstance(expr, _SPECIAL) or type(expr) in _EVALS \
            or getattr(expr, "host_eval_row", None) is not None:
        return all(supports_host_eval(c) for c in expr.children)
    return False


# ---------------------------------------------------------------------------
# transition + host operator nodes
# ---------------------------------------------------------------------------

class ColumnarToRowExec(TpuExec):
    """Device batches → host rows (reference GpuColumnarToRowExec.scala:335).
    Consumed via rows(); as a safety net execute() passes batches through
    untouched (a columnar parent means the transition was optimized out)."""

    def __init__(self, child: TpuExec):
        super().__init__(child)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def rows(self) -> Iterator[tuple]:
        for b in self.child.execute():
            yield from b.to_pylist()

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        yield from self.child.execute()

    def node_description(self):
        return "ColumnarToRowExec"


class RowToColumnarExec(TpuExec):
    """Host rows → device batches (reference GpuRowToColumnarExec.scala:861),
    batching to `batch_rows` rows per upload."""

    def __init__(self, child: TpuExec, schema: Schema,
                 batch_rows: int = 1 << 16):
        super().__init__(child)
        self._schema = schema
        self._batch_rows = batch_rows

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def additional_metrics(self):
        return ((NUM_INPUT_BATCHES, DEBUG),)

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        names = self._schema.names
        buf: List[tuple] = []
        with self.metrics[OP_TIME].ns_timer():
            for row in self.child.rows():
                buf.append(row)
                if len(buf) >= self._batch_rows:
                    yield self._flush(names, buf)
                    buf = []
            if buf:
                yield self._flush(names, buf)

    def _flush(self, names, buf) -> ColumnarBatch:
        data = {n: [r[i] for r in buf] for i, n in enumerate(names)}
        return ColumnarBatch.from_pydict(data, self._schema)

    def node_description(self):
        return "RowToColumnarExec"


class _HostRowExec(TpuExec):
    """Base for host row-engine operators: children expose rows()."""

    def rows(self) -> Iterator[tuple]:
        raise NotImplementedError

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        raise AssertionError(
            f"{type(self).__name__} is row-based; wrap in RowToColumnarExec")


class HostProjectExec(_HostRowExec):
    """Row-engine projection over host-evaluable expressions (the CPU
    operator the reference falls back to for unsupported projections)."""

    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        super().__init__(child)
        in_schema = child.output_schema
        self._bound = [resolve(e, in_schema) for e in exprs]
        fields = []
        for i, (raw, b) in enumerate(zip(exprs, self._bound)):
            fields.append(StructField(output_name(raw, f"col{i}"),
                                      b.data_type))
        self._schema = Schema(tuple(fields))

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def rows(self) -> Iterator[tuple]:
        with self.metrics[OP_TIME].ns_timer():
            for row in self.child.rows():
                yield tuple(row_eval(e, row) for e in self._bound)

    def node_description(self):
        return f"HostProjectExec[{len(self._bound)} exprs]"


class HostFilterExec(_HostRowExec):
    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__(child)
        self._bound = resolve(condition, child.output_schema)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def rows(self) -> Iterator[tuple]:
        with self.metrics[OP_TIME].ns_timer():
            for row in self.child.rows():
                if row_eval(self._bound, row) is True:
                    yield row

    def node_description(self):
        return "HostFilterExec"
