"""Batch coalescing — reference GpuCoalesceBatches.scala:875 /
AbstractGpuCoalesceIterator:250. Concatenates small batches up to the target
batch size (spark.rapids.sql.batchSizeBytes) so downstream kernels run at
MXU-friendly sizes. Pending input is held as SpillableBatch so the coalesce
window never pins more HBM than the catalog allows."""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import bucket_capacity
from ..config import active_conf
from ..memory.retry import with_retry_no_split
from ..memory.spillable import SpillableBatch
from ..ops.basic import concat_columns, sanitize
from ..types import Schema
from ..obs import dispatch as obs_dispatch
from ..obs.dispatch import instrument
from . import adaptive
from .base import (COMPILE_TIME, CONCAT_TIME, DEBUG, DISPATCH_METRICS,
                   NUM_DISPATCHES, NUM_INPUT_BATCHES, NUM_INPUT_ROWS,
                   PIPELINE_STAGE_METRICS, TpuExec)


from functools import partial


@partial(instrument, label="coalesce.concat_pair",
         static_argnums=(2,))
def _concat_pair(a: ColumnarBatch, b: ColumnarBatch, cap: int
                 ) -> ColumnarBatch:
    cols = [concat_columns(ca, cb, a.num_rows, b.num_rows, cap)
            for ca, cb in zip(a.columns, b.columns)]
    return ColumnarBatch(cols, a.num_rows + b.num_rows, a.schema)


def concat_batches(batches: List[ColumnarBatch], schema: Schema
                   ) -> ColumnarBatch:
    """Concatenate active rows of all batches into one batch whose capacity
    is the bucket of the total. Tree-shaped pairwise reduction: each row is
    copied O(log k) times instead of the O(k) of a left fold, and each
    round runs ONE compiled concat program per capacity-shape pair (jit
    cache keyed on shapes + static out capacity)."""
    assert batches
    level = batches
    while len(level) > 1:
        nxt_level = []
        for i in range(0, len(level) - 1, 2):
            a, b = level[i], level[i + 1]
            if a._host_rows is not None and b._host_rows is not None:
                # exact: tight output bucket from known row counts
                rows = a._host_rows + b._host_rows
                cap = bucket_capacity(rows)
                out = _concat_pair(a, b, cap)
                nxt_level.append(ColumnarBatch(out.columns, rows, schema))
            else:
                # device row counts: don't sync — bucket by capacities
                cap = bucket_capacity(a.capacity + b.capacity)
                out = _concat_pair(a, b, cap)
                nxt_level.append(ColumnarBatch(out.columns, out.num_rows,
                                               schema))
        if len(level) % 2:
            nxt_level.append(level[-1])
        level = nxt_level
    return level[0]


class CoalesceBatchesExec(TpuExec):
    def __init__(self, child: TpuExec, target_bytes: Optional[int] = None):
        super().__init__(child)
        self.target_bytes = target_bytes or active_conf().batch_size_bytes

    #: dictionary-encoded batches flow through untouched on the
    #: single-batch path; a real multi-batch concat materializes first
    #: inside flush() — per-batch dictionaries differ, and
    #: concat_columns requires one shared payload
    consumes_encoded = True

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return (CONCAT_TIME, (NUM_INPUT_ROWS, DEBUG),
                (NUM_INPUT_BATCHES, DEBUG)) + PIPELINE_STAGE_METRICS \
            + DISPATCH_METRICS

    def _fingerprint_extras(self):
        # its concat program is a module-level site (process-cached
        # already); the extras exist so PARENT subtrees stay cacheable
        return (self.target_bytes,)

    @property
    def runs_own_pipeline_stage(self) -> bool:
        # wraps its input in a stage of its own — or, when the child
        # already runs one, that stage feeds this exec directly: either
        # way the output edge is covered and a consumer must not stack
        # another stage on it
        return True

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        in_rows = self.metrics[NUM_INPUT_ROWS]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        concat_time = self.metrics[CONCAT_TIME]
        pending: List[SpillableBatch] = []
        pending_bytes = 0

        def flush() -> Optional[ColumnarBatch]:
            nonlocal pending, pending_bytes
            if not pending:
                return None
            # the concat program is a module-level dispatch site: the
            # metric scope attributes its dispatches to this exec
            with concat_time.ns_timer(), obs_dispatch.metric_scope(
                    self.metrics[NUM_DISPATCHES],
                    self.metrics[COMPILE_TIME]):
                spillables, pending = pending, []
                pending_bytes = 0
                def do(items):
                    batches = [s.get_batch() for s in items]
                    try:
                        if len(batches) > 1:
                            from ..columnar.encoded import \
                                materialize_batch
                            batches = [materialize_batch(b, seam="concat")
                                       for b in batches]
                        return concat_batches(batches, self.output_schema)
                    finally:
                        for s in items:
                            s.release()
                try:
                    return with_retry_no_split(spillables, do)
                finally:
                    # close on BOTH paths: an exhausted retry must not
                    # leave the swapped-out set registered in the
                    # catalog (the outer finally only sees `pending`)
                    for s in spillables:
                        s.close()

        # pipelined input (ISSUE 3): upstream compute of batch N+1 runs
        # on the producer thread while this operator accumulates /
        # concatenates batch N — unless the child already runs its own
        # stage (TpuExec.runs_own_pipeline_stage): stacking a second one
        # on the same edge would double threads and live prefetched
        # device batches for zero extra overlap.
        depth = 0 if self.child.runs_own_pipeline_stage else None
        stage = self.pipeline_stage(self.child.execute(), "coalesce",
                                    depth=depth)
        try:
            for batch in stage:
                in_batches.add(1)
                if batch._host_rows is not None:
                    in_rows.add(batch._host_rows)
                else:
                    in_rows.add_device(batch.num_rows)
                size = batch.device_size_bytes()
                # OOM-feedback right-sizing (ISSUE 19): a with_retry
                # SPLIT earlier in this query shrank the governed batch
                # target — honor it here so later batches stop
                # re-triggering the retry lane. One context-pointer
                # read per batch, no conf access.
                target = self.target_bytes
                override = adaptive.batch_target_override()
                if override is not None and override < target:
                    target = override
                if pending and pending_bytes + size > target:
                    yield flush()
                pending.append(SpillableBatch.from_batch(batch))
                pending_bytes += size
                if pending_bytes >= target:
                    yield flush()
            tail = flush()
            if tail is not None:
                yield tail
        finally:
            stage.close()
            for s in pending:
                s.close()
