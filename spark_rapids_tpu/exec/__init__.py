"""Columnar execution operators (reference layer L3, SURVEY §2.3): TPU
plan nodes producing/consuming ColumnarBatch, the analog of GpuExec trees."""

from .base import TpuExec, TpuMetric  # noqa: F401
