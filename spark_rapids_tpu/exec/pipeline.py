"""Bounded asynchronous stage boundary for the pull-model operator chain
(ISSUE 3 tentpole).

The engine is a synchronous pull-model iterator chain: while the host
decodes/deserializes/uploads the NEXT batch, the device sits idle. The
reference accelerator hides that host cost everywhere — the
multithreaded cloud reader fetches ahead, shuffle fetches overlap kernel
launches, spill writes back asynchronously. `pipelined(it, depth)` is
the one primitive that buys the same overlap here: it moves an input
iterator onto a background producer thread feeding a bounded FIFO queue,
so the producer works `depth` batches ahead of the consumer.

Contracts (tests/test_pipeline.py):

* strict FIFO — items arrive in exactly the source order;
* exception propagation — a producer error is re-raised at the consumer
  call site AFTER the items produced before it (the original traceback
  is preserved on the exception object);
* clean shutdown — `close()` (or abandoning the wrapping generator,
  whose ``finally`` calls it) unblocks a producer stuck on a full queue,
  closes the source iterator, and joins the thread: no leaked threads,
  asserted via ``threading.enumerate()``;
* degradation — depth <= 0 (or pipeline.enabled=false) returns the
  plain synchronous iterator, bit-identical behavior.

Thread-local context (active conf, event-log query id, speculation
scope) is captured at the consumer and re-installed in the producer, so
operators running behind the boundary keep their conf, their query
attribution and their speculation-flag scope.

Observability: the boundary accumulates consumer stall (`wait_ns`,
blocked on an empty queue) and producer stall (`full_ns`, blocked on a
full queue), optionally into the owning operator's `pipelineWaitNs` /
`pipelineFullWaitNs` metrics, and emits one `pipeline_wait` + one
`pipeline_full` event record when the stage finishes. The overlap ratio
derived from these is surfaced by `QueryProfile.top_operators()`.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Iterator, Optional

from ..config import (PIPELINE_CLOSE_TIMEOUT_MS, PIPELINE_DEPTH,
                      PIPELINE_ENABLED, active_conf)
from .. import faults
from ..obs import phase as obs_phase

_END = object()


class StageCancelled(RuntimeError):
    """Raised by a stage consumer running on an OUTER closed stage's
    producer thread (nested stages). Deliberately NOT StopIteration: a
    consumer that materializes its input as a complete result (e.g.
    CachedRelation) must see the cut as an error, or it would cache the
    truncated stream as if it were the whole relation."""

#: shutdown poll period: a blocked producer/waiter re-checks the closed
#: flag this often (latency of an abandoned query's teardown, never of
#: the steady state)
_POLL_S = 0.05

_tls = threading.local()


def cancelled() -> bool:
    """True on a pipeline producer thread whose consumer closed the
    stage (False anywhere else). Long blocking waits inside producer
    code (e.g. the admission semaphore) poll this so an abandoned query
    can always tear down."""
    ev = getattr(_tls, "cancel_event", None)
    return ev is not None and ev.is_set()


def pipeline_depth(conf=None) -> int:
    """The configured prefetch depth, or 0 when pipelining is disabled."""
    conf = conf if conf is not None else active_conf()
    if not conf.get(PIPELINE_ENABLED):
        return 0
    return max(0, conf.get(PIPELINE_DEPTH))


def pipelined(source: Iterable[Any], depth: Optional[int] = None,
              label: str = "stage", wait_metric=None, full_metric=None,
              wall_metric=None, conf=None,
              emit_events: bool = True) -> Iterator[Any]:
    """Wrap `source` in a bounded background-producer iterator.

    depth None = the conf (spark.rapids.tpu.pipeline.{enabled,depth});
    depth <= 0 = the plain synchronous iterator (zero threads, zero
    behavior change). The returned object always has ``close()`` —
    consumers call it from a ``finally`` so early abandonment joins the
    producer thread. ``emit_events=False`` keeps a stage that is not an
    engine operator (e.g. tools/pipeline_bench driven in-process by
    bench.py) out of the query event log — its synthetic stalls would
    otherwise contaminate the real pipeline_wait/pipeline_full totals.
    """
    d = pipeline_depth(conf) if depth is None else depth
    if d <= 0:
        return _SyncStage(source)
    return PipelinedIterator(source, d, label=label,
                             wait_metric=wait_metric,
                             full_metric=full_metric,
                             wall_metric=wall_metric,
                             emit_events=emit_events)


class _SyncStage:
    """Degraded (synchronous) stage: the source iterator plus the
    close() and stall-counter surface the pipelined wiring (and
    tools/pipeline_bench.py) expect — a sync stage never stalls, so the
    counters stay 0."""

    __slots__ = ("_it", "wait_ns", "full_ns", "wall_ns", "batches")

    def __init__(self, source: Iterable[Any]):
        self._it = iter(source)
        self.wait_ns = 0
        self.full_ns = 0
        self.wall_ns = 0
        self.batches = 0

    def __iter__(self):
        return self

    def __next__(self):
        item = next(self._it)
        self.batches += 1
        return item

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()


class PipelinedIterator:
    """Background producer thread + bounded FIFO queue (one stage
    boundary). Single producer, single consumer."""

    def __init__(self, source: Iterable[Any], depth: int,
                 label: str = "stage", wait_metric=None, full_metric=None,
                 wall_metric=None, emit_events: bool = True):
        self._source = source
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, depth))
        self._label = label
        self._closed = threading.Event()
        self._exc: Optional[BaseException] = None
        self._finished = False
        self._stats_done = False
        self._wait_metric = wait_metric
        self._full_metric = full_metric
        self._wall_metric = wall_metric
        self._emit_events = emit_events
        #: close() watchdog budget (conf, read at stage construction)
        self._close_timeout_s = max(
            0.1, active_conf().get(PIPELINE_CLOSE_TIMEOUT_MS) / 1000.0)
        #: True once close() gave up joining a wedged producer
        self.stuck = False
        #: consumer ns blocked on an empty queue / producer ns blocked
        #: on a full one — the two stall signals overlap analysis needs
        self.wait_ns = 0
        self.full_ns = 0
        #: stage lifetime (construction -> finish/close): the overlap
        #: denominator, 1 - wait/wall = fraction of the stage NOT
        #: stalled on its input
        self.wall_ns = 0
        self.batches = 0
        self._t0 = time.perf_counter_ns()
        # producer-side thread-local context, captured HERE (the
        # consumer thread) and re-installed in the producer
        self._conf = active_conf()
        from ..obs import events as obs_events
        self._qid = obs_events.current_query_id()
        from .speculation import capture_context
        self._spec_ctx = capture_context()
        from .task_retry import capture_attempt
        self._attempt = capture_attempt()
        from . import lifecycle
        self._lctx = lifecycle.current_context()
        self._engaged = lifecycle.capture_engagement()
        self._thread = threading.Thread(
            target=self._run, name=f"pipeline-{label}", daemon=True)
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _run(self) -> None:
        # EVERYTHING runs inside the try: a failure in context install
        # or iter(source) must still reach the except/finally, or _END
        # is never posted and the consumer hangs on q.get() forever
        it = None
        try:
            from ..config import set_active_conf
            from ..obs import events as obs_events
            set_active_conf(self._conf)
            obs_events.adopt_query_id(self._qid)
            from .speculation import adopt_context
            adopt_context(*self._spec_ctx)
            # the task-attempt number too: an exchange WRITE driven from
            # this producer tags its shuffle temp files with it — left
            # un-adopted, attempt 2's producer would reuse attempt 1's
            # temp names and a detached (pipeline_stuck) attempt-1
            # producer could tear its files
            from .task_retry import adopt_attempt
            adopt_attempt(self._attempt)
            # the lifecycle context too (ISSUE 6): operators running
            # behind this boundary tick the consumer's cancellation
            # token, and nested blocking waits (semaphore, inner
            # stages) notice a cancelled query from this thread
            from . import lifecycle
            lifecycle.adopt_context(self._lctx)
            lifecycle.adopt_engagement(self._engaged)
            _tls.cancel_event = self._closed
            it = iter(self._source)
            while not self._closed.is_set():
                if self._lctx is not None and self._lctx.cancelled():
                    # cancelled query: stop starting new producer work.
                    # check() RAISES (caught below into self._exc, so
                    # the consumer re-raises at _END) — a bare break
                    # would post a clean end-of-stream and a truncated
                    # tail could read as normal completion (the same
                    # silent-truncation class the PR 3 StageCancelled
                    # fix closed for stage-close cuts)
                    self._lctx.check("compute")
                try:
                    # chaos fault point — engine operator stages only:
                    # emit_events=False stages (tools/pipeline_bench run
                    # in-process by bench.py) are synthetic, and a fault
                    # injected there would corrupt the bench's pipeline
                    # summary instead of exercising any recovery path
                    if self._emit_events:
                        # keyed by stage label: each stage draws its own
                        # deterministic injection sequence regardless of
                        # how the OS interleaves producer threads
                        faults.check("pipeline.produce", key=self._label)
                    item = next(it)
                except StopIteration:
                    break
                t0 = time.perf_counter_ns()
                if not self._offer(item):
                    break
                self.full_ns += time.perf_counter_ns() - t0
        except BaseException as e:  # noqa: BLE001 — carried to consumer
            self._exc = e
        finally:
            if self._exc is None and not self._closed.is_set() \
                    and self._lctx is not None and self._lctx.cancelled():
                # the loop exited via an _offer() that noticed the
                # cancellation (returned False on a full queue): the
                # stream IS truncated, so _END must not read as normal
                # completion — carry the cancellation to the consumer.
                # Derived via check() (review r3), not hand-built: the
                # shared path emits the ONE query_cancelled event and
                # bumps the lifecycle counter like every other checker.
                try:
                    self._lctx.check("compute")
                except BaseException as e:  # noqa: BLE001 — the
                    self._exc = e           # cancellation itself
            if it is not None and (
                    self._closed.is_set()
                    or (self._lctx is not None and self._lctx.cancelled())):
                # early shutdown (stage closed, or the governed query
                # was cancelled and this loop broke out): close the
                # abandoned source so its finally blocks (spillable
                # handles, shuffle files) run
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001 — teardown only
                        pass
            self._offer(_END)

    def _offer(self, item: Any) -> bool:
        """put() that a consumer-side close() can always unblock."""
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                if self._lctx is not None and self._lctx.cancelled():
                    # a cancelled query's consumer stopped draining:
                    # don't park on its full queue until close() lands
                    return False
                continue
        return False

    # -- consumer ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter_ns()
        while True:
            try:
                item = self._q.get(timeout=_POLL_S)
                break
            except queue.Empty:
                # lifecycle governor: a consumer parked on an empty
                # queue is exactly where a stalled producer wedges a
                # query — the deadline/cancel token is checked here so
                # an expired query unwinds with phase attribution
                # instead of waiting out the stall
                from . import lifecycle
                lifecycle.check_current("pipeline-wait")
                if cancelled():
                    # this consumer IS an outer stage's producer and
                    # that stage was closed: stop pulling so the outer
                    # close() can join. The outer producer's teardown
                    # closes our source generator (and through it, this
                    # stage) — without this check, nested stages could
                    # wedge an abandoning close() forever. Raised as an
                    # error, not StopIteration: a materializing consumer
                    # (CachedRelation) must not mistake the cut stream
                    # for a complete one.
                    dt = time.perf_counter_ns() - t0
                    self.wait_ns += dt
                    obs_phase.add("pipeline-stall", dt)
                    raise StageCancelled(self._label)
        dt = time.perf_counter_ns() - t0
        self.wait_ns += dt
        # phase attribution (ISSUE 17): the consumer's blocked-on-
        # producer time IS the budget producer-thread accruals fold
        # into (obs/phase.PhaseLedger.snapshot)
        obs_phase.add("pipeline-stall", dt)
        if item is _END:
            self._finished = True
            self._thread.join()
            self._finish_stats()
            if self._exc is not None:
                exc, self._exc = self._exc, None
                # re-raise the producer's error AT THE CONSUMER call
                # site; the original producer traceback travels on
                # exc.__traceback__
                raise exc
            raise StopIteration
        self.batches += 1
        return item

    def close(self) -> None:
        """Shut the stage down (idempotent): unblock + join the
        producer, drain the queue, report stats. Safe to call whether
        the stage finished, failed, or was abandoned mid-stream.

        Watchdog (ISSUE 4): a producer wedged somewhere cancellation
        can't reach (a blocking C call, a deadlocked external resource)
        must not hang query teardown or interpreter exit — after
        spark.rapids.tpu.pipeline.closeTimeoutMs the stage gives up,
        emits `pipeline_stuck`, and detaches the (daemon) thread."""
        self._closed.set()
        self._drain()
        deadline = time.monotonic() + self._close_timeout_s
        while self._thread.is_alive():
            if time.monotonic() >= deadline:
                self.stuck = True
                from ..obs import events as obs_events
                obs_events.emit(
                    "pipeline_stuck", stage=self._label,
                    timeout_ms=int(self._close_timeout_s * 1000))
                break
            self._thread.join(timeout=_POLL_S)
            self._drain()
        self._finished = True
        self._finish_stats()

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def _finish_stats(self) -> None:
        if self._stats_done:
            return
        self._stats_done = True
        self.wall_ns = time.perf_counter_ns() - self._t0
        if self._wait_metric is not None:
            self._wait_metric.add(self.wait_ns)
        if self._full_metric is not None:
            self._full_metric.add(self.full_ns)
        if self._wall_metric is not None:
            self._wall_metric.add(self.wall_ns)
        if not self._emit_events:
            return
        from ..obs import events as obs_events
        bus = obs_events.active_bus()
        if bus is not None:
            bus.emit("pipeline_wait", stage=self._label,
                     wait_ns=self.wait_ns, wall_ns=self.wall_ns,
                     batches=self.batches)
            bus.emit("pipeline_full", stage=self._label,
                     full_ns=self.full_ns, batches=self.batches)
