"""Concurrent workload governor (ISSUE 7 tentpole) — fair admission,
per-query memory quotas, overload shedding.

Every prior robustness layer (chaos recovery lanes, the lifecycle
governor) is scoped to ONE query; N concurrent sessions race the shared
device budget, spill catalog and admission semaphore with no fairness,
no quota and no backpressure. The reference engine leans on Spark's
scheduler + YARN/K8s admission for this; production query platforms
treat admission control and memory oversubscription as first-class
(Theseus's data-movement-aware scheduling under oversubscribed GPU
memory, Sparkle's contention management on large shared executors).
Standalone, this module is that layer:

* **Admission** — `admitted()` wraps every governed collect. At most
  `spark.rapids.tpu.workload.maxConcurrentQueries` queries run; up to
  `workload.queueDepth` more wait in the queue, granted in
  priority-then-FIFO order (PRIORITIES: interactive before batch) with
  aging — every AGING_EVERY-th grant goes to the OLDEST waiter
  regardless of class, so batch can never starve behind a steady
  interactive stream. The PR 6 deadline spans queue wait (the
  QueryContext is installed before admission), `cancel_query()`
  dequeues a queued query, and a cancellation noticed here carries the
  `admission-wait` phase.

* **Per-query memory quotas** — each admitted query gets a soft share
  of the device budget: max(budget * memoryQuotaFraction,
  budget / admitted_count), rebalanced as queries finish. The budget
  manager (memory/budget.py) consults it on the PRESSURE path only: an
  over-quota query spills ITS OWN catalog entries first (quota_spill
  event) and surfaces remaining pressure as its own TpuRetryOOM —
  its spill/split retry lane pays, not a neighbor's working set.
  Tickets ride the QueryContext, so pipeline producer threads inherit
  them with adopt_context like conf/query-id/attempt.

* **Overload shedding** — queue-full, admission-timeout and
  known-degraded-device (an open `device_dispatch` breaker) arrivals
  fail FAST with QueryAdmissionError (classified fatal — task retry
  must not burn attempts re-asking a saturated engine) carrying a
  `retry_after_ms` hint. `TpuSession.health()` reports queue depth,
  admitted count and the shed counters.

Disabled (`spark.rapids.tpu.workload.enabled`, default false) the whole
module costs one conf read per collect and nothing per batch.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

#: admission states a query moves through (docs/robustness.md table is
#: lint-checked against this, like the breaker tables)
ADMISSION_STATES = ("queued", "admitted", "shed", "cancelled", "released")

#: priority class -> rank (lower = preferred). The admission queue and
#: the device semaphore both order waiters by (rank, FIFO seq); the
#: docs table is lint-checked against this registry.
PRIORITIES: Dict[str, int] = {"interactive": 0, "batch": 1}

#: aging cadence shared by admission and the semaphore: every
#: AGING_EVERY-th grant picks the OLDEST waiter regardless of priority
#: class — the deterministic no-starvation guarantee (a batch waiter is
#: granted within AGING_EVERY * queue-length grants, worst case)
AGING_EVERY = 4


class QueryAdmissionError(RuntimeError):
    """The workload governor refused to start this query (queue full,
    admission timeout, or a known-degraded device). Classified `fatal`
    by faults.classify — retrying immediately would re-ask a saturated
    engine; `retry_after_ms` is the earliest sensible resubmit hint."""

    def __init__(self, msg: str, reason: str = "queue_full",
                 retry_after_ms: int = 0):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = retry_after_ms


def priority_rank(name: str) -> int:
    return PRIORITIES.get(str(name).strip().lower(),
                          PRIORITIES["interactive"])


def pick_fair(items, grants: int, rank, seq):
    """THE priority-then-FIFO-with-aging selection rule, shared by the
    admission queue and the device semaphore's permit pool (fairness
    must hold identically at both gates — docs/robustness.md): normally
    min (rank, seq); every AGING_EVERY-th grant the oldest item
    outright, so the lower class cannot starve. `rank`/`seq` are
    accessors over the waiter type. Returns None when empty."""
    if not items:
        return None
    if grants % AGING_EVERY == AGING_EVERY - 1:
        return min(items, key=seq)
    return min(items, key=lambda x: (rank(x), seq(x)))


class Ticket:
    """One query's admission record. `device_bytes` is the quota
    accounting surface — charged/discharged by the buffer catalog as
    entries it owns move on/off the DEVICE tier. `quota_frac` is
    captured from the ADMITTING conf (the reserving thread's
    active_conf may be unrelated — the same class of bug
    _max_concurrent guards release() against)."""

    _ids = itertools.count(1)

    __slots__ = ("ticket_id", "priority", "rank", "state", "seq",
                 "enqueued_at", "device_bytes", "quota_frac")

    def __init__(self, priority: str = "interactive", seq: int = 0,
                 quota_frac: float = 0.5):
        self.ticket_id = next(Ticket._ids)
        self.priority = priority if priority in PRIORITIES \
            else "interactive"
        self.rank = PRIORITIES[self.priority]
        self.state = "queued"
        self.seq = seq
        self.enqueued_at = time.monotonic()
        self.device_bytes = 0
        self.quota_frac = quota_frac


class WorkloadManager:
    """Process-wide admission queue + quota bookkeeping. All state under
    one condition; grants happen inside `_pump_locked` whenever a slot
    frees or an arrival finds one open."""

    def __init__(self):
        self._cond = threading.Condition()
        self._queued: List[Ticket] = []
        self._admitted: List[Ticket] = []
        self._seq = itertools.count(1)
        self._grants = 0
        #: the admission cap of the most recent admit() — release()
        #: pumps with THIS, not the releasing thread's active_conf():
        #: bench lanes admit with a conf never installed thread-locally,
        #: and a mismatched cap would over-admit past the configured
        #: slots or leave freed slots to the waiters' 50ms self-poll
        self._max_concurrent = 4
        self._counters: Dict[str, int] = {
            "queued": 0, "admitted": 0, "shed": 0,
            "cancelled_in_queue": 0, "quota_spills": 0,
        }

    # -- fair ordering -----------------------------------------------------
    def _pick_next(self) -> Optional[Ticket]:
        """Next queued ticket under the shared weighted-fair-with-aging
        rule (pick_fair)."""
        return pick_fair(self._queued, self._grants,
                         rank=lambda t: t.rank, seq=lambda t: t.seq)

    def _pump_locked(self, max_concurrent: int,
                     pending: List[tuple]) -> None:
        """Grant queued tickets while slots are free (caller holds the
        condition). Events are APPENDED to `pending`, not emitted: the
        condition also serializes the per-batch charge/discharge hot
        path, so event-bus file I/O must happen after the caller
        releases it (_flush)."""
        granted = False
        while len(self._admitted) < max_concurrent:
            t = self._pick_next()
            if t is None:
                break
            self._queued.remove(t)
            self._grants += 1
            t.state = "admitted"
            self._admitted.append(t)
            self._counters["admitted"] += 1
            granted = True
            pending.append(("query_admitted", dict(
                priority=t.priority,
                wait_ms=int((time.monotonic() - t.enqueued_at) * 1000),
                admitted=len(self._admitted),
                queued=len(self._queued))))
        if granted:
            self._cond.notify_all()

    @staticmethod
    def _flush(pending: List[tuple]) -> None:
        """Emit buffered (kind, fields) events — always OUTSIDE the
        condition."""
        if not pending:
            return
        from ..obs import events as obs_events
        for kind, fields in pending:
            obs_events.emit(kind, **fields)
        pending.clear()

    # -- admission ---------------------------------------------------------
    def admit(self, conf, ctx=None) -> Ticket:
        """Block until this query is admitted, or shed it. `ctx` is the
        governing QueryContext (deadline + cancellation span the queue
        wait); None runs admission without cancellation (bench lanes
        driving exec trees directly)."""
        from ..config import (WORKLOAD_ADMISSION_TIMEOUT_MS,
                              WORKLOAD_MAX_CONCURRENT,
                              WORKLOAD_MEMORY_QUOTA_FRACTION,
                              WORKLOAD_PRIORITY, WORKLOAD_QUEUE_DEPTH)
        max_concurrent = max(1, conf.get(WORKLOAD_MAX_CONCURRENT))
        queue_depth = max(0, conf.get(WORKLOAD_QUEUE_DEPTH))
        timeout_ms = max(0, conf.get(WORKLOAD_ADMISSION_TIMEOUT_MS))
        priority = conf.get(WORKLOAD_PRIORITY)
        quota_frac = conf.get(WORKLOAD_MEMORY_QUOTA_FRACTION)
        # shed BEFORE queueing into a known-degraded device: an open
        # device_dispatch breaker means dispatches are currently dying —
        # admitting would spend this query's whole retry budget on them.
        # Read-only consult (no half-open transition: recovery probes
        # belong to already-running attempts, not to admission).
        from . import lifecycle
        cooldown_ms = lifecycle.breaker_shed_hint_ms("device_dispatch",
                                                     conf)
        t_adm0 = time.monotonic_ns()
        pending: List[tuple] = []
        try:
            if cooldown_ms is not None:
                self._shed("breaker_open", cooldown_ms, priority, None,
                           pending)
            with self._cond:
                self._max_concurrent = max_concurrent
                t = Ticket(priority, seq=next(self._seq),
                           quota_frac=quota_frac)
                if len(self._admitted) < max_concurrent \
                        and not self._queued:
                    # free slot, empty queue: grant through the one
                    # shared path (no queue residency — wait_ms ~0)
                    self._queued.append(t)
                    self._pump_locked(max_concurrent, pending)
                    assert t.state == "admitted"
                    if ctx is not None:
                        ctx.phase = "admitted"
                    return t
                if len(self._queued) >= queue_depth:
                    # "come back after roughly one admission turn" —
                    # the admission TIMEOUT is a queue-wait bound, not
                    # a queue-full backoff; don't conflate them
                    self._shed("queue_full", 100, priority, t, pending)
                if ctx is not None and ctx.deadline is not None \
                        and ctx.deadline - time.monotonic() <= 0:
                    # the query's whole wall-clock budget is already
                    # gone: queueing could only hand a dead query a slot
                    self._shed("deadline_infeasible", 100, priority, t,
                               pending)
                self._queued.append(t)
                self._counters["queued"] += 1
                if ctx is not None:
                    ctx.phase = "queued"
                pending.append(("query_queued", dict(
                    priority=t.priority, queued=len(self._queued),
                    admitted=len(self._admitted))))
            deadline = (time.monotonic() + timeout_ms / 1000.0
                        if timeout_ms else None)
            while True:
                # each 50ms turn re-enters the condition for the checks
                # and exits to flush — buffered events (incl. grants
                # this waiter's pump handed to OTHERS) never sit behind
                # a parked wait
                self._flush(pending)
                with self._cond:
                    try:
                        self._pump_locked(max_concurrent, pending)
                        if t.state != "queued":
                            if ctx is not None:
                                ctx.phase = "admitted"
                            break
                        if deadline is not None \
                                and time.monotonic() >= deadline:
                            # the wait already proved the queue moves
                            # slower than the configured bound
                            self._shed("timeout",
                                       max(timeout_ms, 100), priority,
                                       t, pending)
                        if ctx is not None:
                            # deadline expiry / cancel_query() while
                            # queued: raises QueryCancelledError with
                            # admission-wait phase attribution
                            ctx.check("admission-wait")
                        self._cond.wait(0.05)
                    except BaseException:
                        if t in self._queued:
                            self._queued.remove(t)
                        if t.state == "queued":
                            t.state = "cancelled"
                            self._counters["cancelled_in_queue"] += 1
                        elif t.state == "admitted":
                            # another thread's pump granted t while an
                            # async exception (KeyboardInterrupt) was
                            # landing in wait(): the caller never sees
                            # the ticket, so release() would never run
                            # — free the slot now or it leaks for the
                            # process lifetime
                            if t in self._admitted:
                                self._admitted.remove(t)
                            t.state = "released"
                            self._pump_locked(max_concurrent, pending)
                        self._cond.notify_all()
                        raise
            # phase attribution (ISSUE 17): the queue residency this
            # slow path just sat out is the query's admission-wait
            # share (the fast-path grant above never queues — ~0 wait)
            from ..obs import phase as obs_phase
            obs_phase.add("admission-wait",
                          time.monotonic_ns() - t_adm0)
            return t
        finally:
            self._flush(pending)

    def _shed(self, reason: str, retry_after_ms: int, priority: str,
              ticket: Optional[Ticket], pending: List[tuple]) -> None:
        """THE shed path: counter + ticket state + buffered event +
        raise, in one place (a reason added later cannot miss one of
        the side effects). Safe with or without the condition held —
        it is re-entrant; the event lands in `pending` and the caller's
        finally emits it outside the lock."""
        with self._cond:
            self._counters["shed"] += 1
            if ticket is not None:
                ticket.state = "shed"
        pending.append(("query_shed", dict(
            reason=reason, priority=priority,
            retry_after_ms=retry_after_ms)))
        raise QueryAdmissionError(
            f"query admission shed ({reason}); retry after "
            f"~{retry_after_ms}ms", reason=reason,
            retry_after_ms=retry_after_ms)

    def release(self, ticket: Ticket) -> None:
        """Query end (success, failure or cancellation): free the slot,
        rebalance quotas, grant the next fair waiter — under the cap
        the queries were ADMITTED with (the releasing thread's
        active_conf may be unrelated, e.g. a bench lane thread)."""
        pending: List[tuple] = []
        with self._cond:
            if ticket in self._admitted:
                self._admitted.remove(ticket)
            elif ticket in self._queued:  # defensive: never left queued
                self._queued.remove(ticket)
            ticket.state = "released"
            self._pump_locked(self._max_concurrent, pending)
            self._cond.notify_all()
        self._flush(pending)

    # -- quotas ------------------------------------------------------------
    def quota_bytes(self, limit: int, frac: float) -> Optional[int]:
        """The soft per-admitted-query device share right now, or None
        when unlimited (nothing admitted). A lone query always gets the
        whole budget; shares grow back as neighbors finish."""
        with self._cond:
            n = len(self._admitted)
        if n <= 1:
            return None
        return max(int(limit * frac), limit // n)

    def note_quota_spill(self) -> None:
        with self._cond:
            self._counters["quota_spills"] += 1

    # -- accounting / surfaces ---------------------------------------------
    def charge(self, ticket: Optional[Ticket], nbytes: int) -> None:
        if ticket is None:
            return
        with self._cond:
            ticket.device_bytes += nbytes

    def discharge(self, ticket: Optional[Ticket], nbytes: int) -> None:
        if ticket is None:
            return
        with self._cond:
            ticket.device_bytes = max(0, ticket.device_bytes - nbytes)

    def counters(self) -> Dict[str, int]:
        with self._cond:
            return dict(self._counters)

    def snapshot(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "queue_depth": len(self._queued),
                "admitted": len(self._admitted),
                "counters": dict(self._counters),
            }

    def queued_count(self) -> int:
        with self._cond:
            return len(self._queued)

    def admitted_count(self) -> int:
        with self._cond:
            return len(self._admitted)


_manager: Optional[WorkloadManager] = None
_manager_lock = threading.Lock()


def manager() -> WorkloadManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = WorkloadManager()
        return _manager


def reset_workload() -> WorkloadManager:
    """Test isolation (the conftest module tripwire)."""
    global _manager
    with _manager_lock:
        _manager = WorkloadManager()
        return _manager


@contextlib.contextmanager
def admitted(conf=None, ctx=None) -> Iterator[Optional[Ticket]]:
    """Admission around one driven query. With the governor disabled
    (spark.rapids.tpu.workload.enabled=false, the default) this is one
    conf read and no ticket. The ticket rides the QueryContext so every
    thread serving the query (pipeline producers adopt the context)
    resolves the same quota accounting."""
    from ..config import WORKLOAD_ENABLED, active_conf
    conf = conf if conf is not None else active_conf()
    if not conf.get(WORKLOAD_ENABLED):
        yield None
        return
    from . import lifecycle
    if ctx is None:
        ctx = lifecycle.current_context()
    ticket = manager().admit(conf, ctx)
    if ctx is not None:
        ctx.workload_ticket = ticket
    try:
        yield ticket
    finally:
        if ctx is not None:
            ctx.workload_ticket = None
        manager().release(ticket)


def current_ticket() -> Optional[Ticket]:
    """The admitted ticket of this thread's governed query (None when
    ungoverned or the governor is off) — resolved through the
    QueryContext, so producer threads inherit it with adopt_context."""
    from . import lifecycle
    ctx = lifecycle.current_context()
    if ctx is None:
        return None
    return getattr(ctx, "workload_ticket", None)


def current_priority_rank() -> int:
    """Semaphore-waiter ordering hook: the rank of this thread's
    query's priority class (interactive when ungoverned)."""
    t = current_ticket()
    return t.rank if t is not None else PRIORITIES["interactive"]


def charge(ticket: Optional[Ticket], nbytes: int) -> None:
    """Catalog hook: `nbytes` of device budget now attributed to
    `ticket`'s query (mirrors every memory_budget().reserve a catalog
    entry makes). None-ticket is the disabled/ungoverned fast path."""
    if ticket is not None:
        manager().charge(ticket, nbytes)


def discharge(ticket: Optional[Ticket], nbytes: int) -> None:
    """Catalog hook: device budget released for `ticket`'s query."""
    if ticket is not None:
        manager().discharge(ticket, nbytes)


def quota_bytes(limit: int) -> Optional[int]:
    """The current thread's query's soft device share of `limit`, or
    None (no quota: governor off, query ungoverned, fraction <= 0, or
    it is the only admitted query). Consulted by memory/budget.py on
    the pressure path only; the fraction is the one the query was
    ADMITTED with (Ticket.quota_frac)."""
    t = current_ticket()
    if t is None or _manager is None or t.quota_frac <= 0:
        return None
    return _manager.quota_bytes(limit, t.quota_frac)


def note_quota_spill(ticket: Ticket, need: int, quota: int,
                     freed: int) -> None:
    """An over-quota query under budget pressure spilled its own
    working set: one quota_spill event + counter."""
    manager().note_quota_spill()
    from ..obs import events as obs_events
    obs_events.emit("quota_spill", need=need, quota=quota, freed=freed,
                    device_bytes=ticket.device_bytes,
                    priority=ticket.priority)


def counters() -> Dict[str, int]:
    """Process-cumulative workload counters (bench {"workload": ...}
    deltas + profile_report roll-up)."""
    m = _manager
    if m is None:
        return {"queued": 0, "admitted": 0, "shed": 0,
                "cancelled_in_queue": 0, "quota_spills": 0}
    return m.counters()


def snapshot() -> Dict[str, Any]:
    """The TpuSession.health() workload section."""
    m = _manager
    if m is None:
        return {"queue_depth": 0, "admitted": 0, "counters": counters()}
    return m.snapshot()
