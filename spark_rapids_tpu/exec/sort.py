"""SortExec — reference GpuSortExec.scala:86 (per-batch sort) +
GpuOutOfCoreSortIterator:281 (spill-backed merge) + GpuTopN (limit.scala:351).

TPU shape: each input batch sorts with one lax.sort over order-key lanes;
the merge phase concatenates sorted runs (spillable between steps) and
re-sorts — XLA's sort on mostly-sorted lanes is cheap, and every merge
re-uses the same compiled program per capacity bucket. TopN keeps only
`limit` rows after every step so device footprint stays bounded.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn, bucket_capacity
from ..expr.core import BoundReference, Expression, resolve
from ..memory.retry import split_in_half_by_rows, with_retry, with_retry_no_split
from ..memory.spillable import SpillableBatch
from ..ops.basic import slice_rows
from ..ops.sort import SortOrder, sort_batch_columns, string_words_for
from ..types import Schema
from .base import NUM_INPUT_BATCHES, SORT_TIME, TpuExec
from .coalesce import concat_batches


def resolve_sort_orders(orders: Sequence, schema: Schema) -> List[SortOrder]:
    """Accepts SortOrder (ordinal-based) or (Expression, asc, nulls_first)."""
    out = []
    for o in orders:
        if isinstance(o, SortOrder):
            out.append(o)
            continue
        expr, asc, nf = (o + (None,))[:3] if isinstance(o, tuple) else (o, True, None)
        bound = resolve(expr, schema)
        assert isinstance(bound, BoundReference), \
            "planner must pre-project computed sort keys"
        out.append(SortOrder(bound.ordinal, asc, nf))
    return out


class SortExec(TpuExec):
    def __init__(self, orders: Sequence, child: TpuExec,
                 limit: Optional[int] = None):
        super().__init__(child)
        self.orders = resolve_sort_orders(orders, child.output_schema)
        self.limit = limit
        # one compiled sort program per (capacity bucket, string words)
        self._jit_sort = jax.jit(self._sort_kernel, static_argnums=(1,))

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return (SORT_TIME, NUM_INPUT_BATCHES)

    def _string_words(self, batch: ColumnarBatch) -> int:
        return string_words_for(batch.columns,
                                [o.ordinal for o in self.orders])

    def _sort_kernel(self, batch: ColumnarBatch, words: int) -> ColumnarBatch:
        cols, _ = sort_batch_columns(batch.columns, self.orders,
                                     batch.num_rows, batch.capacity, words)
        return ColumnarBatch(cols, batch.num_rows, batch.schema)

    def _sort_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        words = self._string_words(batch)
        out = self._jit_sort(batch, words)
        out = ColumnarBatch(out.columns, batch.num_rows, batch.schema,
                            batch._host_rows)
        if self.limit is not None and batch.num_rows_host > self.limit:
            cols = [slice_rows(c, jnp.int32(0), jnp.int32(self.limit),
                               bucket_capacity(self.limit))
                    for c in out.columns]
            out = ColumnarBatch(cols, self.limit, batch.schema)
        return out

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        sort_time = self.metrics[SORT_TIME]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        runs: List[SpillableBatch] = []
        with sort_time.ns_timer():
            for batch in self.child.execute():
                in_batches.add(1)
                spillable = SpillableBatch.from_batch(batch)
                try:
                    for sorted_batch in with_retry(
                            spillable, self._sort_spillable,
                            split_policy=split_in_half_by_rows):
                        runs.append(SpillableBatch.from_batch(sorted_batch))
                finally:
                    spillable.close()
            if not runs:
                return
            if len(runs) == 1:
                only = runs[0]
                batch = only.get_batch()
                only.release()
                only.close()
                yield batch
                return
            # merge: concat all runs, one final sort. Out-of-core behavior
            # comes from runs being spillable and with_retry splitting the
            # merge set when it cannot fit.
            yield self._merge(runs)

    def _sort_spillable(self, s: SpillableBatch) -> ColumnarBatch:
        batch = s.get_batch()
        try:
            return self._sort_one(batch)
        finally:
            s.release()

    def _merge(self, runs: List[SpillableBatch]) -> ColumnarBatch:
        def do(items):
            batches = [s.get_batch() for s in items]
            try:
                merged = concat_batches(batches, self.output_schema)
                return self._sort_one(merged)
            finally:
                for s in items:
                    s.release()
        try:
            return with_retry_no_split(runs, do)
        finally:
            for s in runs:
                s.close()

    def node_description(self):
        lim = f", limit={self.limit}" if self.limit is not None else ""
        return f"SortExec[{self.orders}{lim}]"


class TopNExec(SortExec):
    """GpuTopN (limit.scala:351): sort+limit per batch, merge keeps `limit`."""

    def __init__(self, limit: int, orders: Sequence, child: TpuExec,
                 offset: int = 0):
        super().__init__(orders, child, limit=limit + offset)
        self.offset = offset

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        for batch in super().internal_execute():
            if self.offset:
                n = max(0, batch.num_rows_host - self.offset)
                cols = [slice_rows(c, jnp.int32(self.offset), jnp.int32(n),
                                   batch.capacity) for c in batch.columns]
                batch = ColumnarBatch(cols, n, batch.schema)
            yield batch
