"""SortExec — reference GpuSortExec.scala:86 (per-batch sort) +
GpuOutOfCoreSortIterator:281 (spill-backed merge) + GpuTopN (limit.scala:351).

TPU shape: each input batch sorts with one lax.sort over order-key lanes.
Small merges concatenate all runs and re-sort (XLA sort on mostly-sorted
lanes is cheap). Big merges go out-of-core: runs stay spilled; a streamed
k-way merge keeps only MERGE_FAN_IN chunk heads device-resident, emits
every row that is provably globally final (lexicographically <= the
smallest not-yet-loaded key, compared on the sort's own order-key lanes),
and spills intermediate runs between passes — device footprint is bounded
by fan-in × chunk size regardless of input size.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn, bucket_capacity
from ..expr.core import BoundReference, Expression, resolve
from ..memory.retry import split_in_half_by_rows, with_retry, with_retry_no_split
from ..memory.spillable import SpillableBatch
from ..ops.basic import active_mask, slice_rows
from ..ops.sort import (
    SortOrder, order_key_lanes, sort_batch_columns, string_words_for,
)
from ..types import Schema
from .base import (DEBUG, DISPATCH_METRICS, GATHER_METRICS, GATHER_TIME,
                   NUM_GATHERS, NUM_INPUT_BATCHES, SORT_TIME, TpuExec)
from .coalesce import concat_batches


def _lex_leq(lanes: List, bound: List):
    """Per-row: lane tuple <= bound tuple (lexicographic, device)."""
    less = jnp.zeros(lanes[0].shape, jnp.bool_)
    eq = jnp.ones(lanes[0].shape, jnp.bool_)
    for lane, b in zip(lanes, bound):
        less = less | (eq & (lane < b))
        eq = eq & (lane == b)
    return less | eq


def _lex_less_scalar(a: List, b: List):
    less = jnp.asarray(False)
    eq = jnp.asarray(True)
    for x, y in zip(a, b):
        less = less | (eq & (x < y))
        eq = eq & (x == y)
    return less


def resolve_sort_orders(orders: Sequence, schema: Schema) -> List[SortOrder]:
    """Accepts SortOrder (ordinal-based) or (Expression, asc, nulls_first)."""
    out = []
    for o in orders:
        if isinstance(o, SortOrder):
            out.append(o)
            continue
        expr, asc, nf = (o + (None,))[:3] if isinstance(o, tuple) else (o, True, None)
        bound = resolve(expr, schema)
        assert isinstance(bound, BoundReference), \
            "planner must pre-project computed sort keys"
        out.append(SortOrder(bound.ordinal, asc, nf))
    return out


class SortExec(TpuExec):
    def __init__(self, orders: Sequence, child: TpuExec,
                 limit: Optional[int] = None):
        super().__init__(child)
        self.orders = resolve_sort_orders(orders, child.output_schema)
        self.limit = limit
        # one compiled sort program per (capacity bucket, string words);
        # the site is plan-fingerprint cached (ISSUE 14) so a rebuilt
        # identical plan reuses it across collects
        self._jit_sort = self._site(self._sort_kernel,
                                    label="SortExec.sort",
                                    static_argnums=(1,))
        # round 8: fixed-width columns ride INSIDE lax.sort as packed
        # lanes, so numGathers here counts only the varlen columns'
        # permutation gathers — the structural proof the sort path needs
        # no row gathers for fixed-width batches
        from ..ops.gather import GatherTracker
        self._gather_track = GatherTracker(self.metrics[NUM_GATHERS],
                                           self.metrics[GATHER_TIME])

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return (SORT_TIME, (NUM_INPUT_BATCHES, DEBUG)) + GATHER_METRICS \
            + DISPATCH_METRICS

    def _fingerprint_extras(self):
        return (tuple((o.ordinal, o.ascending, o.nulls_first)
                      for o in self.orders), self.limit)

    def _string_words(self, batch: ColumnarBatch) -> int:
        return string_words_for(batch.columns,
                                [o.ordinal for o in self.orders])

    def _sort_kernel(self, batch: ColumnarBatch, words: int) -> ColumnarBatch:
        cols, _ = sort_batch_columns(batch.columns, self.orders,
                                     batch.num_rows, batch.capacity, words)
        return ColumnarBatch(cols, batch.num_rows, batch.schema)

    def _sort_one(self, batch: ColumnarBatch) -> ColumnarBatch:
        words = self._string_words(batch)
        with self._gather_track.observe((batch.capacity, words)):
            out = self._jit_sort(batch, words)
        out = ColumnarBatch(out.columns, batch.num_rows, batch.schema,
                            batch._host_rows)
        if self.limit is not None:
            # device-side min(rows, limit): the old num_rows_host check
            # cost a ~100 ms tunnel sync per batch (round 4)
            n = jnp.minimum(batch.num_rows, jnp.int32(self.limit))
            if batch.capacity > bucket_capacity(self.limit):
                cols = [slice_rows(c, jnp.int32(0), n,
                                   bucket_capacity(self.limit))
                        for c in out.columns]
            else:
                from ..ops.basic import sanitize
                cols = [sanitize(c, n) for c in out.columns]
            out = ColumnarBatch(cols, n, batch.schema)
        return out

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        try:
            yield from self._execute_sort()
        finally:
            self._gather_track.emit_event(type(self).__name__,
                                          self._op_id)

    def _execute_sort(self) -> Iterator[ColumnarBatch]:
        sort_time = self.metrics[SORT_TIME]
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        runs: List[SpillableBatch] = []
        with sort_time.ns_timer():
            for batch in self.child.execute():
                in_batches.add(1)
                spillable = SpillableBatch.from_batch(batch)
                try:
                    for sorted_batch in with_retry(
                            spillable, self._sort_spillable,
                            split_policy=split_in_half_by_rows):
                        runs.append(SpillableBatch.from_batch(sorted_batch))
                finally:
                    spillable.close()
            if not runs:
                return
            if len(runs) == 1:
                only = runs[0]
                batch = only.get_batch()
                only.release()
                only.close()
                yield batch
                return
            from ..config import SORT_OOC_ENABLED, active_conf
            if (self.limit is None and len(runs) > self.MERGE_FAN_IN
                    and active_conf().get(SORT_OOC_ENABLED)):
                # big merge: bounded-memory streamed k-way merge over
                # spilled runs (GpuOutOfCoreSortIterator analog)
                yield from self._merge_out_of_core([[r] for r in runs])
                return
            # small merge: concat all runs, one final sort; with_retry
            # splits the merge set under OOM
            yield self._merge(runs)

    def _sort_spillable(self, s: SpillableBatch) -> ColumnarBatch:
        batch = s.get_batch()
        try:
            return self._sort_one(batch)
        finally:
            s.release()

    def _merge(self, runs: List[SpillableBatch]) -> ColumnarBatch:
        def do(items):
            batches = [s.get_batch() for s in items]
            try:
                merged = concat_batches(batches, self.output_schema)
                return self._sort_one(merged)
            finally:
                for s in items:
                    s.release()
        try:
            return with_retry_no_split(runs, do)
        finally:
            for s in runs:
                s.close()

    #: runs merged per streaming pass; device footprint is bounded by
    #: ~2 × MERGE_FAN_IN × chunk capacity
    MERGE_FAN_IN = 8

    def _merge_out_of_core(self, run_lists: List[List[SpillableBatch]]
                           ) -> Iterator[ColumnarBatch]:
        """Multi-pass streamed merge: each pass merges groups of
        MERGE_FAN_IN runs, spilling the merged chunks; the final pass
        streams directly to the consumer."""
        fan = self.MERGE_FAN_IN
        live: List[List[SpillableBatch]] = run_lists
        nxt: List[List[SpillableBatch]] = []
        try:
            while len(live) > fan:
                nxt = []
                for g in range(0, len(live), fan):
                    group = live[g:g + fan]
                    if len(group) == 1:
                        nxt.append(group[0])
                        continue
                    merged = [SpillableBatch.from_batch(b)
                              for b in self._stream_merge(group)]
                    nxt.append(merged)
                live, nxt = nxt, []
            if len(live) == 1:
                for s in list(live[0]):
                    b = s.get_batch()
                    s.release()
                    s.close()
                    live[0].pop(0)
                    yield b
                return
            yield from self._stream_merge(live)
        finally:
            # error or early consumer abandonment: close everything left —
            # the current pass's inputs AND any merged runs already
            # produced into the next pass
            for r in live + nxt:
                for s in r:
                    s.close()

    def _stream_merge(self, group: List[List[SpillableBatch]]
                      ) -> Iterator[ColumnarBatch]:
        """Streamed k-way merge of sorted chunked runs.

        Invariant: a row may be emitted once it is lexicographically <=
        the loaded maximum of every run that still has unloaded chunks —
        any future row of run r is >= r's loaded max. Each head keeps its
        unemittable suffix device-resident; exhausted heads refill from
        their spilled queue. One small host sync (per-head emit counts)
        per loaded chunk."""
        # consume the caller's run lists IN PLACE so an abandoned or
        # failed merge leaves exactly the unconsumed spillables for the
        # caller's finally-close
        queues = group
        heads: List[Optional[ColumnarBatch]] = [None] * len(queues)
        # emitted chunks re-split to the input chunk bucket so chunk size
        # stays constant across merge passes (the memory bound depends on
        # it: footprint <= ~2 × fan-in × chunk)
        from ..columnar.column import bucket_capacity as _bc
        chunk_cap = max((_bc(max(int(s.num_rows), 1))
                         for q in queues for s in q), default=0) or 128

        def emit(batch: ColumnarBatch) -> Iterator[ColumnarBatch]:
            n = batch.num_rows_host
            if n <= chunk_cap:
                yield batch
                return
            for start in range(0, n, chunk_cap):
                m = min(chunk_cap, n - start)
                cols = [slice_rows(c, jnp.int32(start), jnp.int32(m),
                                   chunk_cap) for c in batch.columns]
                yield ColumnarBatch(cols, m, batch.schema)

        # per-head lane cache: lanes only recompute when a head changes
        # (refill/slice) or the global string-word width grows — unchanged
        # heads are byte-identical across rounds (review finding r1)
        lane_cache: dict = {}
        words_cache: dict = {}
        words = 1
        while True:
            for i, q in enumerate(queues):
                if heads[i] is None and q:
                    s = q.pop(0)
                    heads[i] = s.get_batch()
                    s.release()
                    s.close()
                    lane_cache.pop(i, None)
                    words_cache[i] = self._string_words(heads[i])
            live = [i for i, h in enumerate(heads) if h is not None]
            if not live:
                return
            constrainers = [i for i in live if queues[i]]
            if not constrainers:
                # everything is loaded: final merge of the remaining heads
                batches = [heads[i] for i in live]
                merged = concat_batches(batches, self.output_schema) \
                    if len(batches) > 1 else batches[0]
                yield from emit(self._sort_one(merged))
                return

            new_words = max(words_cache[i] for i in live)
            if new_words > words:
                lane_cache.clear()  # lane widths must agree across heads
                words = new_words
            for i in live:
                if i not in lane_cache:
                    lane_cache[i] = order_key_lanes(
                        heads[i].columns, self.orders, heads[i].num_rows,
                        heads[i].capacity, words)[1:]  # drop activity lane
            # bound: lexicographic min of constrainer heads' last rows
            bound = None
            for i in constrainers:
                h = heads[i]
                idx = jnp.clip(h.num_rows - 1, 0, h.capacity - 1)
                b = [lane[idx] for lane in lane_cache[i]]
                if bound is None:
                    bound = b
                else:
                    take = _lex_less_scalar(b, bound)
                    bound = [jnp.where(take, x, y)
                             for x, y in zip(b, bound)]

            emit_parts: List[ColumnarBatch] = []
            counts = []
            for i in live:
                h = heads[i]
                leq = _lex_leq(lane_cache[i], bound) \
                    & active_mask(h.num_rows, h.capacity)
                counts.append(jnp.sum(leq.astype(jnp.int32)))
            fetched = [int(c) for c in jax.device_get(counts)]
            for i, cnt in zip(live, fetched):
                h = heads[i]
                n = h.num_rows_host
                if cnt > 0:
                    cols = [slice_rows(c, jnp.int32(0), jnp.int32(cnt),
                                       bucket_capacity(max(cnt, 1)))
                            for c in h.columns]
                    emit_parts.append(ColumnarBatch(cols, cnt, h.schema))
                if cnt >= n:
                    heads[i] = None  # fully emitted: refill next round
                    lane_cache.pop(i, None)
                elif cnt > 0:
                    rest = n - cnt
                    cols = [slice_rows(c, jnp.int32(cnt), jnp.int32(rest),
                                       bucket_capacity(max(rest, 1)))
                            for c in h.columns]
                    heads[i] = ColumnarBatch(cols, rest, h.schema)
                    lane_cache.pop(i, None)
            if emit_parts:
                merged = concat_batches(emit_parts, self.output_schema) \
                    if len(emit_parts) > 1 else emit_parts[0]
                yield from emit(self._sort_one(merged))

    def node_description(self):
        lim = f", limit={self.limit}" if self.limit is not None else ""
        return f"SortExec[{self.orders}{lim}]"


class TopNExec(SortExec):
    """GpuTopN (limit.scala:351): sort+limit per batch, merge keeps `limit`."""

    def __init__(self, limit: int, orders: Sequence, child: TpuExec,
                 offset: int = 0):
        super().__init__(orders, child, limit=limit + offset)
        self.offset = offset

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        for batch in super().internal_execute():
            if self.offset:
                n = max(0, batch.num_rows_host - self.offset)
                cols = [slice_rows(c, jnp.int32(self.offset), jnp.int32(n),
                                   batch.capacity) for c in batch.columns]
                batch = ColumnarBatch(cols, n, batch.schema)
            yield batch


class PartitionWiseSortExec(TpuExec):
    """Per-partition sort over a range exchange: the child yields one
    batch STREAM per partition (execute_partitions) in ascending bound
    order, so sorting each partition independently yields a GLOBALLY
    sorted stream (the reference's distributed sort: GpuRangePartitioner
    bounds + per-partition GpuSortExec). One inner SortExec is reused so
    compiled sort programs cache across partitions."""

    def __init__(self, orders: Sequence, child: TpuExec):
        super().__init__(child)
        from .basic import InMemoryScanExec
        self._scan = InMemoryScanExec([], child.output_schema)
        self._sort = SortExec(orders, self._scan)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        # partition boundaries come from execute_partitions (round 5:
        # exchanges stream a partition as MULTIPLE pieces — flat batches
        # no longer delimit partitions)
        for gen in self.child.execute_partitions():
            self._scan._batches = list(gen)
            yield from self._sort.execute()

    def node_description(self):
        return "PartitionWiseSortExec"
