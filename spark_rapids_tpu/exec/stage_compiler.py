"""Whole-stage compilation (ISSUE 14 tentpole) — one jitted program per
pipeline stage, with a plan-fingerprint program cache.

The engine dispatched one jitted program per operator per batch with
Python at every batch boundary — PR 13's dispatch ledger measured it:
q3 ran HashJoinExec at 3.0 + AggregateExec at 2.0 dispatches per output
batch, and every `DataFrame.collect()` rebuilt its exec tree and
recompiled the whole plan (~1.9s/collect on the scaled q1 CPU lane).
Flare (PAPERS.md) shows the per-operator interpretation overhead
collapses when stages compile to one native unit; XLA is our codegen.

Two halves, both gated by `spark.rapids.tpu.stage.fusion.enabled`:

1. **Stage planner** — `compile_stages(root)` walks the converted
   `TpuExec` tree top-down and greedily groups maximal chains of
   whitelisted operators into `CompiledStageExec` nodes:

   * ``map``: a Filter/Project/Expand chain (>= 2 ops) feeding a
     non-fusable consumer — per input batch ONE program evaluates every
     projection, ANDs every filter into one row mask and compacts ONCE
     (filters become masks, not gathers — the FilterExec.fused_step
     contract, now generalized past aggregates).
   * ``agg``: an AggregateExec (complete/partial, masked-bucket
     eligible) that already absorbed a filter/project chain — the
     stage drives the agg's one-program-per-batch streaming step with
     buffer DONATION on the carried state (donate_argnums: the fold's
     in-place HBM reuse) and the stage-boundary governance harness.
   * ``join_agg``: the flagship — filter -> inner-join probe ->
     project -> partial/complete aggregate as ONE program per stream
     batch: the build table is computed INSIDE the first fused
     dispatch and carried as program state, candidate sizing rides the
     join's speculative size-cache contract (cold execution: one
     standalone sizing program; warm: zero host syncs), and the
     probe's output never materializes between operators.

   Non-whitelisted operators (exchanges, sorts, windows, UDFs,
   generators, limits) break the stage and keep their per-op execs.

2. **Program cache** — exec program sites built through
   `TpuExec._site` carry a canonical plan-subtree fingerprint
   (`fingerprint_node`: node semantics x output schema x child
   fingerprints x trace-affecting conf digest x backend platform) as
   their `cache_key`; `obs.dispatch` then serves one process-wide
   `InstrumentedJit` per (label, fingerprint), so a reused plan's
   second collect() is ALL jit cache hits — zero fresh traces,
   measured by the PR 13 ledger. The same fingerprint is the seed for
   ROADMAP item 5's sub-plan result cache.

Governance at stage granularity (the enabling refactor ROADMAP 2 calls
out): compute bodies handed to the dispatch chokepoint are PURE traced
dataflow — the `stage-governance` analyzer rule enforces it — and the
per-batch hooks live in the stage-boundary harness
(`TpuExec.batch_harness` + the lifecycle tick in `TpuExec._drive`):
cooperative cancellation per batch, a keyed `device.dispatch` chaos
fault point per fused dispatch, gather/dispatch metric attribution
around the one program, and `device_dispatch` breaker engagement — an
OPEN breaker demotes the stage back to per-operator execution for that
run (PR 5 degradation, now at stage granularity).

CPU results are identical with fusion on or off (tier-1 asserted; the
spec-tier fold replays the exact same program composition, the exact
tier reuses the agg's own merge machinery). Donation is a no-op on CPU
backends; TPU rounds validate the donated-state fold — and must watch
the OOM-retry lane, where a failed donated dispatch's state buffer is
the documented open risk.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Dict, List, Optional, Tuple

from ..columnar.batch import ColumnarBatch
from ..types import Schema
from .base import (AGG_TIME, DISPATCH_METRICS, GATHER_METRICS,
                   GATHER_TIME, NUM_DISPATCHES, NUM_GATHERS, TpuExec)

__all__ = [
    "CompiledStageExec", "compile_stages", "fingerprint_node",
    "trace_conf_digest", "schema_sig", "counters",
    "reset_stage_counters", "FUSABLE_OPS",
]

#: the fusion whitelist (docs/perf.md's fusion-whitelist table is
#: lint-checked against these keys): operator class -> how it fuses
#: into a stage program. Everything else breaks the stage.
FUSABLE_OPS: Dict[str, str] = {
    "FilterExec": "row mask ANDed into the stage program (one "
                  "compaction per stage, not one gather per filter)",
    "ProjectExec": "expression evaluation inlined via the engine's own "
                   "columnar_eval compiler",
    "ExpandExec": "all projections emitted from ONE program per input "
                  "batch (grouping sets)",
    "HashJoinExec": "inner-join probe fused into the consuming "
                    "partial aggregate's per-stream-batch program; the "
                    "build table is computed inside the first fused "
                    "dispatch and carried as program state",
    "AggregateExec": "masked-bucket update + fold into donated carried "
                     "state (complete/partial modes), evaluate "
                     "in-program",
}


# ---------------------------------------------------------------------------
# process counters (bench `{"stage"}` block, the chaos-delta pattern)
# ---------------------------------------------------------------------------

_COUNTER_LOCK = threading.Lock()
_COUNTERS = {"stages_fused": 0, "ops_fused": 0, "executions": 0,
             "fallbacks": 0, "dispatches": 0, "batches": 0}


def _note(**deltas) -> None:
    with _COUNTER_LOCK:
        for k, v in deltas.items():
            _COUNTERS[k] += v


def counters() -> Dict[str, int]:
    """Stage-fusion process counters + the program-site cache's
    activity (obs/dispatch.py) — ONE surface for the bench block."""
    from ..obs import dispatch as obs_dispatch
    with _COUNTER_LOCK:
        out = dict(_COUNTERS)
    sc = obs_dispatch.site_cache_counters()
    out["cache_sites"] = sc["sites"]
    out["cache_hits"] = sc["hits"]
    return out


def reset_stage_counters() -> None:
    with _COUNTER_LOCK:
        for k in _COUNTERS:
            _COUNTERS[k] = 0
        _SIZE_CACHES.clear()


#: fingerprint -> {(stream_cap, build_cap): (cand_cap, s_caps,
#: b_caps)} — the join sizing caches shared across rebuilt identical
#: plans; LRU-capped so distinct plans cannot grow it unboundedly
_SIZE_CACHES: Dict[str, Dict] = {}
_SIZE_CACHE_MAX = 128


def _shared_size_cache(fp: Optional[str]) -> Dict:
    if fp is None:
        return {}
    with _COUNTER_LOCK:
        cache = _SIZE_CACHES.pop(fp, None)
        if cache is None:
            cache = {}
        _SIZE_CACHES[fp] = cache  # re-append: most recently used
        while len(_SIZE_CACHES) > _SIZE_CACHE_MAX:
            _SIZE_CACHES.pop(next(iter(_SIZE_CACHES)))
        return cache


# ---------------------------------------------------------------------------
# plan fingerprints (the program-cache key contract)
# ---------------------------------------------------------------------------

def schema_sig(schema: Schema) -> Tuple:
    """Hashable signature of a schema — name, full type (decimal
    precision/scale, nested element types via simple_name), nullability."""
    return tuple((f.name, f.data_type.simple_name(), bool(f.nullable))
                 for f in schema.fields)


#: conf entries whose values a trace can depend on (consulted at trace
#: time inside exec kernels, or captured into exec closures at plan
#: build). Two plans tracing under different values of ANY of these
#: must never share compiled programs — they are part of the digest.
def _digest_entries():
    from .. import config as C
    return (C.FUSION_ENABLED, C.STAGE_FUSION_ENABLED, C.AGG_SPECULATIVE,
            C.AGG_GROUP_SLOTS, C.AGG_ROUNDS, C.PALLAS_ENABLED,
            C.PALLAS_FUSED_TIER, C.PALLAS_FUSED_BENCH_FILE,
            C.IMPROVED_FLOAT_OPS, C.STABLE_SORT, C.SORT_OOC_ENABLED,
            C.DECIMAL_ENABLED, C.SHUFFLE_DEVICE_PARTITION,
            C.UPLOAD_PACKED, C.BATCH_SIZE_BYTES, C.SCAN_ENCODED)


def trace_conf_digest(conf=None) -> Optional[Tuple]:
    """The trace-affecting slice of the active conf as a hashable
    tuple, plus the backend platform — folded into every plan
    fingerprint. None when the stage.fusion gate is off (fingerprints
    disabled => per-instance program sites, the pre-ISSUE-14 shape)."""
    from ..config import STAGE_FUSION_ENABLED, active_conf
    conf = conf if conf is not None else active_conf()
    if not conf.get(STAGE_FUSION_ENABLED):
        return None
    import jax
    vals = tuple(str(conf.get(e)) for e in _digest_entries())
    return vals + (jax.default_backend(),)


def fingerprint_node(node: TpuExec, extras) -> Optional[str]:
    """Canonical fingerprint of `node`'s subtree: class name + the
    node's semantic extras + output-schema signature + every child's
    fingerprint + the conf digest. Equal fingerprints MUST imply
    byte-identical traces — that is the program cache's soundness
    contract (trace-time tier consults that read mutable state outside
    the digest — a kern_bench file edited mid-process, a breaker
    opening — bake per compiled shape, exactly as they already did
    under bench-style plan reuse)."""
    digest = trace_conf_digest()
    if digest is None:
        return None
    child_fps = []
    for c in node.children:
        fp = c.plan_fingerprint()
        if fp is None:
            return None
        child_fps.append(fp)
    import hashlib
    payload = repr((type(node).__name__, extras,
                    schema_sig(node.output_schema),
                    tuple(child_fps), digest))
    return hashlib.sha1(payload.encode()).hexdigest()


_donation_filter_installed = False


def _filter_cpu_donation_warning() -> None:
    """CPU backends can NEVER honor buffer donation, so jax's 'Some
    donated buffers were not usable' warning is pure noise there — the
    fused fold's donation is the intentional TPU optimization. Installed
    lazily, once, and ONLY on cpu-family backends: on real TPU the
    warning is a genuine signal (a donated buffer that unexpectedly
    could not be aliased) and must stay audible."""
    global _donation_filter_installed
    if _donation_filter_installed:
        return
    _donation_filter_installed = True
    import jax
    if jax.default_backend() == "cpu":
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")


def _nbytes_of(tree) -> int:
    """Total bytes of a pytree's array leaves, from shapes only —
    never a device sync (the stage_fused event's donated-bytes field)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shp = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shp is None or dt is None:
            continue
        n = 1
        for d in shp:
            n *= int(d)
        total += n * dt.itemsize
    return total


# ---------------------------------------------------------------------------
# the fused stage operator
# ---------------------------------------------------------------------------

class CompiledStageExec(TpuExec):
    """One compiled pipeline stage: a whitelisted operator chain whose
    per-batch body is ONE dispatch-ledger-routed jitted program.

    `children` are the stage's dataflow SOURCES (the first
    non-whitelisted execs below the chain); the absorbed operator
    nodes are kept (``_absorbed``, outermost first) both for
    description/metadata (output schema, grouping contract) and as the
    per-operator FALLBACK path: a demotion — open `device_dispatch`
    breaker, ineligible flavor, empty input corner — re-drives the
    original chain root over the same sources, so degradation (PR 5)
    works at stage granularity and results never depend on the stage
    engaging.

    Accounting: the stage owns its program sites (numDispatches /
    compileTimeNs land here; `QueryProfile.dispatch_summary()` shows
    the fused chain as one row), runs the gather engine's structural
    accounting around each fused dispatch, and emits one `stage_fused`
    event per fused execution. The exact-tier multi-batch merge
    delegates to the absorbed aggregate's own merge machinery — those
    merge dispatches attribute to the (hidden) aggregate node, so the
    stage row stays the honest per-stream-batch figure."""

    def __init__(self, kind: str, absorbed: List[TpuExec],
                 sources: List[TpuExec], join=None, agg=None):
        self._kind = kind
        self._absorbed = list(absorbed)
        self._terminal = absorbed[0]
        self._join = join
        self._agg = agg
        super().__init__(*sources)
        _filter_cpu_donation_warning()
        from ..ops.gather import GatherTracker
        self._gather_track = GatherTracker(self.metrics[NUM_GATHERS],
                                           self.metrics[GATHER_TIME])
        #: (stream_cap, build_cap) -> [cand_cap, s_caps, b_caps, uses]:
        #: the join's speculative sizing contract. Keyed process-wide
        #: by plan fingerprint so a rebuilt identical plan (every
        #: collect) stays WARM — stale caps are safe by the same
        #: overflow-flag contract that makes them safe within one
        #: instance; no fingerprint = instance-local cache. Only the
        #: join_agg kind sizes probes — map/agg stages must not churn
        #: the shared LRU with dead entries.
        self._size_cache = _shared_size_cache(
            self.plan_fingerprint() if kind == "join_agg" else None)
        if kind == "map":
            self._steps = [op.stage_step()
                           for op in reversed(self._absorbed)]
            self._jit_map = self._site(self._map_body,
                                       label="CompiledStageExec.map")
        elif kind == "agg":
            self._jit_step = self._site(
                self._agg_spec_body, label="CompiledStageExec.step",
                donate_argnums=(1, 2))
            self._jit_step_exact = self._site(
                self._agg_exact_body,
                label="CompiledStageExec.step_exact")
        else:  # join_agg
            self._jit_sizing = self._site(
                self._sizing_body, label="CompiledStageExec.sizing")
            self._jit_step = self._site(
                self._ja_spec_body,
                label="CompiledStageExec.probe_step",
                static_argnums=(5, 6, 7, 8), donate_argnums=(3, 4))
            self._jit_step_exact = self._site(
                self._ja_exact_body,
                label="CompiledStageExec.probe_step_exact",
                static_argnums=(3, 4, 5, 6))
        _note(stages_fused=1, ops_fused=len(self._absorbed))

    # -- TpuExec surface ---------------------------------------------------
    @property
    def output_schema(self) -> Schema:
        return self._terminal.output_schema

    def additional_metrics(self):
        # computeAggTime keeps the surface the absorbed AggregateExec
        # used to report (inclusive of the source drive, the agg's own
        # convention) so metric-keyed tooling survives fusion; map
        # stages register it too (zero) — the declaration must stay
        # self-independent (docs-lint contract)
        return (AGG_TIME,) + GATHER_METRICS + DISPATCH_METRICS

    @property
    def output_grouped_by(self):
        # the absorbed chain's links are intact, so the terminal op's
        # contract (e.g. the inner join's key-grouped emission feeding
        # a downstream group-by) reads straight through
        return self._terminal.output_grouped_by

    @property
    def consumes_encoded(self) -> bool:
        # a map stage can run on dictionary-encoded inputs (ISSUE 18)
        # exactly when every absorbed operator could individually —
        # the fused body runs the same columnar_eval/compaction those
        # operators would. agg/join_agg stages fold values into
        # aggregate state, so they need materialized inputs. No
        # encoded-ness entry is folded into the plan fingerprint:
        # DictionaryColumn and its decoded form are DIFFERENT pytree
        # structures, so jit keys the compiled program on the actual
        # input encoding already — the SCAN_ENCODED conf digest entry
        # only separates plans whose EXECS were built under different
        # gate values.
        if self._kind != "map":
            return False
        return all(op.consumes_encoded for op in self._absorbed)

    def _fingerprint_extras(self):
        term_fp = self._terminal.plan_fingerprint()
        if term_fp is None:
            return None
        return (self._kind, term_fp)

    def node_description(self) -> str:
        ops = "+".join(type(op).__name__ for op in self._absorbed)
        return f"CompiledStageExec[{self._kind}: {ops}]"

    @property
    def _stage_label(self) -> str:
        return f"{self._kind}:" + \
            "+".join(type(op).__name__ for op in self._absorbed)

    # -- engagement / fallback --------------------------------------------
    def _stage_engaged(self) -> bool:
        """Per-execution gate: an open `device_dispatch` breaker (PR 5)
        demotes this stage to per-operator execution until its
        cooldown/probe closes it; a healthy consult notes the
        engagement so classified-transient failures of this attempt
        count against the domain."""
        from . import lifecycle
        if not lifecycle.breaker_allows("device_dispatch"):
            return False
        lifecycle.engage_domain("device_dispatch")
        return True

    def _drive_fallback(self):
        _note(fallbacks=1)
        yield from self._terminal.execute()

    def internal_execute(self):
        if not self._stage_engaged():
            yield from self._drive_fallback()
            return
        disp = self.metrics[NUM_DISPATCHES]
        d0 = disp.value
        t0 = time.perf_counter_ns()
        #: [input batches, donated bytes] updated LIVE by the drive
        #: below — a consumer abandoning the stream early (a limit)
        #: must still see the true counts in the stage_fused event
        live = self._live_stats = [0, 0]
        if self._kind == "map":
            gen = self._execute_map()
        elif self._kind == "agg":
            gen = self._execute_agg()
        else:
            gen = self._execute_join_agg()
        fell_back = False
        try:
            for item in gen:
                if item is _FALLBACK:
                    # empty-input corner: the per-op chain owns the
                    # empty-aggregate semantics — re-drive it (sources
                    # are exhausted-empty, so this is cheap and exact)
                    fell_back = True
                    yield from self._drive_fallback()
                    return
                yield item
        finally:
            n_in, donated = live
            if not fell_back:
                # one gather_stats per execution (the wired-exec
                # convention): the fused probe/compaction gathers
                # reconcile with the stage's numGathers metric
                self._gather_track.emit_event(type(self).__name__,
                                              self._op_id)
                wall = time.perf_counter_ns() - t0
                if self._kind != "map":
                    self.metrics[AGG_TIME].add(wall)
                d = disp.value - d0
                _note(executions=1, batches=n_in, dispatches=d)
                from ..obs import events as obs_events
                obs_events.emit(
                    "stage_fused", stage=self._kind,
                    label=self._stage_label, ops=len(self._absorbed),
                    batches=n_in, dispatches=d, donated_bytes=donated,
                    wall_ns=time.perf_counter_ns() - t0)

    # -- map stage ---------------------------------------------------------
    def _map_body(self, batch: ColumnarBatch):
        """PURE traced body (stage-governance rule): every projection
        evaluated, every filter ANDed into ONE mask, ONE compaction at
        the end of each output path. Expand fans out: all projections
        of one input batch emit from this single program."""
        from ..ops.basic import compact_columns
        from .basic import eval_projection
        outs: List[ColumnarBatch] = []

        def run(cur, mask, steps):
            for i, step in enumerate(steps):
                if step[0] == "filter":
                    pred = step[1].columnar_eval(cur)
                    m = pred.data & pred.validity
                    mask = m if mask is None else (mask & m)
                elif step[0] == "project":
                    cur = eval_projection(step[1], cur, step[2])
                else:  # expand: fan out over its projections
                    for bound in step[1]:
                        nxt = eval_projection(bound, cur, step[2])
                        run(nxt, mask, steps[i + 1:])
                    return
            if mask is None:
                outs.append(cur)
            else:
                cols, n = compact_columns(cur.columns, mask,
                                          cur.num_rows)
                outs.append(ColumnarBatch(cols, n, cur.schema))

        run(batch, None, self._steps)
        return tuple(outs)

    def _execute_map(self):
        from ..memory.retry import split_in_half_by_rows, with_retry
        from ..memory.spillable import SpillableBatch
        live = self._live_stats
        n_in = 0
        for batch in self.children[0].execute():
            n_in += 1
            live[0] = n_in
            sp = SpillableBatch.from_batch(batch)
            try:
                def run(s):
                    b = s.get_batch()
                    try:
                        with self.batch_harness(
                                gather_shape=("map", b.capacity),
                                fault_point="device.dispatch",
                                fault_key=f"stage:map:{n_in}"):
                            return self._jit_map(b)
                    finally:
                        s.release()
                for outs in with_retry(
                        sp, run, split_policy=split_in_half_by_rows):
                    for out in outs:
                        yield out
            finally:
                sp.close()

    # -- agg stage ---------------------------------------------------------
    def _agg_spec_body(self, batch, state, flag):
        return self._agg._streaming_step(batch, state, flag)

    def _agg_exact_body(self, batch):
        part = self._agg._fused_update_exact(batch)
        ev = None if self._agg.mode == "partial" \
            else self._agg._evaluate(part)
        return part, ev

    def _fresh_state(self):
        """Fresh (never the agg's cached) initial state: the fused
        step DONATES the carried state, and donating a cached buffer
        would invalidate it for the next execution on backends that
        honor donation."""
        import jax.numpy as jnp
        from ..columnar.batch import empty_batch
        return (empty_batch(self._agg._buffer_schema,
                            capacity=self._agg._small_cap()),
                jnp.asarray(False))

    def _spec_allowed(self) -> bool:
        from .speculation import speculation_allowed
        agg = self._agg
        return agg._masked_ok and agg._spec_enabled \
            and speculation_allowed()

    def _execute_agg(self):
        from ..memory.retry import split_in_half_by_rows, with_retry
        from ..memory.spillable import SpillableBatch
        from .speculation import current_scope
        agg = self._agg
        live = self._live_stats
        spec = self._spec_allowed()
        saw = False
        n_in = 0
        if spec:
            state, flag = self._fresh_state()
            ev = None
            for batch in self.children[0].execute():
                saw = True
                n_in += 1
                live[0] = n_in
                live[1] = _nbytes_of((state, flag))
                sp = SpillableBatch.from_batch(batch)
                box = [state, flag, None]
                try:
                    def run(s):
                        b = s.get_batch()
                        try:
                            with self.batch_harness(
                                    gather_shape=("agg", b.capacity),
                                    fault_point="device.dispatch",
                                    fault_key=f"stage:agg:{n_in}"):
                                return self._jit_step(b, box[0], box[1])
                        finally:
                            s.release()
                    for out in with_retry(
                            sp, run,
                            split_policy=split_in_half_by_rows):
                        box[0], box[1], box[2] = out
                finally:
                    sp.close()
                state, flag, ev = box
            if not saw:
                yield _FALLBACK
                return
            scope = current_scope()
            if scope is not None:
                scope.record(flag)
            if agg.mode == "partial":
                yield state
            else:
                yield (ev if ev is not None
                       else agg._jit_evaluate(state))
        else:
            parts: List = []
            n_parts = 0
            last_ev = None
            for batch in self.children[0].execute():
                saw = True
                n_in += 1
                live[0] = n_in
                sp = SpillableBatch.from_batch(batch)
                try:
                    def run(s):
                        b = s.get_batch()
                        try:
                            with self.batch_harness(
                                    gather_shape=("agg", b.capacity),
                                    fault_point="device.dispatch",
                                    fault_key=f"stage:agg:{n_in}"):
                                return self._jit_step_exact(b)
                        finally:
                            s.release()
                    for part, ev in with_retry(
                            sp, run,
                            split_policy=split_in_half_by_rows):
                        # the agg's own shrink + MERGE_FAN_IN window:
                        # live partials stay bounded under a forced-
                        # spill budget, exactly like the per-op drive
                        agg._absorb_partial(parts, part)
                        n_parts += 1
                        last_ev = ev
                finally:
                    sp.close()
            if not saw:
                for p in parts:
                    p.close()
                yield _FALLBACK
                return
            yield self._finish_exact(
                parts, last_ev if n_parts == 1 else None)

    def _finish_exact(self, parts, last_ev):
        """Exact-tier tail: a single partial was already evaluated
        in-program (the N=1 steady state: one dispatch total); several
        delegate to the absorbed aggregate's own merge machinery —
        byte-identical to the per-operator merge path."""
        agg = self._agg
        if len(parts) == 1:
            only = parts[0]
            merged = only.get_batch()
            only.release()
            only.close()
            if agg.mode == "partial":
                return merged
            return last_ev if last_ev is not None \
                else agg._jit_evaluate(merged)
        merged = agg._merge_all(parts)
        return merged if agg.mode == "partial" \
            else agg._jit_evaluate(merged)

    # -- join_agg stage ----------------------------------------------------
    def _sizing_body(self, build_batch, stream_batch):
        """Cold-path sizing program: build table + probe counts + the
        exact byte needs, ONE dispatch (the table is re-derived inside
        the first fused step — sizing runs once per size-cache miss,
        not per batch)."""
        table = self._join._build_kernel(build_batch)
        _lo, _counts, _sk, total, needs = \
            self._join._counts_kernel(table, stream_batch)
        return total, needs

    def _probe_in_stage(self, table, build_batch, stream_batch,
                        cand_cap, s_caps, b_caps, use_fused):
        """Traced: counts + probe + emit, plus the speculative-sizing
        overflow flag (the join's _probe_one contract, in-program)."""
        import jax.numpy as jnp
        lo, counts, skey_cols, total, needs = \
            self._join._counts_kernel(table, stream_batch)
        zeros = jnp.zeros((table.capacity,), jnp.bool_)
        out, _bm = self._join._probe_kernel(
            table, build_batch, stream_batch, (lo, counts, skey_cols),
            zeros, cand_cap, s_caps, b_caps, use_fused)
        flag = total > cand_cap
        s_needs, b_needs = needs
        for need, cap in zip(
                list(s_needs) + list(b_needs),
                [c for c in s_caps if c is not None]
                + [c for c in b_caps if c is not None]):
            flag = flag | (need > cap)
        return out, flag

    def _ja_spec_body(self, table, build_batch, stream_batch, state,
                      flag, cand_cap, s_caps, b_caps, use_fused):
        if table is None:
            table = self._join._build_kernel(build_batch)
        out, size_flag = self._probe_in_stage(
            table, build_batch, stream_batch, cand_cap, s_caps, b_caps,
            use_fused)
        state, flag, ev = self._agg._streaming_step(
            out, state, flag | size_flag)
        return table, state, flag, ev

    def _ja_exact_body(self, table, build_batch, stream_batch,
                       cand_cap, s_caps, b_caps, use_fused):
        if table is None:
            table = self._join._build_kernel(build_batch)
        out, size_flag = self._probe_in_stage(
            table, build_batch, stream_batch, cand_cap, s_caps, b_caps,
            use_fused)
        part = self._agg._fused_update_exact(out)
        ev = None if self._agg.mode == "partial" \
            else self._agg._evaluate(part)
        return table, part, ev, size_flag

    def _sizing(self, build_batch, stream_batch):
        """Host half of the join's speculative sizing contract: warm
        shape -> cached static caps, overflow checked by a device flag
        inside the fused program (recorded with the speculation scope);
        cold shape (or no scope) -> ONE sizing dispatch + exact caps.
        Bounded staleness (the join's SPEC_REFRESH contract, ADVICE
        r4): after SPEC_REFRESH warm uses the entry expires and the
        next probe re-measures FRESH — no monotone max — so one
        pathological batch cannot inflate the plan shape's buckets for
        the process lifetime. Returns ((cand_cap, s_caps, b_caps,
        use_fused), warm)."""
        import jax
        from ..columnar.column import bucket_capacity
        from ..ops.pallas_tier import fused_tier_enabled
        from .joins import HashJoinExec, _byte_cap_tuple
        from .speculation import speculation_allowed
        key = (stream_batch.capacity, build_batch.capacity)
        cached = self._size_cache.get(key)
        use_fused = fused_tier_enabled("join_probe", key)
        if cached is not None and speculation_allowed():
            cached[3] += 1
            if cached[3] > HashJoinExec.SPEC_REFRESH:
                del self._size_cache[key]
                cached = None
            else:
                return (cached[0], cached[1], cached[2], use_fused), \
                    True
        total_dev, needs_dev = self._jit_sizing(build_batch,
                                                stream_batch)
        total, (s_needs, b_needs) = jax.device_get(
            (total_dev, needs_dev))
        cand_cap = bucket_capacity(max(int(total), 1))
        s_caps = _byte_cap_tuple(stream_batch.columns, s_needs)
        b_caps = _byte_cap_tuple(build_batch.columns, b_needs)
        if cached is not None:
            # keep buckets monotone so steady state stays compiled
            oc, os_, ob = cached[0], cached[1], cached[2]
            cand_cap = max(cand_cap, oc)
            s_caps = tuple(None if c is None else max(c, o)
                           for c, o in zip(s_caps, os_))
            b_caps = tuple(None if c is None else max(c, o)
                           for c, o in zip(b_caps, ob))
        self._size_cache[key] = [cand_cap, s_caps, b_caps, 0]
        return (cand_cap, s_caps, b_caps, use_fused), False

    def _execute_join_agg(self):
        from ..columnar.batch import empty_batch
        from ..memory.retry import split_in_half_by_rows, with_retry
        from ..memory.spillable import SpillableBatch
        from .coalesce import concat_batches
        from .speculation import current_scope
        join, agg = self._join, self._agg
        bi = 1 if join.build_side == "right" else 0
        build_child, stream_child = self.children[bi], \
            self.children[1 - bi]
        batches = list(build_child.execute())
        if batches:
            build_batch = concat_batches(batches,
                                         build_child.output_schema)
        else:
            build_batch = empty_batch(build_child.output_schema)
        spec = self._spec_allowed()
        table = None
        state = flag = ev = None
        parts: List = []
        n_parts = 0
        last_ev = None
        if spec:
            state, flag = self._fresh_state()
        saw = False
        n_in = 0
        live = self._live_stats
        scope = current_scope()
        for stream_batch in stream_child.execute():
            saw = True
            n_in += 1
            live[0] = n_in
            (cand_cap, s_caps, b_caps, use_fused), warm = \
                self._sizing(build_batch, stream_batch)
            sp = SpillableBatch.from_batch(stream_batch)
            try:
                if spec:
                    live[1] = _nbytes_of((state, flag))
                    box = [table, state, flag, None]

                    def run(s):
                        b = s.get_batch()
                        try:
                            with self.batch_harness(
                                    gather_shape=(
                                        "join_agg", b.capacity,
                                        build_batch.capacity, cand_cap,
                                        s_caps, b_caps, use_fused),
                                    fault_point="device.dispatch",
                                    fault_key=f"stage:join:{n_in}"):
                                return self._jit_step(
                                    box[0], build_batch, b, box[1],
                                    box[2], cand_cap, s_caps, b_caps,
                                    use_fused)
                        finally:
                            s.release()
                    for out in with_retry(
                            sp, run,
                            split_policy=split_in_half_by_rows):
                        box[0], box[1], box[2], box[3] = out
                    table, state, flag, ev = box
                else:
                    def run(s):
                        b = s.get_batch()
                        try:
                            with self.batch_harness(
                                    gather_shape=(
                                        "join_agg", b.capacity,
                                        build_batch.capacity, cand_cap,
                                        s_caps, b_caps, use_fused),
                                    fault_point="device.dispatch",
                                    fault_key=f"stage:join:{n_in}"):
                                return self._jit_step_exact(
                                    table, build_batch, b, cand_cap,
                                    s_caps, b_caps, use_fused)
                        finally:
                            s.release()
                    for tbl, part, pev, size_flag in with_retry(
                            sp, run,
                            split_policy=split_in_half_by_rows):
                        table = tbl
                        # bounded accumulation: the agg's shrink +
                        # MERGE_FAN_IN window (forced-spill parity)
                        agg._absorb_partial(parts, part)
                        n_parts += 1
                        last_ev = pev
                        if warm and scope is not None:
                            scope.record(size_flag)
            finally:
                sp.close()
        if not saw:
            for p in parts:
                p.close()
            yield _FALLBACK
            return
        if spec:
            if scope is not None:
                scope.record(flag)
            if agg.mode == "partial":
                yield state
            else:
                yield (ev if ev is not None
                       else agg._jit_evaluate(state))
        else:
            yield self._finish_exact(
                parts, last_ev if n_parts == 1 else None)


#: sentinel: the fused drive hit a corner the per-op chain owns
_FALLBACK = object()


# ---------------------------------------------------------------------------
# the stage planner
# ---------------------------------------------------------------------------

def compile_stages(root: TpuExec, conf=None) -> TpuExec:
    """Rewrite a converted TpuExec tree: whitelisted chains become
    CompiledStageExec nodes; everything else is untouched. The no-op
    path (conf off) returns `root` as-is."""
    from ..config import STAGE_FUSION_ENABLED, active_conf
    conf = conf if conf is not None else active_conf()
    if not conf.get(STAGE_FUSION_ENABLED):
        return root
    return _rewrite(root)


def _rewrite(node: TpuExec) -> TpuExec:
    stage = _try_stage(node)
    target = stage if stage is not None else node
    kids = list(target.children)
    changed = False
    for i, c in enumerate(kids):
        new = _rewrite(c)
        if new is not c:
            kids[i] = new
            changed = True
            # an absorbing aggregate's streaming source may bypass the
            # children chain — keep it pointing at the live node
            if getattr(target, "_source", None) is c:
                target._source = new
    if changed:
        target.children = kids if isinstance(target.children, list) \
            else type(target.children)(kids)
    return target


def _agg_eligible(agg) -> bool:
    from ..config import FUSION_ENABLED, active_conf
    return (agg.mode in ("complete", "partial") and agg._masked_ok
            and active_conf().get(FUSION_ENABLED))


def _join_eligible(join) -> bool:
    from .joins import INNER
    # inner only: no build flags, no stream-preserved tails — the
    # probe's one-output-batch-per-stream-batch dataflow the fused
    # program composes with the aggregate update
    return join.join_type == INNER and not join._need_build_flags


def _try_stage(node: TpuExec) -> Optional[CompiledStageExec]:
    from .aggregate import AggregateExec
    from .basic import ExpandExec, FilterExec, ProjectExec
    from .joins import HashJoinExec
    if isinstance(node, CompiledStageExec):
        return None
    if isinstance(node, AggregateExec) and _agg_eligible(node):
        src = node._source
        if isinstance(src, HashJoinExec) and _join_eligible(src):
            return CompiledStageExec(
                "join_agg", absorbed=[node] + _chain_between(node, src)
                + [src], sources=list(src.children), join=src, agg=node)
        if node._fused_steps:
            # a REAL chain (filter/project absorbed); a bare group-by
            # is already one program per batch — wrapping it would
            # only rename its profile row
            return CompiledStageExec(
                "agg", absorbed=[node] + _chain_between(node, src),
                sources=[src], agg=node)
        return None
    if isinstance(node, (FilterExec, ProjectExec, ExpandExec)):
        chain = [node]
        cur = node
        while True:
            child = cur.children[0]
            if isinstance(child, (FilterExec, ProjectExec, ExpandExec)):
                chain.append(child)
                cur = child
            else:
                break
        if len(chain) >= 2:
            return CompiledStageExec("map", absorbed=chain,
                                     sources=[cur.children[0]])
    return None


def _chain_between(agg, src) -> List[TpuExec]:
    """The operator nodes the aggregate absorbed between itself and
    its streaming source (for stage description/accounting)."""
    out = []
    cur = agg.children[0] if agg.children else None
    while cur is not None and cur is not src:
        out.append(cur)
        cur = cur.children[0] if cur.children else None
    return out
