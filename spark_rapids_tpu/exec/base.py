"""TpuExec — base of the columnar operator tree (reference GpuExec,
sql-plugin/.../GpuExec.scala:365 `doExecuteColumnar`; metric registry at
GpuExec.scala:49-116 with ESSENTIAL/MODERATE/DEBUG levels).

Operators form a tree; `execute()` returns an iterator of ColumnarBatch.
Each operator's device work is jax-traced per batch *shape bucket*, so a
pipeline of execs compiles into a small set of XLA programs reused across
batches. Host-side control (iteration, spill, retry, coalesce decisions)
stays in Python exactly where the reference keeps it in Scala.
"""

from __future__ import annotations

import itertools
import time
from contextlib import contextmanager, nullcontext
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

from ..columnar.batch import ColumnarBatch
from ..types import Schema

# the same three-level scale as obs/events.py (the single name->int
# parser lives there: events.parse_level)
ESSENTIAL = 0
MODERATE = 1
DEBUG = 2


def metrics_level_from_conf(conf=None) -> int:
    """spark.rapids.sql.metrics.level as an int (unknown → MODERATE),
    the visibility cut for all_metrics()/last_query_metrics()
    (reference GpuExec.scala:36-47)."""
    from ..config import METRICS_LEVEL, active_conf
    from ..obs.events import parse_level
    conf = conf if conf is not None else active_conf()
    return parse_level(conf.get(METRICS_LEVEL))


class TpuMetric:
    """Accumulating operator metric (reference GpuMetric).

    Device-produced values (e.g. a traced row count) are accumulated as
    device scalars and only materialized when the metric is READ. A d2h
    sync in the steady-state batch loop costs orders of magnitude more
    than the kernels themselves (the analog of a cudaStreamSynchronize
    per batch), so `add_device` must never block.
    """

    __slots__ = ("name", "level", "_value", "_pending")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self._value = 0
        self._pending: List = []

    def add(self, v):
        self._value += v

    def add_device(self, scalar):
        """Accumulate a device scalar lazily (no sync until read)."""
        self._pending.append(scalar)

    @property
    def value(self):
        if self._pending:
            import jax.numpy as jnp
            pending, self._pending = self._pending, []
            # one stacked transfer, not one round trip per scalar
            self._value += int(jnp.sum(jnp.stack(
                [jnp.asarray(s).astype(jnp.int64) for s in pending])))
        return self._value

    @value.setter
    def value(self, v):
        self._pending = []
        self._value = v

    def ns_timer(self):
        return _NsTimer(self)


class _NsTimer:
    def __init__(self, metric: TpuMetric):
        self.metric = metric

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.metric.add(time.perf_counter_ns() - self._t0)


# canonical metric names (reference GpuMetric companion, GpuExec.scala:49-96)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
CONCAT_TIME = "concatTime"
JOIN_TIME = "joinTime"
BUILD_TIME = "buildTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
NUM_TASKS_FALL_BACKED = "numTasksFallBacked"
SPILL_TIME = "spillTime"
PARTITION_SIZE = "dataSize"
SHUFFLE_WRITE_TIME = "shuffleWriteTime"
SHUFFLE_READ_TIME = "shuffleReadTime"
SHUFFLE_PACK_TIME = "shufflePackTimeNs"
BROADCAST_TIME = "broadcastTime"
PIPELINE_WAIT = "pipelineWaitNs"
PIPELINE_FULL_WAIT = "pipelineFullWaitNs"
PIPELINE_WALL = "pipelineWallNs"
NUM_GATHERS = "numGathers"
GATHER_TIME = "gatherTimeNs"
NUM_UPLOADS = "numUploads"
UPLOAD_PACK_TIME = "uploadPackTimeNs"
NUM_DISPATCHES = "numDispatches"
COMPILE_TIME = "compileTimeNs"

#: the closed set of metric names execs may register — one name, one
#: meaning, exactly like the reference's GpuMetric companion object.
#: tests/test_docs_lint.py asserts every additional_metrics() entry
#: resolves here, so a typo'd or duplicate-meaning name fails tier-1.
CANONICAL_METRICS = frozenset({
    NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, NUM_INPUT_ROWS, NUM_INPUT_BATCHES,
    OP_TIME, SORT_TIME, AGG_TIME, CONCAT_TIME, JOIN_TIME, BUILD_TIME,
    PEAK_DEVICE_MEMORY, NUM_TASKS_FALL_BACKED, SPILL_TIME, PARTITION_SIZE,
    SHUFFLE_WRITE_TIME, SHUFFLE_READ_TIME, SHUFFLE_PACK_TIME,
    BROADCAST_TIME,
    PIPELINE_WAIT, PIPELINE_FULL_WAIT, PIPELINE_WALL,
    NUM_GATHERS, GATHER_TIME,
    NUM_UPLOADS, UPLOAD_PACK_TIME,
    NUM_DISPATCHES, COMPILE_TIME,
})

#: per-operator instance ids for event/span attribution (two
#: AggregateExecs in one plan stay distinguishable in the event log)
_OP_IDS = itertools.count(1)

#: an additional_metrics() entry: a bare canonical name (MODERATE) or
#: (name, level)
MetricSpec = Union[str, Tuple[str, int]]

#: the metric triple every exec that runs a pipelined() input stage
#: registers (include in additional_metrics(); bind with
#: TpuExec.pipeline_stage)
PIPELINE_STAGE_METRICS = ((PIPELINE_WAIT, MODERATE),
                          (PIPELINE_FULL_WAIT, MODERATE),
                          (PIPELINE_WALL, MODERATE))

#: the metric pair every gather-engine-wired exec registers (include in
#: additional_metrics(); bind with ops.gather.GatherTracker): the
#: structural count of materializing row gathers per execution and the
#: wall-ns of the gather-bearing kernel dispatches
GATHER_METRICS = ((NUM_GATHERS, MODERATE), (GATHER_TIME, MODERATE))

#: the metric pair every upload-engine-wired exec registers (include in
#: additional_metrics(); attributed via columnar.upload.metric_sink /
#: promote_stream): batch uploads this execution dispatched and the
#: wall-ns spent packing + transferring them
UPLOAD_METRICS = ((NUM_UPLOADS, MODERATE), (UPLOAD_PACK_TIME, MODERATE))

#: the metric pair every dispatch-ledger-wired exec registers (include
#: in additional_metrics(); bound by building the exec's jit sites with
#: obs.dispatch.instrument(owner=self), or via dispatch.metric_scope
#: for module-level program sites): program dispatches this exec issued
#: and the wall-ns its fresh traces spent compiling (ISSUE 13 — the
#: per-stage dispatches/batch baseline whole-stage compilation answers
#: to). Dispatches are counted at CALL time, so jit cache hits replay
#: identical counts on repeated executions.
DISPATCH_METRICS = ((NUM_DISPATCHES, MODERATE), (COMPILE_TIME, MODERATE))


class TpuExec:
    """Base columnar operator."""

    #: ISSUE 18 (encoded execution): True when this exec's kernels accept
    #: DictionaryColumn inputs from its children (code-space predicates,
    #: encoded-key joins, pass-through projections). Execs override it —
    #: usually with an eligibility walk over their bound expressions
    #: (expr/predicates.encoded_safe_predicate) — and the default False
    #: guarantees an operator never silently misreads the encoded layout:
    #: its children materialize at the batch boundary instead.
    consumes_encoded: bool = False

    #: stamped by the PARENT's execute() before this exec's first batch is
    #: pulled (child iterators start lazily): whether encoded columns may
    #: cross this exec's output boundary. The root of a plan is never
    #: stamped, so root output always materializes (the late-
    #: materialization seam — results are byte-identical with the lane
    #: off).
    _encoded_ok_for_parent: bool = False

    def __init__(self, *children: "TpuExec"):
        self.children: List[TpuExec] = list(children)
        self._op_id = next(_OP_IDS)
        self.metrics: Dict[str, TpuMetric] = {}
        for name in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES):
            self.metrics[name] = TpuMetric(name, ESSENTIAL)
        self.metrics[OP_TIME] = TpuMetric(OP_TIME, MODERATE)
        for spec in self.additional_metrics():
            name, level = spec if isinstance(spec, tuple) \
                else (spec, MODERATE)
            self.metrics[name] = TpuMetric(name, level)

    # -- subclass surface --------------------------------------------------
    @property
    def output_schema(self) -> Schema:
        raise NotImplementedError(type(self).__name__)

    def additional_metrics(self) -> Sequence[MetricSpec]:
        return ()

    @property
    def output_grouped_by(self):
        """Grouping contract of this exec's output batches, or None.

        A tuple of frozensets of output column names: within every
        emitted batch, rows carrying equal values for (one representative
        of each class) are CONTIGUOUS, and the columns inside one class
        are pairwise equal per row (e.g. the two sides of an equi-join
        key). A downstream group-by whose keys pick a representative from
        every class (and nothing else) may skip its sort
        (ops/aggregate.groupby_aggregate pre_grouped)."""
        return None

    @property
    def runs_own_pipeline_stage(self) -> bool:
        """True when this exec's execute() already drives a pipelined()
        producer stage of its own. A consumer that would wrap its input
        in another stage (e.g. CoalesceBatchesExec) skips it then —
        stacking two stages on one edge doubles threads and live
        prefetched batches for zero extra overlap. Wrapper execs that
        delegate execution to a child should forward the child's value."""
        return False

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        raise NotImplementedError(type(self).__name__)

    def _fingerprint_extras(self):
        """Semantic parameters of THIS node beyond its class, output
        schema and children — everything a trace of its programs
        depends on (bound expressions, modes, captured conf knobs).
        Returning None opts the subtree out of the plan-fingerprint
        program cache (the safe default: an exec whose trace semantics
        are not fully captured here must never share compiled programs
        across instances)."""
        return None

    def plan_fingerprint(self) -> Optional[str]:
        """Canonical plan-subtree fingerprint (ISSUE 14): equal
        fingerprints promise byte-identical traces, so the process-wide
        program cache (obs/dispatch.py) may hand a later collect()'s
        rebuilt exec the programs an identical earlier plan already
        compiled — and the stage compiler keys CompiledStageExec
        programs (and, later, ROADMAP 5's sub-plan result cache) off
        the same digest. Combines per-node semantics
        (_fingerprint_extras), the output schema, every child's
        fingerprint, the backend platform and the trace-affecting conf
        digest. None = some node in the subtree opted out (or the
        stage.fusion gate is off) — callers fall back to per-instance
        program sites. Memoized per instance: compute it only after
        the node's semantic fields are final."""
        memo = self.__dict__.get("_plan_fp", False)
        if memo is not False:
            return memo
        fp = None
        try:
            extras = self._fingerprint_extras()
            if extras is not None:
                from .stage_compiler import fingerprint_node
                fp = fingerprint_node(self, extras)
        except Exception:  # noqa: BLE001 — fingerprinting is an
            fp = None      # optimization; never fail plan build
        self.__dict__["_plan_fp"] = fp
        return fp

    def _site(self, fn, label: str, key_salt=None, **jit_kwargs):
        """Build one of this exec's program sites through the dispatch
        chokepoint, keyed by the plan fingerprint when available — a
        semantically identical exec in a later collect() then reuses
        the SAME compiled programs (zero fresh traces, the PR 13
        per-collect-recompile finding closed). `key_salt`
        disambiguates several sites sharing one label on one exec
        (ExpandExec's per-projection programs): without it the cache
        would hand every projection the FIRST one's program."""
        from ..obs.dispatch import instrument
        fp = self.plan_fingerprint()
        key = None if fp is None else \
            (fp if key_salt is None else (fp, key_salt))
        return instrument(fn, label=label, owner=self, cache_key=key,
                          **jit_kwargs)

    def batch_harness(self, gather_shape=None, fault_point=None,
                      fault_key=None, metric_scope: bool = False):
        """THE per-batch stage-boundary governance harness (ISSUE 14).

        Compute bodies handed to the dispatch chokepoint must stay PURE
        traced dataflow (the `stage-governance` analyzer rule): the
        per-batch governance hooks — gather accounting, chaos fault
        points, module-site dispatch metric attribution — bind HERE,
        around the one program call, at the stage boundary. Lifecycle
        cancellation ticks already live at the TpuExec._drive batch
        boundary, and breaker engagement is noted at trace time by the
        tier selector, so the PR 5/6 contracts hold at stage
        granularity. Returns a context manager; plain per-op paths and
        CompiledStageExec route through the same helper so every wired
        boundary changes together."""
        scopes = []
        if fault_point is not None:
            from .. import faults
            faults.check(fault_point, key=fault_key)
        if gather_shape is not None:
            tracker = getattr(self, "_gather_track", None)
            if tracker is not None:
                scopes.append(tracker.observe(gather_shape))
        if metric_scope:
            from ..obs import dispatch as obs_dispatch
            scopes.append(obs_dispatch.metric_scope(
                self.metrics[NUM_DISPATCHES],
                self.metrics[COMPILE_TIME]))
        if not scopes:
            return nullcontext()
        if len(scopes) == 1:
            return scopes[0]

        @contextmanager
        def _stacked():
            with scopes[0], scopes[1]:
                yield
        return _stacked()

    def pipeline_stage(self, source, label: str, depth=None):
        """The one way an exec wraps an input in a pipelined() stage:
        binds this operator's three PIPELINE_STAGE_METRICS (which its
        additional_metrics() must register) and tags the stage label
        with the op id. Callers drive the returned stage inside
        try/finally with stage.close() — close/metric conventions live
        here so all wired boundaries change together."""
        from .pipeline import pipelined
        return pipelined(source, depth=depth,
                         label=f"{label}-{self._op_id}",
                         wait_metric=self.metrics[PIPELINE_WAIT],
                         full_metric=self.metrics[PIPELINE_FULL_WAIT],
                         wall_metric=self.metrics[PIPELINE_WALL])

    # -- public ------------------------------------------------------------
    def execute(self) -> Iterator[ColumnarBatch]:
        """Final wrapper (reference GpuExec.doExecuteColumnar:365): counts
        output rows/batches around the operator's own iterator, with an
        xprof trace annotation per batch step (the reference's NVTX
        range; shows operator names over their XLA ops in timelines).

        With the event log enabled (spark.rapids.tpu.eventLog.enabled)
        this is also the operator span source: one `op_open` when the
        iterator starts, one `op_batch` per step (wall-ns around the
        pull, so INCLUSIVE of child time — the pull model's analog of
        the reference's NVTX range nesting), and one `op_close` carrying
        the cumulative totals when it finishes (or is abandoned by a
        limit). Disabled mode pays exactly one active_bus() check."""
        from ..obs import events as obs_events
        rows = self.metrics[NUM_OUTPUT_ROWS]
        batches = self.metrics[NUM_OUTPUT_BATCHES]
        name = type(self).__name__
        # retain last outputs ONLY when failure dumping is configured —
        # otherwise each operator would pin one device batch for the
        # whole query, stealing memory the spill machinery counts as free
        try:
            from ..config import DEBUG_DUMP_PATH, active_conf
            dump_enabled = bool(active_conf().get(DEBUG_DUMP_PATH))
        except Exception:  # noqa: BLE001 — conf unavailable early
            dump_enabled = False
        # encoded-execution stamping (ISSUE 18): children learn whether
        # THIS exec's kernels can consume their encoded columns before
        # their first batch is pulled (internal_execute below builds the
        # child iterators lazily); an unstamped/False child materializes
        # at its own yield boundary in _drive
        for c in self.children:
            c._encoded_ok_for_parent = self.consumes_encoded
        it = self.internal_execute()
        bus = obs_events.active_bus()
        # lifecycle governor (ISSUE 6): the ONE batch-boundary
        # cancellation hook for every operator — outside a governed
        # query (tests/bench driving exec trees directly) qctx is None
        # and each batch pays exactly this pointer check; inside one,
        # tick() checks the deadline/cancel token every
        # query.cancelCheckBatches batches and raises
        # QueryCancelledError at the boundary
        from . import lifecycle
        qctx = lifecycle.current_context()
        try:
            yield from self._drive(it, bus, qctx, name, rows, batches,
                                   dump_enabled)
        finally:
            # synchronous teardown (ISSUE 6): when an exception (a
            # cancellation tick, a downstream operator error) unwinds
            # THROUGH this frame, the internal iterator below us may be
            # left suspended — closing it here runs its try/finally
            # chain NOW (pipeline stages join their producer threads,
            # staged spillables close), instead of whenever GC drops
            # the suspended frames. Exhausted iterators close as a
            # no-op, so the steady state is unchanged.
            close = getattr(it, "close", None)
            if close is not None:
                close()

    def _drive(self, it, bus, qctx, name, rows, batches, dump_enabled):
        from ..columnar.encoded import materialize_batch
        from ..obs import events as obs_events
        from ..utils.tracing import annotate_op
        # late materialization (ISSUE 18): when the parent's kernels
        # cannot consume encoded columns, decode them HERE — once, at the
        # batch boundary, through the gather engine — instead of letting
        # them reach code that would misread the layout. Identity (one
        # isinstance scan) for batches with no encoded columns.
        decode = not self._encoded_ok_for_parent
        if bus is None:
            # fast path: bit-identical to the pre-obs loop
            while True:
                if qctx is not None:
                    qctx.tick()
                with annotate_op(name):
                    try:
                        batch = next(it)
                    except StopIteration:
                        return
                    except Exception:
                        self._dump_failure_inputs(name)
                        raise
                    if decode:
                        batch = materialize_batch(batch, seam="boundary")
                batches.add(1)
                if batch._host_rows is not None:
                    rows.add(batch._host_rows)
                else:
                    rows.add_device(batch.num_rows)
                if qctx is not None:
                    # live-introspection progress (ISSUE 11): current
                    # operator + root-output batch/row counts; host row
                    # counts only — never a device sync
                    qctx.note_batch(name, self._op_id, batch._host_rows)
                if dump_enabled:
                    self._last_output = batch
                yield batch
        # instrumented path
        bus.emit("op_open", op=name, op_id=self._op_id)
        # snapshot so op_close reports THIS execution's rows, not the
        # metric's lifetime total — bench reuses one plan object across
        # iterations, and profile_report sums rows across closes
        try:
            rows_at_open = rows.value
        except Exception:  # noqa: BLE001
            rows_at_open = None
        # dispatch plane (ISSUE 13): wired execs carry DISPATCH_METRICS
        # — snapshot them so one dispatch_stats record per execution
        # reports per-execution deltas (the gather_stats convention)
        disp = self.metrics.get(NUM_DISPATCHES)
        comp = self.metrics.get(COMPILE_TIME)
        disp_at_open = disp.value if disp is not None else None
        comp_at_open = comp.value if comp is not None else 0
        total_ns = 0
        nbatches = 0
        emit_batches = bus.level >= obs_events.DEBUG
        try:
            while True:
                if qctx is not None:
                    qctx.tick()
                t0 = time.perf_counter_ns()
                with annotate_op(name):
                    try:
                        batch = next(it)
                    except StopIteration:
                        return
                    except Exception:
                        self._dump_failure_inputs(name)
                        bus.emit("op_error", op=name, op_id=self._op_id)
                        raise
                    if decode:
                        batch = materialize_batch(batch, seam="boundary")
                step_ns = time.perf_counter_ns() - t0
                total_ns += step_ns
                nbatches += 1
                batches.add(1)
                if batch._host_rows is not None:
                    rows.add(batch._host_rows)
                else:
                    rows.add_device(batch.num_rows)
                if qctx is not None:
                    qctx.note_batch(name, self._op_id, batch._host_rows)
                if emit_batches:
                    # device_size_bytes() walks the whole pytree — only
                    # pay it when the DEBUG-level record will be kept
                    bus.emit("op_batch", op=name, op_id=self._op_id,
                             wall_ns=step_ns, rows=batch._host_rows,
                             bytes=batch.device_size_bytes())
                if dump_enabled:
                    self._last_output = batch
                yield batch
        finally:
            # reading the metric materializes pending device counts (one
            # stacked transfer, query-end only); the open-snapshot delta
            # makes op_close.rows per-execution, and on a fresh plan it
            # reconciles exactly with last_query_metrics() totals
            try:
                out_rows = rows.value - rows_at_open \
                    if rows_at_open is not None else None
            except Exception:  # noqa: BLE001 — close is best-effort
                out_rows = None
            bus.emit("op_close", op=name, op_id=self._op_id,
                     wall_ns=total_ns, batches=nbatches, rows=out_rows)
            if disp_at_open is not None \
                    and disp.value > disp_at_open:
                bus.emit("dispatch_stats", op=name, op_id=self._op_id,
                         dispatches=disp.value - disp_at_open,
                         compile_ns=(comp.value - comp_at_open
                                     if comp is not None else 0),
                         batches=nbatches)

    #: most recent batch this operator yielded (= a child's view of its
    #: input); consumed by the failure dump below
    _last_output: "ColumnarBatch" = None

    def _dump_failure_inputs(self, name: str) -> None:
        """On operator failure, dump the children's last-yielded batches —
        the failing operator's actual inputs (reference DumpUtils dump-
        failing-batches hooks) — plus the REAL active exception's
        traceback. Conf-gated; never masks the error."""
        try:
            import sys

            from ..config import DEBUG_DUMP_PATH, active_conf
            if not active_conf().get(DEBUG_DUMP_PATH):
                return
            from ..utils.dump import dump_on_error
            scope = dump_on_error(name)
            for c in self.children:
                if c._last_output is not None:
                    scope.observe(c._last_output)
            # called from the operator's except block: sys.exc_info() IS
            # the failure being dumped
            scope.__exit__(*sys.exc_info())
        except Exception:  # noqa: BLE001 — dumping is best-effort
            pass

    @property
    def child(self) -> "TpuExec":
        assert len(self.children) == 1, type(self).__name__
        return self.children[0]

    def collect(self) -> List[tuple]:
        """Materialize results. Opens a speculation scope: aggregates may
        run their fast masked-bucket tier and flag overflow on device; the
        flag costs one extra host read here, and a trip re-runs the plan
        with every operator on its exact tier."""
        from .speculation import force_exact, speculation_scope

        # late materialization (ISSUE 18): collect consumes root batches
        # through to_pylist -> fetch_batch_host, which decodes encoded
        # columns at the "output" seam — let them flow there instead of
        # double-decoding at the root's own _drive boundary
        self._encoded_ok_for_parent = True

        def run() -> List[tuple]:
            out: List[tuple] = []
            for batch in self.execute():
                out.extend(batch.to_pylist())
            return out

        with speculation_scope() as scope:
            out = run()
            if scope.tripped():
                with force_exact():
                    out = run()
        return out

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.node_description()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def node_description(self) -> str:
        return type(self).__name__

    def all_metrics(self, level: Optional[int] = None) -> Dict[str, int]:
        """Flat per-operator metric values, filtered to entries at or
        below `level` (None = the spark.rapids.sql.metrics.level conf) —
        the reference's ESSENTIAL/MODERATE/DEBUG visibility cut
        (GpuExec.scala:36-47). Pass DEBUG explicitly to see everything."""
        if level is None:
            level = metrics_level_from_conf()
        out = {}
        def walk(node, path, label):
            for name, m in node.metrics.items():
                if m.level <= level:
                    out[f"{path}{label}.{name}"] = m.value
            for i, c in enumerate(node.children):
                # the child ordinal disambiguates same-class siblings
                # (self-joins): without it both sides collide on one
                # key and one side's metrics silently vanish
                walk(c, f"{path}{label}/", f"{type(c).__name__}[{i}]")
        walk(self, "", type(self).__name__)
        return out
