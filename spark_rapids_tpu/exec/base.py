"""TpuExec — base of the columnar operator tree (reference GpuExec,
sql-plugin/.../GpuExec.scala:365 `doExecuteColumnar`; metric registry at
GpuExec.scala:49-116 with ESSENTIAL/MODERATE/DEBUG levels).

Operators form a tree; `execute()` returns an iterator of ColumnarBatch.
Each operator's device work is jax-traced per batch *shape bucket*, so a
pipeline of execs compiles into a small set of XLA programs reused across
batches. Host-side control (iteration, spill, retry, coalesce decisions)
stays in Python exactly where the reference keeps it in Scala.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Sequence

from ..columnar.batch import ColumnarBatch
from ..types import Schema

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2


class TpuMetric:
    """Accumulating operator metric (reference GpuMetric).

    Device-produced values (e.g. a traced row count) are accumulated as
    device scalars and only materialized when the metric is READ. A d2h
    sync in the steady-state batch loop costs orders of magnitude more
    than the kernels themselves (the analog of a cudaStreamSynchronize
    per batch), so `add_device` must never block.
    """

    __slots__ = ("name", "level", "_value", "_pending")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self._value = 0
        self._pending: List = []

    def add(self, v):
        self._value += v

    def add_device(self, scalar):
        """Accumulate a device scalar lazily (no sync until read)."""
        self._pending.append(scalar)

    @property
    def value(self):
        if self._pending:
            import jax.numpy as jnp
            pending, self._pending = self._pending, []
            # one stacked transfer, not one round trip per scalar
            self._value += int(jnp.sum(jnp.stack(
                [jnp.asarray(s).astype(jnp.int64) for s in pending])))
        return self._value

    @value.setter
    def value(self, v):
        self._pending = []
        self._value = v

    def ns_timer(self):
        return _NsTimer(self)


class _NsTimer:
    def __init__(self, metric: TpuMetric):
        self.metric = metric

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self.metric.add(time.perf_counter_ns() - self._t0)


# canonical metric names (reference GpuMetric companion, GpuExec.scala:49-96)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
CONCAT_TIME = "concatTime"
JOIN_TIME = "joinTime"
BUILD_TIME = "buildTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
NUM_TASKS_FALL_BACKED = "numTasksFallBacked"
SPILL_TIME = "spillTime"


class TpuExec:
    """Base columnar operator."""

    def __init__(self, *children: "TpuExec"):
        self.children: List[TpuExec] = list(children)
        self.metrics: Dict[str, TpuMetric] = {}
        for name in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES):
            self.metrics[name] = TpuMetric(name, ESSENTIAL)
        self.metrics[OP_TIME] = TpuMetric(OP_TIME, MODERATE)
        for name in self.additional_metrics():
            self.metrics[name] = TpuMetric(name, MODERATE)

    # -- subclass surface --------------------------------------------------
    @property
    def output_schema(self) -> Schema:
        raise NotImplementedError(type(self).__name__)

    def additional_metrics(self) -> Sequence[str]:
        return ()

    @property
    def output_grouped_by(self):
        """Grouping contract of this exec's output batches, or None.

        A tuple of frozensets of output column names: within every
        emitted batch, rows carrying equal values for (one representative
        of each class) are CONTIGUOUS, and the columns inside one class
        are pairwise equal per row (e.g. the two sides of an equi-join
        key). A downstream group-by whose keys pick a representative from
        every class (and nothing else) may skip its sort
        (ops/aggregate.groupby_aggregate pre_grouped)."""
        return None

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        raise NotImplementedError(type(self).__name__)

    # -- public ------------------------------------------------------------
    def execute(self) -> Iterator[ColumnarBatch]:
        """Final wrapper (reference GpuExec.doExecuteColumnar:365): counts
        output rows/batches around the operator's own iterator, with an
        xprof trace annotation per batch step (the reference's NVTX
        range; shows operator names over their XLA ops in timelines)."""
        from ..utils.tracing import annotate_op
        rows = self.metrics[NUM_OUTPUT_ROWS]
        batches = self.metrics[NUM_OUTPUT_BATCHES]
        name = type(self).__name__
        # retain last outputs ONLY when failure dumping is configured —
        # otherwise each operator would pin one device batch for the
        # whole query, stealing memory the spill machinery counts as free
        try:
            from ..config import DEBUG_DUMP_PATH, active_conf
            dump_enabled = bool(active_conf().get(DEBUG_DUMP_PATH))
        except Exception:  # noqa: BLE001 — conf unavailable early
            dump_enabled = False
        it = self.internal_execute()
        while True:
            with annotate_op(name):
                try:
                    batch = next(it)
                except StopIteration:
                    return
                except Exception:
                    self._dump_failure_inputs(name)
                    raise
            batches.add(1)
            if batch._host_rows is not None:
                rows.add(batch._host_rows)
            else:
                rows.add_device(batch.num_rows)
            if dump_enabled:
                self._last_output = batch
            yield batch

    #: most recent batch this operator yielded (= a child's view of its
    #: input); consumed by the failure dump below
    _last_output: "ColumnarBatch" = None

    def _dump_failure_inputs(self, name: str) -> None:
        """On operator failure, dump the children's last-yielded batches —
        the failing operator's actual inputs (reference DumpUtils dump-
        failing-batches hooks) — plus the REAL active exception's
        traceback. Conf-gated; never masks the error."""
        try:
            import sys

            from ..config import DEBUG_DUMP_PATH, active_conf
            if not active_conf().get(DEBUG_DUMP_PATH):
                return
            from ..utils.dump import dump_on_error
            scope = dump_on_error(name)
            for c in self.children:
                if c._last_output is not None:
                    scope.observe(c._last_output)
            # called from the operator's except block: sys.exc_info() IS
            # the failure being dumped
            scope.__exit__(*sys.exc_info())
        except Exception:  # noqa: BLE001 — dumping is best-effort
            pass

    @property
    def child(self) -> "TpuExec":
        assert len(self.children) == 1, type(self).__name__
        return self.children[0]

    def collect(self) -> List[tuple]:
        """Materialize results. Opens a speculation scope: aggregates may
        run their fast masked-bucket tier and flag overflow on device; the
        flag costs one extra host read here, and a trip re-runs the plan
        with every operator on its exact tier."""
        from .speculation import force_exact, speculation_scope

        def run() -> List[tuple]:
            out: List[tuple] = []
            for batch in self.execute():
                out.extend(batch.to_pylist())
            return out

        with speculation_scope() as scope:
            out = run()
            if scope.tripped():
                with force_exact():
                    out = run()
        return out

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.node_description()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def node_description(self) -> str:
        return type(self).__name__

    def all_metrics(self) -> Dict[str, int]:
        out = {}
        def walk(node, path):
            label = f"{type(node).__name__}"
            for name, m in node.metrics.items():
                out[f"{path}{label}.{name}"] = m.value
            for i, c in enumerate(node.children):
                walk(c, f"{path}{label}/")
        walk(self, "")
        return out
