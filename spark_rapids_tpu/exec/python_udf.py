"""Pandas UDF exec family — grouped map (applyInPandas), grouped
aggregate, mapInPandas/mapInBatch, cogrouped map and window-in-pandas.

Reference: the 14-file exec family under
sql-plugin/src/main/scala/org/apache/spark/sql/rapids/execution/python/
(GpuFlatMapGroupsInPandasExec.scala:79, GpuAggregateInPandasExec.scala,
GpuMapInBatchExec.scala, GpuFlatMapCoGroupsInPandasExec.scala,
GpuWindowInPandasExecBase.scala). There the plugin keeps data columnar on
the GPU and ships Arrow batches over a socket to a Python worker; here
the engine IS the Python process, so the transport collapses to one
device→Arrow fetch per batch and the group slicing that the reference
does with cuDF contiguous_split becomes host-side pandas groupby over
engine-computed key columns (expressions evaluate on device first).

Shape notes:
- group completeness: like the reference (which requires an upstream
  hash partitioning), each exec sees its full input; all child batches
  fold into one pandas frame before grouping;
- NULL keys form a real group (Spark groupBy semantics; dropna=False);
- output re-enters the engine through Arrow with the declared schema, so
  dtype mismatches fail loudly at the boundary, not downstream.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Sequence, Tuple

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..expr.core import Expression, col
from ..types import DataType, Schema, StructField, to_arrow as _t2a
from .base import DISPATCH_METRICS, OP_TIME, TpuExec
from .basic import bind_projection, eval_projection, projection_schema

_KEY_PREFIX = "__pandas_gkey_"


def _batch_to_pandas(batch: ColumnarBatch):
    return batch.to_arrow().to_pandas()


def _pandas_to_batches(pdf, schema: Schema,
                       max_rows: int = 1 << 20) -> List[ColumnarBatch]:
    import pyarrow as pa
    arrow_schema = pa.schema([pa.field(f.name, _t2a(f.data_type))
                              for f in schema.fields])
    if len(pdf) == 0:
        return []
    pdf = pdf[[f.name for f in schema.fields]]
    out = []
    for s in range(0, len(pdf), max_rows):
        table = pa.Table.from_pandas(pdf.iloc[s:s + max_rows],
                                     schema=arrow_schema,
                                     preserve_index=False)
        out.append(ColumnarBatch.from_arrow(table))
    return out


class _PandasExecBase(TpuExec):
    """Shared drive: evaluate (child cols + key exprs) on device per
    batch, fetch each to pandas, concat, and expose host group frames."""

    def __init__(self, key_exprs: Sequence[Expression], child: TpuExec):
        super().__init__(child)
        from ..expr.predicates import IsNotNull
        in_schema = child.output_schema
        self._key_names = [f"{_KEY_PREFIX}{i}"
                           for i in range(len(key_exprs))]
        # one validity lane per key: pandas folds NULL into NaN at the
        # to_pandas boundary, but Spark groups NaN as a DISTINCT non-null
        # value — the (value, is_not_null) pair keeps them apart
        self._key_valid_names = [f"{n}_valid" for n in self._key_names]
        pre = [col(n) for n in in_schema.names] + [
            k.alias(n) for k, n in zip(key_exprs, self._key_names)] + [
            IsNotNull(k).alias(n)
            for k, n in zip(key_exprs, self._key_valid_names)]
        self._pre_bound = bind_projection(pre, in_schema)
        self._pre_schema = projection_schema(pre, in_schema)
        from ..obs.dispatch import instrument
        self._jit_pre = instrument(
            lambda b: eval_projection(self._pre_bound, b,
                                      self._pre_schema),
            label="PandasExec.pre_project", owner=self)

    def additional_metrics(self):
        return DISPATCH_METRICS

    def _host_frame(self):
        import pandas as pd
        frames = [_batch_to_pandas(self._jit_pre(b))
                  for b in self.child.execute()]
        frames = [f for f in frames if len(f)]
        if not frames:
            return None
        return pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0]

    def _groups(self, pdf):
        """Yield (key_tuple, group_pdf_without_key_cols)."""
        if not self._key_names:
            yield (), pdf
            return
        nk = len(self._key_names)
        by = self._key_names + self._key_valid_names
        for key, g in pdf.groupby(by, sort=True, dropna=False):
            if not isinstance(key, tuple):
                key = (key,)
            vals, valids = key[:nk], key[nk:]
            key = tuple(None if not ok else k
                        for k, ok in zip(vals, valids))
            yield key, g.drop(columns=by)


class GroupedMapInPandasExec(_PandasExecBase):
    """df.groupBy(keys).applyInPandas(fn, schema) — reference
    GpuFlatMapGroupsInPandasExec.scala:79."""

    def __init__(self, key_exprs: Sequence[Expression], fn: Callable,
                 out_schema: Schema, child: TpuExec):
        super().__init__(key_exprs, child)
        self.fn = fn
        self._out_schema = out_schema

    @property
    def output_schema(self) -> Schema:
        return self._out_schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        import pandas as pd
        with self.metrics[OP_TIME].ns_timer():
            pdf = self._host_frame()
            if pdf is None:
                return
            outs = []
            for _, g in self._groups(pdf):
                r = self.fn(g.reset_index(drop=True))
                assert isinstance(r, pd.DataFrame), \
                    "applyInPandas function must return a pandas DataFrame"
                if len(r):
                    outs.append(r)
            if not outs:
                return
            merged = pd.concat(outs, ignore_index=True) \
                if len(outs) > 1 else outs[0]
            yield from _pandas_to_batches(merged, self._out_schema)


class AggregateInPandasExec(_PandasExecBase):
    """df.groupBy(keys).agg(pandas_udf) — one scalar per (group, agg);
    output = key columns + agg columns. Reference
    GpuAggregateInPandasExec.scala."""

    def __init__(self, key_exprs: Sequence[Expression],
                 aggs: Sequence[Tuple[Callable, str, DataType,
                                      Sequence[Expression]]],
                 key_names: Sequence[str], child: TpuExec):
        # aggs: (fn, output name, result type, input expressions); fn
        # receives one pandas Series per input expression
        self._aggs = list(aggs)
        self._out_key_names = list(key_names)
        all_inputs: List[Expression] = [e for _, _, _, ins in self._aggs
                                        for e in ins]
        # ride the key machinery: keys first, then agg inputs
        self._n_keys = len(key_exprs)
        super().__init__(list(key_exprs) + list(all_inputs), child)
        self._input_names = self._key_names[self._n_keys:]
        self._agg_slots = []
        pos = 0
        for _, _, _, ins in self._aggs:
            self._agg_slots.append(
                [self._input_names[pos + j] for j in range(len(ins))])
            pos += len(ins)
        # grouping must NOT include the agg inputs (nor their validity
        # lanes)
        self._key_names = self._key_names[: self._n_keys]
        self._key_valid_names = self._key_valid_names[: self._n_keys]

    @property
    def output_schema(self) -> Schema:
        from ..expr.core import resolve
        child_sch = self.child.output_schema
        fields = []
        for name, kexpr in zip(self._out_key_names,
                               self._pre_schema.fields[
                                   len(child_sch.fields):
                                   len(child_sch.fields) + self._n_keys]):
            fields.append(StructField(name, kexpr.data_type))
        for _, name, rt, _ in self._aggs:
            fields.append(StructField(name, rt))
        return Schema(tuple(fields))

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        import pandas as pd
        with self.metrics[OP_TIME].ns_timer():
            pdf = self._host_frame()
            if pdf is None:
                return
            rows: List[tuple] = []
            for key, g in self._groups(pdf):
                vals = []
                for (fn, _, _, _), slots in zip(self._aggs,
                                                self._agg_slots):
                    vals.append(fn(*[g[s].reset_index(drop=True)
                                     for s in slots]))
                rows.append(tuple(key) + tuple(vals))
            out = pd.DataFrame(
                rows, columns=[f.name for f in self.output_schema.fields])
            yield from _pandas_to_batches(out, self.output_schema)


class MapInBatchExec(TpuExec):
    """df.mapInPandas(fn, schema): fn(iterator of pandas DataFrames) ->
    iterator of DataFrames, streamed batch-by-batch. Reference
    GpuMapInBatchExec.scala (base of mapInPandas / mapInArrow)."""

    def __init__(self, fn: Callable, out_schema: Schema, child: TpuExec):
        super().__init__(child)
        self.fn = fn
        self._out_schema = out_schema

    @property
    def output_schema(self) -> Schema:
        return self._out_schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        with self.metrics[OP_TIME].ns_timer():
            def frames():
                for b in self.child.execute():
                    pdf = _batch_to_pandas(b)
                    if len(pdf):
                        yield pdf
            for out in self.fn(frames()):
                yield from _pandas_to_batches(out, self._out_schema)


class CoGroupedMapInPandasExec(TpuExec):
    """cogroup(left.groupBy(k), right.groupBy(k)).applyInPandas —
    fn(left_group_df, right_group_df) per key in either side (missing
    side passes an empty frame). Reference
    GpuFlatMapCoGroupsInPandasExec.scala."""

    def __init__(self, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], fn: Callable,
                 out_schema: Schema, left: TpuExec, right: TpuExec):
        super().__init__(left, right)
        self.fn = fn
        self._out_schema = out_schema
        self._lside = _PandasSide(left_keys, left)
        self._rside = _PandasSide(right_keys, right)

    @property
    def output_schema(self) -> Schema:
        return self._out_schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        import pandas as pd
        with self.metrics[OP_TIME].ns_timer():
            lg = self._lside.host_groups()
            rg = self._rside.host_groups()
            keys = list(lg.keys()) + [k for k in rg.keys() if k not in lg]
            outs = []
            lempty = self._lside.empty_frame()
            rempty = self._rside.empty_frame()
            for k in keys:
                r = self.fn(lg.get(k, lempty), rg.get(k, rempty))
                assert isinstance(r, pd.DataFrame)
                if len(r):
                    outs.append(r)
            if not outs:
                return
            merged = pd.concat(outs, ignore_index=True) \
                if len(outs) > 1 else outs[0]
            yield from _pandas_to_batches(merged, self._out_schema)


class _PandasSide(_PandasExecBase):
    """One cogroup input: owns its key projection and host grouping."""

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def host_groups(self):
        pdf = self._host_frame()
        if pdf is None:
            return {}
        return {k: g.reset_index(drop=True) for k, g in self._groups(pdf)}

    def empty_frame(self):
        import pandas as pd
        return pd.DataFrame(
            {f.name: pd.Series([], dtype=object)
             for f in self.child.output_schema.fields})

    def internal_execute(self):  # never driven directly
        raise NotImplementedError


class WindowInPandasExec(_PandasExecBase):
    """Whole-partition window over a pandas UDF: fn(input series...) ->
    scalar, broadcast to every row of the partition (the reference's
    GpuWindowInPandasExec main case — unbounded-to-unbounded frames,
    GpuWindowInPandasExecBase.scala)."""

    def __init__(self, part_exprs: Sequence[Expression],
                 wins: Sequence[Tuple[Callable, str, DataType,
                                      Sequence[Expression]]],
                 child: TpuExec):
        self._wins = list(wins)
        all_inputs: List[Expression] = []
        for _, _, _, ins in self._wins:
            all_inputs.extend(ins)
        self._n_parts = len(part_exprs)
        super().__init__(list(part_exprs) + all_inputs, child)
        self._win_names = self._key_names[self._n_parts:]
        self._win_slots = []
        pos = 0
        for _, _, _, ins in self._wins:
            self._win_slots.append(
                [self._win_names[pos + j] for j in range(len(ins))])
            pos += len(ins)
        self._key_names = self._key_names[: self._n_parts]
        self._key_valid_names = self._key_valid_names[: self._n_parts]

    @property
    def output_schema(self) -> Schema:
        fields = list(self.child.output_schema.fields)
        for _, name, rt, _ in self._wins:
            fields.append(StructField(name, rt))
        return Schema(tuple(fields))

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        import pandas as pd
        with self.metrics[OP_TIME].ns_timer():
            pdf = self._host_frame()
            if pdf is None:
                return
            n_child = len(self.child.output_schema.fields)
            child_names = [f.name for f in self.child.output_schema.fields]
            outs = []
            for _, g in self._groups(pdf):
                piece = g[child_names].reset_index(drop=True)
                for (fn, name, _, _), slots in zip(self._wins,
                                                   self._win_slots):
                    val = fn(*[g[s].reset_index(drop=True)
                               for s in slots])
                    piece[name] = val
                outs.append(piece)
            merged = pd.concat(outs, ignore_index=True) \
                if len(outs) > 1 else outs[0]
            yield from _pandas_to_batches(merged, self.output_schema)
