"""Exchange execs — planner-produced repartitioning over the device mesh
(reference GpuShuffleExchangeExecBase.scala:167 planning entry,
prepareBatchShuffleDependency:277 device-side split, and the shuffle-plugin
UCX transport; SURVEY §2.5).

TPU-first redesign: no shuffle service, no serialized blocks. An exchange
is ONE compiled SPMD program over the mesh — evaluate the partition key
expressions on device, hash-partition rows (Spark-exact murmur3 pmod),
`lax.all_to_all` over the ICI axis, compact the received rows. XLA lowers
the collective to ICI neighbor exchanges with no host involvement.

Receive-buffer sizing (review finding r1: the worst-case default was
n_parts × capacity): a histogram program measures the actual max partition
load and max string byte length across all devices first — ONE host sync
per exchange, amortized over the whole stage — so the slot capacity fits
the data and fixed-width string lanes can never truncate.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..columnar.batch import ColumnarBatch, empty_batch
from ..columnar.column import StringColumn, bucket_capacity
from ..expr.core import Expression
from ..ops.basic import active_mask
from ..ops.strings import string_lengths
from ..parallel.exchange import (exchange_columns, negotiate_slot_cap,
                                 partition_ids)
from ..parallel.mesh import DATA_AXIS, active_mesh, mesh_axis_size
from ..types import Schema
from ..obs import events as obs_events
from ..obs import phase as obs_phase
from ..obs.dispatch import instrument
from .base import (BROADCAST_TIME, DEBUG, DISPATCH_METRICS, ESSENTIAL,
                   GATHER_METRICS,
                   GATHER_TIME, MODERATE,
                   NUM_GATHERS, NUM_INPUT_BATCHES, NUM_INPUT_ROWS,
                   NUM_OUTPUT_BATCHES,
                   NUM_OUTPUT_ROWS, NUM_UPLOADS, OP_TIME, PARTITION_SIZE,
                   PIPELINE_STAGE_METRICS, SHUFFLE_PACK_TIME,
                   SHUFFLE_READ_TIME, SHUFFLE_WRITE_TIME,
                   UPLOAD_METRICS, UPLOAD_PACK_TIME, TpuExec)
from .basic import InMemoryScanExec, bind_projection
from .coalesce import concat_batches


def _squeeze0(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _expand0(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


def _host_key_array(col, n: int, idx=None):
    """Vectorized host materialization of a range-partition sort key
    (ISSUE 9 satellite): fixed-width columns become an object array via
    one astype (floats widened to f64 first, so NaN checks keep seeing
    python floats), strings decode from one contiguous bytes snapshot.
    Returns None for nested types (the caller falls back to to_pylist).
    `idx` restricts to sampled rows."""
    import numpy as np

    from ..columnar.column import Column, StringColumn
    from ..types import BinaryType
    if type(col) is Column:
        data = np.asarray(col.data)[:n]
        valid = np.asarray(col.validity)[:n]
        if idx is not None:
            data, valid = data[idx], valid[idx]
        if data.dtype.kind == "f":
            data = data.astype(np.float64)
        out = data.astype(object)  # python scalars, like .item()
        out[~valid] = None
        return out
    if isinstance(col, StringColumn):
        offsets = np.asarray(col.offsets)
        valid = np.asarray(col.validity)
        buf = np.asarray(col.data).tobytes()
        binary = isinstance(col.dtype, BinaryType)
        rows = range(n) if idx is None else idx
        out = np.empty(n if idx is None else len(idx), dtype=object)
        for j, i in enumerate(rows):
            if valid[i]:
                raw = buf[offsets[i]: offsets[i + 1]]
                out[j] = raw if binary else raw.decode("utf-8")
        return out
    return None


class ShuffleExchangeExec(TpuExec):
    """Hash-repartition child output across the mesh so rows with equal
    partition-key values colocate on one device shard.

    With no active mesh (or a 1-device mesh) the exchange is the identity —
    the single-partition plan needs no data movement. Otherwise the flat
    stream yields each shard's staged PIECES in partition order (round 5:
    one piece at a time, a skewed shard is never concatenated whole);
    consumers that need partition boundaries use execute_partitions()."""

    def __init__(self, partition_exprs: Sequence[Expression], child: TpuExec,
                 mesh=None):
        super().__init__(child)
        self.partition_exprs = list(partition_exprs)
        self._mesh = mesh if mesh is not None else active_mesh()
        self._bound = bind_projection(self.partition_exprs,
                                      child.output_schema)
        self._jit_measure = instrument(
            self._measure_kernel,
            label="ShuffleExchangeExec.measure", owner=self)
        self._steps = {}

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return ((NUM_INPUT_BATCHES, DEBUG), (NUM_INPUT_ROWS, DEBUG),
                (PARTITION_SIZE, ESSENTIAL)) + PIPELINE_STAGE_METRICS \
            + DISPATCH_METRICS

    @property
    def runs_own_pipeline_stage(self) -> bool:
        # _drain_partition prefetches staged shard pieces through its
        # own pipelined() stage — a consumer must not stack another
        return True

    @property
    def n_partitions(self) -> int:
        return 1 if self._mesh is None else mesh_axis_size(self._mesh)

    # -- kernels -----------------------------------------------------------
    def _local_pid(self, local: ColumnarBatch, n: int):
        keys = [e.columnar_eval(local) for e in self._bound]
        return partition_ids(keys, local.num_rows, local.capacity, n)

    def _measure_kernel(self, stacked):
        """Per-device partition histogram + max string byte length. Runs
        vmapped over the device axis (it is pure per-device measurement —
        no collective), one host sync for both scalars."""
        n = self.n_partitions

        def per_dev(local: ColumnarBatch):
            pid = self._local_pid(local, n)
            ones = jnp.where(pid < n, jnp.int32(1), jnp.int32(0))
            counts = jax.ops.segment_sum(ones, pid.astype(jnp.int32),
                                         num_segments=n + 1)
            max_count = jnp.max(counts[:n])
            max_len = jnp.int32(0)
            act = active_mask(local.num_rows, local.capacity)
            for c in local.columns:
                if isinstance(c, StringColumn):
                    lens = string_lengths(c)
                    max_len = jnp.maximum(
                        max_len, jnp.max(jnp.where(act, lens, 0)))
            return max_count, max_len, counts[:n]

        max_count, max_len, totals = jax.vmap(per_dev)(stacked)
        return jnp.max(max_count), jnp.max(max_len), jnp.sum(totals,
                                                             axis=0)

    def _get_step(self, cap: int, slot_cap: int, width: int):
        key = (cap, slot_cap, width)
        step = self._steps.get(key)
        if step is not None:
            return step
        n = self.n_partitions
        schema = self.output_schema

        def spmd(stacked):
            local = _squeeze0(stacked)
            pid = self._local_pid(local, n)
            cols, n_recv = exchange_columns(
                list(local.columns), (), local.num_rows, local.capacity,
                DATA_AXIS, n, slot_cap=slot_cap, string_width=width,
                pid=pid)
            return _expand0(ColumnarBatch(cols, n_recv, schema))

        from ..parallel.mesh import shard_map_compat
        step = instrument(shard_map_compat(
            spmd, mesh=self._mesh, in_specs=P(DATA_AXIS),
            out_specs=P(DATA_AXIS)),
            label="ShuffleExchangeExec.exchange_step", owner=self)
        self._steps[key] = step
        return step

    def _exchange_round(self, batches: List[ColumnarBatch]):
        """One SPMD exchange over a bounded group of input batches;
        returns the n received shard batches."""
        from ..parallel.distributed import stack_batches, unstack_batches
        n = self.n_partitions
        schema = self.output_schema
        groups = [batches[d::n] for d in range(n)]
        per_dev = []
        for g in groups:
            if not g:
                per_dev.append(empty_batch(schema))
            elif len(g) == 1:
                per_dev.append(g[0])
            else:
                per_dev.append(concat_batches(g, schema))
        cap = max(b.capacity for b in per_dev)
        per_dev = [b.sized_to(cap) for b in per_dev]
        stacked = stack_batches(per_dev)

        max_count, max_len, totals = self._jit_measure(stacked)
        # one host sync per ROUND: size the receive buffer to the
        # measured max partition load, and string lanes to the measured
        # max byte length (truncation structurally impossible)
        slot_cap = negotiate_slot_cap(int(max_count), cap)
        width = max(8, (int(max_len) + 7) // 8 * 8)

        out = self._get_step(cap, slot_cap, width)(stacked)
        import numpy as _np
        return list(unstack_batches(out, n)), _np.asarray(totals)

    # -- drive -------------------------------------------------------------
    def internal_execute(self) -> Iterator[ColumnarBatch]:
        """Flat drive: staged shard pieces stream out one at a time in
        partition order (round 5, ADVICE r3 #2 resolved for real: a
        skewed shard is no longer concatenated whole at yield — peak
        device memory is one round of input + one staged PIECE).
        Consumers that need partition boundaries (ShuffledHashJoinExec,
        PartitionWiseSortExec) use execute_partitions() instead."""
        for gen in self.execute_partitions():
            yield from gen

    def _stream_single(self) -> Iterator[ColumnarBatch]:
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        for b in self.child.execute():
            in_batches.add(1)
            if b._host_rows is not None:
                in_rows.add(b._host_rows)
            else:
                in_rows.add_device(b.num_rows)
            yield b

    def execute_partitions(self) -> Iterator[Iterator[ColumnarBatch]]:
        """One lazy batch-generator per partition, in partition order.
        Each generator unspills its staged pieces one at a time."""
        if self.n_partitions == 1:
            yield self._stream_single()
            return
        staged = self._run_rounds()
        schema = self.output_schema
        for d in range(self.n_partitions):
            yield self._drain_partition(staged[d], schema)

    def _drain_partition(self, pieces, schema) -> Iterator[ColumnarBatch]:
        from ..columnar.batch import empty_batch as _eb
        out_rows = self.metrics[NUM_OUTPUT_ROWS]
        out_batches = self.metrics[NUM_OUTPUT_BATCHES]
        if not pieces:
            out_batches.add(1)
            yield _eb(schema)
            return

        def unspill() -> Iterator[ColumnarBatch]:
            it = iter(pieces)
            try:
                for sp in it:
                    try:
                        b = sp.get_batch()
                        sp.release()
                    except BaseException:
                        # a failed promotion (e.g. TpuRetryOOM escaping
                        # the retry loop) must still drop THIS piece's
                        # catalog entry, not just the unreached tail
                        sp.close()
                        raise
                    sp.close()
                    yield b
            finally:
                for sp in it:  # early close: drop the staged remainder
                    sp.close()

        # pipelined shuffle read (ISSUE 3): the unspill/host->device
        # promotion of piece k+1 overlaps the consumer's compute on k
        stage = self.pipeline_stage(unspill(), "exchange-read")
        try:
            for b in stage:
                out_batches.add(1)
                if b._host_rows is not None:
                    out_rows.add(b._host_rows)
                else:
                    out_rows.add_device(b.num_rows)
                yield b
        finally:
            stage.close()

    def _run_rounds(self):
        """Streamed, bounded rounds (round-2 verdict item 6): child
        batches flow through the ICI exchange in fixed-byte rounds; each
        round's received shards stage as SPILLABLE batches. Returns the
        per-partition staged piece lists."""
        from ..config import EXCHANGE_ROUND_BYTES, active_conf
        from ..memory.spillable import SpillableBatch

        n = self.n_partitions
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        round_budget = active_conf().get(EXCHANGE_ROUND_BYTES)
        staged: List[List[SpillableBatch]] = [[] for _ in range(n)]
        pending: List[ColumnarBatch] = []
        pending_bytes = 0
        self.rounds = 0
        self._part_totals = None
        # runtime statistics (ISSUE 11): the mesh exchange measures
        # exact per-partition ROW counts per round (its histogram
        # program) — bytes stay on device, so its skew basis is rows
        from ..obs import stats as obs_stats
        stats_rec = obs_stats.ExchangeRecorder(type(self).__name__,
                                               self._op_id, n)

        def flush():
            nonlocal pending, pending_bytes
            if not pending:
                return
            with self.metrics[OP_TIME].ns_timer():
                shards, totals = self._exchange_round(pending)
            # exact per-partition totals accumulate ACROSS rounds; the
            # metric is the max over partitions of the whole-stage totals
            self._part_totals = totals if self._part_totals is None \
                else self._part_totals + totals
            stats_rec.record_map(totals.tolist(), None, 0)
            for d, shard in enumerate(shards):
                staged[d].append(SpillableBatch.from_batch(shard))
            pending = []
            pending_bytes = 0
            self.rounds += 1

        for b in self.child.execute():
            in_batches.add(1)
            if b._host_rows is not None:
                in_rows.add(b._host_rows)
            else:
                in_rows.add_device(b.num_rows)
            pending.append(b)
            pending_bytes += b.device_size_bytes()
            if pending_bytes >= round_budget:
                flush()
        flush()
        if self._part_totals is not None:
            max_part = int(self._part_totals.max())
            self.metrics[PARTITION_SIZE].add(max_part)
            obs_events.emit("exchange", exec="ShuffleExchangeExec",
                            op_id=self._op_id, partitions=self.n_partitions,
                            rounds=self.rounds, max_partition_bytes=max_part)
            stats_rec.finish_and_emit()
        return staged

    def node_description(self):
        return (f"ShuffleExchangeExec[n={self.n_partitions}, "
                f"keys={self.partition_exprs!r}]")


class HostShuffleExchangeExec(TpuExec):
    """Hash-repartition through the host shuffle manager (the reference's
    MULTITHREADED shuffle mode, RapidsShuffleInternalManagerBase.scala:238/
    :569): partition ids are computed on device (Spark-exact murmur3 pmod),
    rows are gathered into compact host blocks, serialized + LZ4-compressed
    on the writer thread pool into per-map data+index files, then read back
    partition by partition on the reader pool.

    This is the always-works exchange: it needs no mesh, bounds device
    memory by partition (the out-of-core repartition the reference gets
    from Spark's file shuffle), and survives any partition count. The
    flat stream yields each partition's decoded blocks in partition
    order WITHOUT concatenation (round 5); partition-aware consumers
    take boundaries from execute_partitions()."""

    def __init__(self, partition_exprs: Sequence[Expression], child: TpuExec,
                 n_partitions: int, conf=None, partitioning: str = "hash",
                 range_order=None):
        """partitioning ∈ hash | roundrobin | single | range (the
        reference's GpuHashPartitioningBase / GpuRoundRobinPartitioning /
        GpuSinglePartitioning / GpuRangePartitioner). Range mode takes
        `range_order` = (ordinal, ascending, nulls_first) on the child
        schema and samples the data for split bounds like
        GpuRangePartitioner's reservoir sampling."""
        super().__init__(child)
        from ..config import SHUFFLE_DEVICE_PARTITION, active_conf
        self.partition_exprs = list(partition_exprs or [])
        self.n_partitions = int(n_partitions)
        self.partitioning = partitioning
        self.range_order = range_order
        self._conf = conf or active_conf()
        if partitioning == "hash":
            assert self.partition_exprs, "hash partitioning needs keys"
            self._bound = bind_projection(self.partition_exprs,
                                          child.output_schema)
            self._jit_pid = instrument(
                self._pid_kernel,
                label="HostShuffleExchangeExec.pid", owner=self)
        self._rr_offset = 0
        # device partition split (ISSUE 9): hash/roundrobin/single pids
        # are device-computable, so the split runs as ONE compiled
        # program (pid -> counts + stable permutation -> packed reorder
        # through the gather engine) + ONE packed D2H; range keeps the
        # host lane — its sampled split bounds are host objects
        self._device_partition = (
            partitioning in ("hash", "roundrobin", "single")
            and bool(self._conf.get(SHUFFLE_DEVICE_PARTITION)))
        # fused split+pack (ISSUE 10 satellite, the round-9 TODO): the
        # D2H packer is traced INTO the partition-split program, so a
        # written batch costs ONE dispatch (pid -> counts + permutation
        # -> packed reorder -> packed uint8 buffer) + ONE D2H copy,
        # instead of a split dispatch followed by a pack dispatch
        from ..columnar import transfer as _transfer
        self._jit_split = instrument(
            lambda b, off: _transfer.pack_split(
                *self._split_kernel(b, off)),
            label="HostShuffleExchangeExec.split_pack", owner=self)
        # ICI device-resident lane (ISSUE 16): when the active mesh's
        # axis size equals this exchange's partition count, map output
        # is exchanged device-to-device (jax.lax.all_to_all) instead of
        # being serialized through the host shuffle files; the host
        # lane below stays the fallback tier (range mode, mismatched
        # partition counts, open breaker, failed collective round)
        from ..config import SHUFFLE_ICI_ENABLED
        self._ici_enabled = bool(self._conf.get(SHUFFLE_ICI_ENABLED))
        self._ici_mesh = None
        # adaptive skew shield (ISSUE 19): set by a downstream
        # partition-aware probe consumer (ShuffledHashJoinExec) on its
        # STREAM-side exchange — a skew split needs map-output-granular
        # host files, so an armed splitter keeps this execution off the
        # ICI all-to-all (uneven splits don't fit the static device
        # collective); measured write bytes surface for the
        # single-build conversion consult
        self._adaptive_probe_split = False
        self._adaptive_write_bytes: Optional[int] = None
        self._ici_measure = None
        self._ici_steps = {}
        #: running per-round high-water marks (ISSUE 11 statistics as
        #: the slot-cap negotiation hint): flooring later rounds by the
        #: earlier measured load keeps the compiled step shape stable
        self._ici_cap_hint = 0
        self._ici_width_hint = 8
        #: host unpack templates per compiled shape key (abstract shapes
        #: via eval_shape — no device work, no gather-recorder side
        #: effects: eval_shape runs OUTSIDE the tracker's observe)
        self._split_templates = {}
        from ..ops.gather import GatherTracker
        self._gather_track = GatherTracker(self.metrics[NUM_GATHERS],
                                           self.metrics[GATHER_TIME])

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return ((NUM_INPUT_BATCHES, DEBUG), (NUM_INPUT_ROWS, DEBUG),
                (PARTITION_SIZE, ESSENTIAL), SHUFFLE_WRITE_TIME,
                SHUFFLE_READ_TIME, (SHUFFLE_PACK_TIME, MODERATE)) \
            + GATHER_METRICS + UPLOAD_METRICS + PIPELINE_STAGE_METRICS \
            + DISPATCH_METRICS

    @property
    def runs_own_pipeline_stage(self) -> bool:
        # _read_partition prefetches fetch + LZ4 decode through its own
        # pipelined() stage — a consumer must not stack another
        return True

    def _fingerprint_extras(self):
        # everything this exec's traced programs depend on beyond the
        # child subtree: the partitioning mode and count, the bound key
        # expressions, the range ordering and the two lane gates
        # (ISSUE 16: the ICI exchange step is a _site program — equal
        # fingerprints let a later identical plan reuse it compiled)
        return ("host_shuffle", self.partitioning, self.n_partitions,
                tuple(repr(e) for e in self.partition_exprs),
                self.range_order, self._device_partition,
                self._ici_enabled)

    def _pid_kernel(self, batch: ColumnarBatch):
        keys = [e.columnar_eval(batch) for e in self._bound]
        return partition_ids(keys, batch.num_rows, batch.capacity,
                             self.n_partitions)

    # -- device partition split (ISSUE 9) ----------------------------------
    def _split_kernel(self, batch: ColumnarBatch, rr_offset):
        """One traced program: pid -> per-partition counts + pid-stable
        permutation -> partition-major reorder through the gather engine
        (ops/partition_split.py). rr_offset is only read on the
        roundrobin lane (hash pids come from the key expressions)."""
        from ..ops.partition_split import partition_table, reorder_columns
        n = self.n_partitions
        if self.partitioning == "hash":
            pid = self._pid_kernel(batch)
        else:  # roundrobin
            iota = jnp.arange(batch.capacity, dtype=jnp.int32)
            pid = (iota + rr_offset) % jnp.int32(n)
            pid = jnp.where(active_mask(batch.num_rows, batch.capacity),
                            pid, jnp.int32(n))
        counts, order = partition_table(pid, batch.num_rows,
                                        batch.capacity, n)
        return counts, reorder_columns(batch.columns, order,
                                       batch.num_rows)

    def _device_split(self, b: ColumnarBatch, n: int):
        """Split one batch on device: returns (host columns in
        partition-major order, exclusive bounds (n_partitions+1,)).
        The split, the reorder AND the D2H packer run as ONE fused
        traced program (ISSUE 10 satellite) whose packed uint8 buffer
        lands the count table and the reordered payload in ONE D2H copy
        — the offset table is the split's only host-synced control
        value, and a written batch costs exactly one dispatch."""
        import numpy as np
        from ..columnar import transfer
        if self.partitioning == "single":
            # no permutation needed: the batch IS partition 0's slice
            cols, _n = transfer.fetch_batch_host(b)
            counts = np.zeros(self.n_partitions, np.int64)
            counts[0] = n
        else:
            off = self._rr_offset
            if self.partitioning == "roundrobin":
                self._rr_offset = int((self._rr_offset + n)
                                      % self.n_partitions)
            # observe keyed by the compiled program shape so the
            # trace-time gather counts replay exactly on jit cache hits
            key = (self.partitioning, b.capacity, tuple(
                (tuple(leaf.shape), str(leaf.dtype))
                for leaf in jax.tree_util.tree_leaves(list(b.columns))))
            tmpl = self._split_templates.get(key)
            if tmpl is None:
                # abstract column shapes for the host-side unpack of the
                # fused program's packed buffer (computed BEFORE observe:
                # eval_shape re-traces the split and must not double the
                # tracker's structural gather counts)
                _c, tmpl = jax.eval_shape(self._split_kernel, b,
                                          jnp.int32(off))
                self._split_templates[key] = tmpl
            with self._gather_track.observe(key):
                buf_dev = self._jit_split(b, jnp.int32(off))
            buf = np.asarray(buf_dev)  # the ONE d2h copy
            transfer.note_d2h(buf.nbytes)
            counts, cols = transfer.unpack_split_host(
                buf, tmpl, self.n_partitions)
        bounds = np.zeros(self.n_partitions + 1, np.int64)
        np.cumsum(counts, out=bounds[1:])
        return cols, bounds

    def _write_map(self, b: ColumnarBatch, n: int, range_bounds, handle,
                   mgr, map_id: int, register: bool = True):
        """Partition + serialize + write one map task's output, on the
        lane the conf selects. Returns (writer, lane, pack_ns,
        rows_per_partition) — the row counts feed the runtime-statistics
        plane (ISSUE 11) and come free from the work each lane already
        did (the split's count table / the host partition batches). Both
        the steady-state write loop and the partition-recovery recompute
        route through here, so recovered map outputs replay the exact
        lane (and round-robin offsets) of the original write."""
        import time as _time

        import numpy as np
        from ..shuffle.manager import (HostShuffleWriter,
                                       partition_batch_host)
        writer = HostShuffleWriter(handle, map_id, mgr, self._conf)
        if self._device_partition and not n:
            # empty batch: zero frames, no partitioning work at all
            writer.write([[] for _ in range(self.n_partitions)],
                         register=register, lane="device")
            return writer, "device", 0, [0] * self.n_partitions
        if self._device_partition:
            t0 = _time.perf_counter_ns()
            cols, bounds = self._device_split(b, n)
            pack_ns = _time.perf_counter_ns() - t0
            self.metrics[SHUFFLE_PACK_TIME].add(pack_ns)
            from ..shuffle.manager import note_shuffle_write
            note_shuffle_write(pack_ns=pack_ns)
            packed = ColumnarBatch(cols, n, self.output_schema)
            writer.write_slices(packed, bounds, register=register)
            rows_pp = np.diff(np.asarray(bounds)).tolist()
            return writer, "device", pack_ns, rows_pp
        pid = self._pid_for(b, n, range_bounds)
        parts = partition_batch_host(b, pid, self.n_partitions)
        writer.write([[p] if p.num_rows_host else [] for p in parts],
                     register=register)
        return writer, "host", 0, [p.num_rows_host for p in parts]

    # -- partition id per mode --------------------------------------------
    def _host_keys(self, batch: ColumnarBatch, n: int, stride: int = 1):
        """First-sort-key values as host objects (None for nulls). The
        numeric and string common cases vectorize off the column's host
        buffers (one astype(object) / one bytes slice pass) instead of
        the old element-by-element object-array build; nested types keep
        the to_pylist fallback. With a stride, only the sampled rows
        materialize (the bounds pass needs ~512 values, not a
        full-column to_pylist)."""
        import numpy as np
        ordinal, _asc, _nf = self.range_order
        col = batch.columns[ordinal]
        idx = np.arange(0, n, stride, dtype=np.int64) if stride > 1 \
            else None
        fast = _host_key_array(col, n, idx)
        if fast is not None:
            return fast
        # nested fallback (array/map/struct/decimal128 sort keys)
        if idx is not None:
            from ..shuffle.serializer import host_gather_column
            col = host_gather_column(col, idx)
            n = len(idx)
        vals = col.to_pylist(n)
        return np.array(vals, dtype=object)

    @staticmethod
    def _is_nan(k) -> bool:
        return isinstance(k, float) and k != k

    def _range_bounds(self, key_samples):
        """Sampled split bounds over the first sort key (reference
        GpuRangePartitioner: sample → sort → n-1 evenly spaced bounds).
        NaN keys are excluded (they route to the greatest partition like
        Spark's NaN-sorts-last); all-equal keys collapse to one
        partition, which is still exact."""
        sample = [k for k in key_samples
                  if k is not None and not self._is_nan(k)]
        sample.sort()
        if not sample:
            return []
        idx = [len(sample) * (i + 1) // self.n_partitions
               for i in range(self.n_partitions - 1)]
        return [sample[min(i, len(sample) - 1)] for i in idx]

    def _pid_for(self, batch: ColumnarBatch, n: int, bounds):
        import numpy as np
        mode = self.partitioning
        if mode == "hash":
            return np.asarray(self._jit_pid(batch))[:n]
        if mode == "single":
            return np.zeros(n, np.int64)
        if mode == "roundrobin":
            pid = (np.arange(n, dtype=np.int64) + self._rr_offset) \
                % self.n_partitions
            self._rr_offset = int((self._rr_offset + n)
                                  % self.n_partitions)
            return pid
        if mode == "range":
            keys = self._host_keys(batch, n)
            _ordinal, asc, nulls_first = self.range_order
            null_pid = 0 if nulls_first else self.n_partitions - 1
            null_mask = np.array([k is None for k in keys], np.bool_)
            # NaN sorts greatest (Spark float ordering): last partition
            # ascending, first descending — never through searchsorted
            nan_mask = np.array([self._is_nan(k) for k in keys], np.bool_)
            safe = np.array([bounds[0] if (k is None or self._is_nan(k))
                             else k for k in keys], dtype=object) \
                if bounds else keys
            if bounds:
                idx = np.searchsorted(np.array(bounds, dtype=object),
                                      safe, side="left").astype(np.int64)
            else:
                idx = np.zeros(n, np.int64)
            idx[nan_mask] = self.n_partitions - 1
            if not asc:
                idx = self.n_partitions - 1 - idx
            idx[null_mask] = null_pid
            return idx
        raise ValueError(f"unknown partitioning {mode!r}")

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        import numpy as np  # noqa: F401 — used by _pid_for

        for gen in self.execute_partitions(flat=True):
            yield from gen

    def execute_partitions(self, flat: bool = False,
                           ) -> "Iterator[Iterator[ColumnarBatch]]":
        """One lazy batch-generator per partition, in partition order:
        decoded blocks stream WITHOUT concatenation (ADVICE r3 #2 — a
        skewed partition's device peak is one decoded block; the old
        contract concatenated the whole shard at yield). Flat consumers
        get the same pieces via internal_execute; partition-aware ones
        (ShuffledHashJoinExec, PartitionWiseSortExec) take the
        boundaries from here.

        Lane selection (ISSUE 16): the ICI device-resident lane when
        eligible — conf on, active mesh axis == partition count,
        device-computable partitioning, breaker closed — else the host
        serialize/LZ4 lane. The ICI lane itself degrades to the host
        lane mid-stream on a failed collective round.

        `flat` marks a partition-oblivious consumer (internal_execute):
        only then may the adaptive replanner coalesce adjacent tiny
        partitions into one read — partition-AWARE consumers (shuffled
        joins, partition-wise sort) must see the static boundaries or
        a zipped pair of exchanges would desync."""
        self._adaptive_write_bytes = None
        if self._ici_eligible():
            yield from self._execute_partitions_ici()
            return
        yield from self._execute_partitions_host(flat=flat)

    def _execute_partitions_host(self, override_source=None,
                                 stats_rec=None, flat: bool = False
                                 ) -> "Iterator[Iterator[ColumnarBatch]]":
        """The host shuffle-manager lane (and the ICI lane's fallback
        tier). `override_source` replaces the child stream when the ICI
        lane degrades mid-stream: the leftover batches it already
        pulled plus the unconsumed remainder. On that path lineage
        capture is off (a recompute would replay the child from batch
        zero and rewrite the wrong map output), the round-robin cursor
        continues from where the ICI rounds left it, and `stats_rec`
        carries the ICI rounds' map records in — the write phase below
        appends its own and emits the execution's ONE exchange_stats
        record."""
        from ..shuffle.manager import HostShuffleReader, shuffle_manager
        mgr = shuffle_manager()
        handle = mgr.register(self.n_partitions, self.output_schema)
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        if override_source is None:
            self._rr_offset = 0
        state = {"done": 0, "outer_done": False, "closed": False}
        try:
            if override_source is not None:
                source = override_source
                bounds = None
            elif self.partitioning == "range":
                # bounds need a full pass: buffer the input as SPILLABLE
                # handles (sampling keys host-side as they stream by), so
                # the buffered data stays under the memory budget — the
                # point of the host-shuffled sort (reference
                # GpuRangePartitioner sampling + spillable buffering)
                from ..memory.spillable import SpillableBatch
                spillables = []
                key_samples: list = []
                for b in self.child.execute():
                    nb = b.num_rows_host
                    if nb:
                        key_samples.extend(self._host_keys(
                            b, nb, stride=max(1, nb // 512)))
                    spillables.append(SpillableBatch.from_batch(b))
                bounds = self._range_bounds(key_samples)

                def drain():
                    for sp in spillables:
                        batch = sp.get_batch()
                        try:
                            yield batch
                        finally:
                            sp.release()
                            sp.close()

                source = drain()
            else:
                source = self.child.execute()
                bounds = None
            from ..config import PARTITION_RECOVERY_ENABLED
            # lineage capture (ISSUE 6): range mode is excluded — its
            # partition bounds come from sampling a spillable buffer
            # that is consumed by the write pass, so a later recompute
            # could not replay the identical pid assignment
            capture_lineage = (
                self.partitioning != "range"
                and override_source is None
                and bool(self._conf.get(PARTITION_RECOVERY_ENABLED)))
            # runtime statistics (ISSUE 11): per-map-output and
            # per-partition row/byte distributions, recorded from the
            # counts the split/serializer already produced — into the
            # governed query's RuntimeStats (when one is running on
            # this thread) and the process-wide collector
            from ..obs import stats as obs_stats
            from ..obs import telemetry
            if stats_rec is None:
                stats_rec = obs_stats.ExchangeRecorder(
                    type(self).__name__, self._op_id, self.n_partitions)
            map_id = 0
            for b in source:
                in_batches.add(1)
                n = b.num_rows_host
                in_rows.add(n)
                # time only the shuffle work (partition/serialize/write),
                # not the upstream compute driving child.execute().
                # Phase attribution (ISSUE 17): the map write's wall is
                # host-pack/serialize except the writer's file-IO share,
                # which the nested add() carves out as shuffle-io (and
                # the span excludes from its own exclusive time)
                with self.metrics[SHUFFLE_WRITE_TIME].ns_timer(), \
                        obs_phase.span("host-pack-serialize"):
                    writer, lane, pack_ns, rows_pp = self._write_map(
                        b, n, bounds, handle, mgr, map_id)
                    obs_phase.add("shuffle-io", writer.io_ns)
                stats_rec.record_map(rows_pp, writer.partition_bytes,
                                     writer.bytes_written)
                telemetry.add("exchange.write_bytes",
                              writer.bytes_written)
                if capture_lineage:
                    handle.lineage[mgr.map_data_path(
                        handle.shuffle_id, map_id)] = \
                        self._make_recompute(handle, mgr, map_id)
                self.metrics[PARTITION_SIZE].add(writer.bytes_written)
                obs_events.emit("exchange",
                                exec="HostShuffleExchangeExec",
                                op_id=self._op_id, map_id=map_id,
                                partitions=self.n_partitions,
                                bytes=writer.bytes_written,
                                partitioning=self.partitioning)
                obs_events.emit("shuffle_write",
                                exec="HostShuffleExchangeExec",
                                op_id=self._op_id, map_id=map_id,
                                lane=lane, bytes=writer.bytes_written,
                                frames=writer.frames_written,
                                pack_ns=pack_ns,
                                serialize_ns=writer.serialize_ns,
                                io_ns=writer.io_ns)
                map_id += 1
            # one gather_stats record per execution, the wired-exec
            # convention (the write phase is where this exec's gathers
            # happen — emit once it is complete, not at stream close)
            self._gather_track.emit_event(type(self).__name__,
                                          self._op_id)
            # one exchange_stats record per execution: the skew/
            # distribution summary profile_report rolls up and the AQE
            # loop (ROADMAP 4) will consult
            stats_rec.finish_and_emit()
            #: measured write total for the single-build conversion
            #: consult (ISSUE 19) — host lane only (ICI rounds record
            #: rows, not bytes)
            self._adaptive_write_bytes = stats_rec.total_bytes() or None
            reader = HostShuffleReader(handle, mgr, self._conf)
            n = self.n_partitions
            # adaptive replanning (ISSUE 19): the consult point — the
            # write phase measured every partition exactly, no reader
            # stream exists yet. The ICI fallback drain is excluded
            # (its stats carry rows only, and lineage is off).
            split_plan, flat_groups = {}, None
            if override_source is None:
                split_plan, flat_groups = self._adaptive_read_plan(
                    stats_rec, reader, handle, flat)

            def cleanup_if_finished():
                if state["outer_done"] and state["done"] >= n \
                        and not state["closed"]:
                    state["closed"] = True
                    mgr.unregister(handle)

            out_rows = self.metrics[NUM_OUTPUT_ROWS]
            out_batches = self.metrics[NUM_OUTPUT_BATCHES]

            def part_stream(p, cell):
                # the handle must outlive the INNER streams: a consumer
                # may list() the outer generator before reading any
                # partition (exhausting the outer must not tear down the
                # shuffle files under the readers)
                groups = split_plan.get(p)
                inner = self._read_partition(reader, p) \
                    if groups is None \
                    else self._read_partition_split(reader, p, groups,
                                                    handle)
                try:
                    for b in inner:
                        out_batches.add(1)
                        if b._host_rows is not None:
                            out_rows.add(b._host_rows)
                        else:
                            out_rows.add_device(b.num_rows)
                        yield b
                finally:
                    # join the pipelined reader (inner's finally closes
                    # its stage) BEFORE _mark_done can unregister the
                    # shuffle files under a still-running producer
                    inner.close()
                    _mark_done(cell)

            def _mark_done(cell):
                if not cell[0]:
                    cell[0] = True
                    state["done"] += 1
                    cleanup_if_finished()

            def _mark_done_all(cells):
                for cell in cells:
                    _mark_done(cell)

            def group_stream(ps, cells):
                # a coalesced read (ISSUE 19 decision 3): chain the
                # member partitions' UNCHANGED streams — same stages,
                # same batches, same order — so the merge is pure read
                # grouping; the finally settles every member's cell
                try:
                    for p, cell in zip(ps, cells):
                        yield from part_stream(p, cell)
                finally:
                    _mark_done_all(cells)

            import weakref
            try:
                if flat_groups is None:
                    for p in range(n):
                        cell = [False]
                        g = part_stream(p, cell)
                        # a NEVER-STARTED generator runs no finally even
                        # on close: the weakref finalizer keeps an
                        # abandoned partition stream from leaking the
                        # shuffle handle
                        weakref.finalize(g, _mark_done, cell)
                        yield g
                else:
                    for ps in flat_groups:
                        cells = [[False] for _ in ps]
                        if len(ps) == 1:
                            g = part_stream(ps[0], cells[0])
                            weakref.finalize(g, _mark_done, cells[0])
                        else:
                            g = group_stream(ps, cells)
                            weakref.finalize(g, _mark_done_all, cells)
                        yield g
            finally:
                state["outer_done"] = True
                cleanup_if_finished()
        except BaseException:
            # write-phase failure or early abandonment of the outer
            # generator: tear down now (cleanup_if_finished guards the
            # registered state against a second unregister)
            if not state["closed"]:
                state["closed"] = True
                mgr.unregister(handle)
            raise

    # -- ICI device-resident lane (ISSUE 16) -------------------------------
    def _ici_eligible(self) -> bool:
        """May this execution take the device-to-device lane? Conf on,
        a device-computable partitioning (range bounds are host
        objects), an active mesh whose axis size IS the partition
        count (the all-to-all sends one slot grid row per peer), and a
        closed `ici_exchange` breaker. A no answer is the degradation
        decision: the host lane is always correct."""
        if not self._ici_enabled or self.n_partitions <= 1:
            return False
        if self.partitioning not in ("hash", "roundrobin", "single"):
            return False
        # variable-length nested payloads (array/map) have no packed
        # slot-grid representation yet — parallel/exchange.py exchanges
        # fixed-width lanes, strings and struct/decimal limbs only
        from ..types import ArrayType, MapType, StructType

        def _collective_ok(dt) -> bool:
            if isinstance(dt, (ArrayType, MapType)):
                return False
            if isinstance(dt, StructType):
                return all(_collective_ok(f.data_type)
                           for f in dt.fields)
            return True

        if not all(_collective_ok(f.data_type)
                   for f in self.output_schema.fields):
            return False
        mesh = active_mesh()
        if mesh is None or mesh_axis_size(mesh) != self.n_partitions:
            return False
        from . import lifecycle
        if not lifecycle.breaker_allows("ici_exchange"):
            return False
        # adaptive skew shield (ISSUE 19): an armed skew splitter needs
        # the host lane's map-output-granular files — uneven sub-reads
        # don't fit the static device collective. The stand-down is a
        # degradation decision, reported through the ISSUE 16 seam
        # (fallback event + counter) so the lane change is visible.
        if self._adaptive_probe_split:
            from ..config import ADAPTIVE_ENABLED, ADAPTIVE_SKEW_FACTOR
            if self._conf.get(ADAPTIVE_ENABLED) \
                    and self._conf.get(ADAPTIVE_SKEW_FACTOR) > 0:
                from ..shuffle.manager import note_ici_exchange
                note_ici_exchange(fallbacks=1)
                obs_events.emit("ici_exchange",
                                exec=type(self).__name__,
                                op_id=self._op_id, fallback=True,
                                reason="adaptive_skew_split")
                return False
        self._ici_mesh = mesh
        return True

    def _ici_pid(self, local: ColumnarBatch, rr_off, n: int):
        """Per-device partition ids inside the SPMD bodies. rr_off is
        the device's round-robin cursor at its batch's first row (a
        traced scalar input — the host tracks it across rounds so the
        assignment is bit-identical to the host lane's)."""
        if self.partitioning == "hash":
            return self._pid_kernel(local)
        act = active_mask(local.num_rows, local.capacity)
        if self.partitioning == "roundrobin":
            iota = jnp.arange(local.capacity, dtype=jnp.int32)
            pid = (iota + rr_off) % jnp.int32(n)
            return jnp.where(act, pid, jnp.int32(n))
        return jnp.where(act, jnp.int32(0), jnp.int32(n))  # single

    def _ici_measure_kernel(self, stacked, rr):
        """Per-device partition histogram + max string byte length,
        vmapped over the device axis (pure measurement, no collective):
        ONE host sync per round sizes the negotiated slot grid. The
        histogram comes back per device — one row per map batch — so
        the runtime-statistics recorder keeps the host lane's per-map
        granularity."""
        n = self.n_partitions

        def per_dev(local: ColumnarBatch, off):
            pid = self._ici_pid(local, off, n)
            ones = jnp.where(pid < n, jnp.int32(1), jnp.int32(0))
            counts = jax.ops.segment_sum(ones, pid.astype(jnp.int32),
                                         num_segments=n + 1)
            max_len = jnp.int32(0)
            act = active_mask(local.num_rows, local.capacity)
            for c in local.columns:
                if isinstance(c, StringColumn):
                    lens = string_lengths(c)
                    max_len = jnp.maximum(
                        max_len, jnp.max(jnp.where(act, lens, 0)))
            return jnp.max(counts[:n]), max_len, counts[:n]

        max_count, max_len, per_map = jax.vmap(per_dev)(stacked, rr)
        return jnp.max(max_count), jnp.max(max_len), per_map

    def _get_ici_measure(self):
        if self._ici_measure is None:
            self._ici_measure = self._site(
                self._ici_measure_kernel,
                "HostShuffleExchangeExec.ici_measure")
        return self._ici_measure

    def _get_ici_step(self, cap: int, slot_cap: int, width: int):
        """The exchange program per (capacity, slot_cap, string width)
        shape AND mesh identity: partition-split into the (n, slot_cap)
        send grid and all-to-all every column lane over the mesh axis —
        built through _site so an identical later plan reuses the
        compiled program (exec/stage_compiler.py fingerprint cache).
        The compiled step closes over the mesh it was built under, so
        the mesh's axis names + devices are part of the key (and the
        fingerprint salt): a session that installs a different mesh
        later — same axis size, different Mesh/device set — gets a
        fresh step instead of a collective over the stale mesh."""
        mesh = self._ici_mesh
        key = (cap, slot_cap, width, mesh.axis_names,
               tuple(mesh.devices.flat))
        step = self._ici_steps.get(key)
        if step is not None:
            return step
        n = self.n_partitions
        schema = self.output_schema

        def spmd(stacked, rr):
            local = _squeeze0(stacked)
            pid = self._ici_pid(local, rr[0], n)
            cols, n_recv = exchange_columns(
                list(local.columns), (), local.num_rows, local.capacity,
                DATA_AXIS, n, slot_cap=slot_cap, string_width=width,
                pid=pid)
            return _expand0(ColumnarBatch(cols, n_recv, schema))

        from ..parallel.mesh import shard_map_compat
        step = self._site(
            shard_map_compat(spmd, mesh=mesh,
                             in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
                             out_specs=P(DATA_AXIS)),
            "HostShuffleExchangeExec.ici_exchange_step", key_salt=key)
        self._ici_steps[key] = step
        return step

    def _ici_exchange_round(self, batches, rr_offs, round_idx: int):
        """One collective round: exactly ONE map batch per device (in
        map order, padded with empties), so partition p's received rows
        concatenate across devices in the host lane's map order —
        byte-identical per-partition row order. Returns the n received
        shard batches + the (n_devices, n_partitions) per-map-batch row
        histogram (sum over axis 0 = the round's partition totals)."""
        import time as _time

        import numpy as _np
        from ..parallel.distributed import stack_batches, unstack_batches
        from ..shuffle.manager import note_ici_exchange
        n = self.n_partitions
        schema = self.output_schema
        per_dev = list(batches) + [empty_batch(schema)
                                   for _ in range(n - len(batches))]
        cap = max(b.capacity for b in per_dev)
        per_dev = [b.sized_to(cap) for b in per_dev]
        rr = jnp.asarray(list(rr_offs) + [0] * (n - len(rr_offs)),
                         dtype=jnp.int32)
        from . import lifecycle
        lifecycle.engage_domain("ici_exchange")
        t0 = _time.perf_counter_ns()
        # the collective dispatch is the chaos seam: the fault key is
        # the deterministic round ordinal, and dispatch metrics land on
        # this exec through the stage-boundary harness. Phase
        # attribution (ISSUE 17): the whole measured round — stack,
        # measure, all-to-all step, unstack — is ici-collective; the
        # span keeps its cached dispatches out of device-compute
        # a round's collective programs hang-bound (when
        # dispatch.timeoutMs > 0) against the ici_exchange breaker, so
        # a wedged all-to-all degrades to the host lane like any other
        # classified-transient round failure (ISSUE 20)
        from . import speculation_shield
        with obs_phase.span("ici-collective"), \
                speculation_shield.dispatch_domain("ici_exchange"), \
                self.batch_harness(fault_point="shuffle.ici_exchange",
                                   fault_key=f"r{round_idx}",
                                   metric_scope=True):
            stacked = stack_batches(per_dev)
            max_count, max_len, per_map = self._get_ici_measure()(
                stacked, rr)
            # one host sync per round; the running high-water hints
            # keep later (smaller) rounds on the SAME compiled step
            self._ici_cap_hint = max(self._ici_cap_hint, int(max_count))
            slot_cap = negotiate_slot_cap(int(max_count), cap,
                                          hint=self._ici_cap_hint)
            self._ici_width_hint = max(
                self._ici_width_hint, (int(max_len) + 7) // 8 * 8)
            width = self._ici_width_hint
            out = self._get_ici_step(cap, slot_cap, width)(stacked, rr)
            shards = unstack_batches(out, n)
        collective_ns = _time.perf_counter_ns() - t0
        per_map = _np.asarray(per_map)
        moved = sum(s.device_size_bytes() for s in shards)
        rows = int(per_map.sum())
        fill = rows / float(n * n * slot_cap) if slot_cap else 0.0
        self.metrics[SHUFFLE_PACK_TIME].add(collective_ns)
        note_ici_exchange(rounds=1, batches=len(batches), bytes=moved,
                          collective_ns=collective_ns)
        obs_events.emit("ici_exchange", exec="HostShuffleExchangeExec",
                        op_id=self._op_id, round=round_idx,
                        partitions=n, batches=len(batches), rows=rows,
                        bytes=moved, slot_cap=slot_cap, width=width,
                        fill=round(fill, 4),
                        collective_ns=collective_ns)
        return shards, per_map

    def _execute_partitions_ici(self):
        """Drive the device-resident lane: child batches group into
        one-batch-per-device rounds, each round runs the measured
        all-to-all program, received shards stage as SPILLABLE catalog
        entries tagged `ici_exchange` (the PR 4-6 spill/quota contracts
        hold). Zero host serialize frames, zero per-batch D2H/H2D.

        Degradation: a classified-transient failure of the COLLECTIVE
        ROUND itself (or an injected `shuffle.ici_exchange` fault)
        records against the `ici_exchange` breaker domain and the rest
        of the stream — the failed round's batches are still in hand —
        degrades to the host serialize lane; partitions then drain the
        staged ICI pieces FIRST and the host partitions after,
        preserving map order. The seam is deliberately THAT narrow: a
        transient raised while pulling from the CHILD stream must
        propagate to the task-retry layer exactly as the host lane
        would propagate it — a generator that raised is finalized, so
        chaining its remainder would silently drop every unconsumed
        child batch and return partial results."""
        from itertools import chain

        from .. import faults
        from ..memory.spillable import SpillableBatch
        from ..obs import stats as obs_stats
        from ..shuffle.manager import note_ici_exchange
        from . import lifecycle
        n = self.n_partitions
        schema = self.output_schema
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        self._rr_offset = 0
        self._ici_cap_hint = 0
        self._ici_width_hint = 8
        staged: List[List[SpillableBatch]] = [[] for _ in range(n)]
        pending: List[ColumnarBatch] = []
        pending_rows = 0
        rr_offs: List[int] = []
        part_totals = None
        round_idx = 0
        fell_back = False
        stats_rec = obs_stats.ExchangeRecorder(type(self).__name__,
                                               self._op_id, n)
        source = self.child.execute()
        try:
            def try_flush() -> bool:
                """Run one collective round over `pending`; True on
                success. Only the round dispatch is inside the
                degradation seam — once its shards are in hand they
                are staged unconditionally (replaying the same batches
                on the host lane after a partial stage would duplicate
                rows)."""
                nonlocal part_totals, pending_rows, round_idx, fell_back
                try:
                    with self.metrics[SHUFFLE_WRITE_TIME].ns_timer():
                        shards, per_map = self._ici_exchange_round(
                            pending, rr_offs, round_idx)
                except Exception as e:  # noqa: BLE001 — degradation seam
                    if not faults.is_task_transient(e):
                        raise
                    # degradation decision: count the failure against
                    # the breaker domain (enough of them opens the
                    # breaker and later exchanges skip the lane up
                    # front) and hand the batches still in hand + the
                    # unconsumed remainder to the always-works host lane
                    lifecycle.record_domain_failure("ici_exchange")
                    note_ici_exchange(fallbacks=1)
                    obs_events.emit("ici_exchange",
                                    exec="HostShuffleExchangeExec",
                                    op_id=self._op_id, round=round_idx,
                                    fallback=True, error=str(e)[:200])
                    # the failed round's batches replay on the host
                    # lane: rewind the round-robin cursor to the
                    # round's first batch so the host lane assigns the
                    # SAME partitions the collective would have
                    if rr_offs:
                        self._rr_offset = rr_offs[0]
                    fell_back = True
                    return False
                for d, shard in enumerate(shards):
                    staged[d].append(SpillableBatch.from_batch(
                        shard, origin="ici_exchange"))
                totals = per_map.sum(axis=0)
                part_totals = totals if part_totals is None \
                    else part_totals + totals
                # one stats record per MAP BATCH (the host lane's
                # granularity): the measure program's per-device
                # histogram rows, skipping the round's padding devices
                for d in range(len(pending)):
                    stats_rec.record_map(per_map[d].tolist(), None, 0)
                in_batches.add(len(pending))
                in_rows.add(pending_rows)
                round_idx += 1
                pending_rows = 0
                del pending[:], rr_offs[:]
                return True

            for b in source:
                rows = b.num_rows_host
                rr_offs.append(self._rr_offset)
                if self.partitioning == "roundrobin":
                    self._rr_offset = int((self._rr_offset + rows) % n)
                pending.append(b)
                pending_rows += rows
                if len(pending) == n and not try_flush():
                    break
            if not fell_back and pending:
                try_flush()
        except BaseException:
            for pieces in staged:
                for sp in pieces:
                    sp.close()
            raise
        if part_totals is not None:
            max_part = int(part_totals.max())
            self.metrics[PARTITION_SIZE].add(max_part)
            obs_events.emit("exchange", exec="HostShuffleExchangeExec",
                            op_id=self._op_id, partitions=n,
                            rounds=round_idx, lane="ici",
                            max_partition_rows=max_part,
                            partitioning=self.partitioning)
        if not fell_back:
            stats_rec.finish_and_emit()
            lifecycle.record_domain_success("ici_exchange")
            yield from self._yield_ici_partitions(staged, schema)
            return
        # hybrid drain: staged ICI rounds carry the EARLIER map
        # batches, the host lane the rest — chaining per partition
        # preserves the host lane's per-partition row order exactly.
        # The stats recorder (already holding the ICI rounds' map
        # records) rides into the host lane, which finish_and_emit()s
        # it once after its write phase: ONE exchange_stats record per
        # execution, whichever lanes it crossed.
        host_gens = self._execute_partitions_host(
            chain(iter(pending), source), stats_rec=stats_rec)
        yield from self._yield_ici_partitions(staged, schema,
                                              host_gens=host_gens)

    def _yield_ici_partitions(self, staged, schema, host_gens=None
                              ) -> "Iterator[Iterator[ColumnarBatch]]":
        """Hand out the per-partition drain generators with the host
        lane's abandonment protection: a NEVER-STARTED generator runs
        no finally even on close, so a weakref finalizer closes each
        partition's staged pieces (and their memory-budget
        reservations) when its generator is dropped undrained;
        partitions the consumer never reached — the outer generator
        closed early — close in the finally. SpillableBatch.close is
        idempotent, so overlapping the inline closes in _unspill_ici
        is safe. On the hybrid-drain path `host_gens` supplies the host
        lane's partition streams to chain after the staged pieces; it
        is closed on the way out so the host side's handle bookkeeping
        sees outer-done even when the consumer stops early."""
        import weakref

        def _close_pieces(pieces):
            for sp in pieces:
                sp.close()

        hg_it = iter(host_gens) if host_gens is not None else None
        handed = 0
        try:
            for p in range(self.n_partitions):
                if hg_it is None:
                    g = self._drain_ici_partition(staged[p], schema)
                else:
                    g = self._chain_ici_host(staged[p], schema,
                                             next(hg_it))
                weakref.finalize(g, _close_pieces, staged[p])
                handed += 1
                yield g
        finally:
            for q in range(handed, self.n_partitions):
                _close_pieces(staged[q])
            if host_gens is not None:
                host_gens.close()

    def _drain_ici_partition(self, pieces, schema
                             ) -> Iterator[ColumnarBatch]:
        out_rows = self.metrics[NUM_OUTPUT_ROWS]
        out_batches = self.metrics[NUM_OUTPUT_BATCHES]
        if not pieces:
            out_batches.add(1)
            yield empty_batch(schema)
            return
        stage = self.pipeline_stage(self._unspill_ici(pieces),
                                    "ici-read")
        try:
            for b in stage:
                out_batches.add(1)
                out_rows.add_device(b.num_rows)
                yield b
        finally:
            stage.close()

    @staticmethod
    def _unspill_ici(pieces) -> Iterator[ColumnarBatch]:
        """Unspill staged shard pieces one at a time (pipelined: piece
        k+1's promotion overlaps the consumer's compute on k); an early
        close drops the staged remainder's catalog entries."""
        it = iter(pieces)
        try:
            for sp in it:
                try:
                    b = sp.get_batch()
                    sp.release()
                except BaseException:
                    sp.close()
                    raise
                sp.close()
                yield b
        finally:
            for sp in it:
                sp.close()

    def _chain_ici_host(self, pieces, schema, host_gen
                        ) -> Iterator[ColumnarBatch]:
        """Fallback drain for one partition: the staged ICI pieces
        (earlier map batches) first, then the host lane's stream. The
        host generator always yields at least an empty batch, so the
        ICI side skips its own empty-partition padding."""
        out_rows = self.metrics[NUM_OUTPUT_ROWS]
        out_batches = self.metrics[NUM_OUTPUT_BATCHES]
        try:
            if pieces:
                stage = self.pipeline_stage(self._unspill_ici(pieces),
                                            "ici-read")
                try:
                    for b in stage:
                        out_batches.add(1)
                        out_rows.add_device(b.num_rows)
                        yield b
                finally:
                    stage.close()
            yield from host_gen
        finally:
            host_gen.close()

    def _make_recompute(self, handle, mgr, map_id: int):
        """Partition-granular recovery lineage (ISSUE 6): a zero-arg
        closure that re-executes ONLY this exchange's child sub-plan
        from its sources and atomically rewrites the one damaged map
        output — the engine analog of Spark recomputing a single lost
        map task instead of the whole job. Runs at shuffle READ time
        (possibly on the pipelined shuffle-read producer thread, which
        has adopted conf/query-id/attempt/lifecycle context); the
        round-robin offset is replayed from zero so the recomputed pid
        assignment is bit-identical to the original write."""

        def recompute() -> None:
            # serialization: the reader invokes lineage closures under
            # the handle's recover_lock (shuffle/manager.py), so two
            # corrupt map outputs read through the PIPELINED partition
            # streams never run this concurrently — the mutable
            # round-robin offset replay below relies on that
            saved_rr = self._rr_offset
            self._rr_offset = 0
            try:
                src = self.child.execute()
                try:
                    for i, b in enumerate(src):
                        n = b.num_rows_host
                        if i < map_id:
                            # skipped map tasks only advance the
                            # round-robin cursor; hash/single pids are
                            # stateless, so no device work is spent
                            if self.partitioning == "roundrobin":
                                self._rr_offset = int(
                                    (self._rr_offset + n)
                                    % self.n_partitions)
                            continue
                        # same lane as the original write (_write_map):
                        # the rewritten map output keeps the original
                        # frame layout, so the reader's frame index and
                        # the seeded chaos keys stay valid
                        self._write_map(b, n, None, handle, mgr,
                                        map_id, register=False)
                        return
                    raise RuntimeError(
                        f"partition recovery: child produced no "
                        f"batch {map_id} on re-execution")
                finally:
                    close = getattr(src, "close", None)
                    if close is not None:
                        close()
            finally:
                self._rr_offset = saved_rr

        return recompute

    def _read_partition(self, reader, p: int) -> Iterator[ColumnarBatch]:
        """Stream one partition's decoded blocks. Pipelined (ISSUE 3):
        the segment fetch + LZ4 decode of block k+1 run on the producer
        thread (over the reader pool) while the consumer computes on
        block k; shuffleReadTime counts only the time this operator
        BLOCKED waiting for a block, in both modes. Decoded blocks are
        HOST-backed (ISSUE 10): this seam promotes each to device as
        ONE packed upload, keyed per (partition, batch ordinal) for
        seeded chaos and attributed to numUploads/uploadPackTimeNs."""
        from ..columnar.upload import promote_stream
        read_time = self.metrics[SHUFFLE_READ_TIME]
        stage = self.pipeline_stage(
            promote_stream(reader.read_partition(p),
                           key_prefix=f"upload:p{p}", seam="shuffle",
                           num_metric=self.metrics[NUM_UPLOADS],
                           time_metric=self.metrics[UPLOAD_PACK_TIME]),
            "shuffle-read")
        saw = False
        try:
            while True:
                with read_time.ns_timer():
                    try:
                        b = next(stage)
                    except StopIteration:
                        break
                saw = True
                yield b
        finally:
            stage.close()
        if not saw:
            yield empty_batch(self.output_schema)

    # -- adaptive replanning (ISSUE 19) -------------------------------------
    def _adaptive_read_plan(self, stats_rec, reader, handle, flat):
        """The exchange-read consult point: decide skew splits (any
        consumer) and tiny-partition coalescing (flat consumers only)
        from the write phase's MEASURED per-partition bytes. Never
        raises — a consult failure records against the `adaptive`
        breaker domain and the static plan runs."""
        from . import adaptive
        op = type(self).__name__
        try:
            per_part = stats_rec.partition_bytes()
            if self.n_partitions <= 1 or per_part is None:
                return {}, None
            if not adaptive.consult(self._conf, op=op,
                                    op_id=self._op_id):
                return {}, None
            split_plan = {}
            thr = adaptive.skew_threshold(per_part, self._conf)
            if thr is not None and len(handle.map_outputs) > 1:
                threshold, median = thr
                for p, b in enumerate(per_part):
                    if b <= threshold:
                        continue
                    groups = reader.plan_map_groups(p, threshold)
                    if len(groups) <= 1:
                        continue
                    split_plan[p] = groups
                    adaptive.note_decision(
                        "skew_split", op=op, op_id=self._op_id,
                        partition=p, bytes=b, threshold=threshold,
                        median_bytes=median, subs=len(groups),
                        max_sub_bytes=max(g[1] for g in groups))
            flat_groups = None
            if flat:
                from ..config import ADAPTIVE_COALESCE_TARGET_BYTES
                target = self._conf.get(ADAPTIVE_COALESCE_TARGET_BYTES)
                if target > 0:
                    flat_groups = adaptive.coalesce_groups(
                        per_part, target, exclude=set(split_plan))
                    if flat_groups is not None:
                        adaptive.note_decision(
                            "partition_coalesce", op=op,
                            op_id=self._op_id,
                            partitions=self.n_partitions,
                            reads=len(flat_groups),
                            target_bytes=target)
            return split_plan, flat_groups
        except Exception as e:  # noqa: BLE001 — replan must not kill
            adaptive.note_error(op=op, op_id=self._op_id, error=e)
            return {}, None

    def _read_partition_split(self, reader, p: int, groups, handle,
                              ) -> Iterator[ColumnarBatch]:
        """A skew-split partition read (ISSUE 19 decision 1): K
        map-granular sub-reads in map order, each its own pipelined
        fetch/decode/promote stage, so the in-flight decode window is
        one sub-read (≤ the skew threshold) instead of the whole hot
        partition. Downstream, each promoted batch is one probe window
        against the replicated build side — concatenated output is
        byte-identical to the unsplit read."""
        from ..columnar.upload import promote_stream
        read_time = self.metrics[SHUFFLE_READ_TIME]
        ordinal = [0]
        saw = False
        for sub, (paths, _sub_bytes) in enumerate(groups):
            stage = self.pipeline_stage(
                promote_stream(
                    reader.read_partition_maps(p, paths, sub, ordinal),
                    key_prefix=f"upload:p{p}", seam="shuffle",
                    num_metric=self.metrics[NUM_UPLOADS],
                    time_metric=self.metrics[UPLOAD_PACK_TIME]),
                "shuffle-read")
            try:
                while True:
                    with read_time.ns_timer():
                        try:
                            b = next(stage)
                        except StopIteration:
                            break
                    saw = True
                    yield b
            finally:
                stage.close()
        if not saw:
            yield empty_batch(self.output_schema)

    def node_description(self):
        return (f"HostShuffleExchangeExec[n={self.n_partitions}, "
                f"keys={self.partition_exprs!r}]")


class BroadcastExchangeExec(TpuExec):
    """Materialize the child once as a single device-resident batch and
    replay it to every consumer execution (reference
    GpuBroadcastExchangeExec.scala:352: the build side is collected,
    serialized once, and kept device-resident on every executor).

    On a TPU mesh the replication itself is free at this layer: the batch
    lives in HBM and multi-chip consumers read it replicated (an
    all-gather-free broadcast — the stream side never moves at all, which
    is the entire point of a broadcast join)."""

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._materialized: Optional[ColumnarBatch] = None

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return (BROADCAST_TIME, (PARTITION_SIZE, ESSENTIAL))

    def _fingerprint_extras(self):
        # stateless pass-through at the program level (materialization
        # is host-side concat via module sites): extras exist so parent
        # subtrees over a broadcast build side stay cacheable
        return ()

    def materialize(self) -> ColumnarBatch:
        if self._materialized is None:
            with self.metrics[BROADCAST_TIME].ns_timer():
                batches = list(self.child.execute())
                if not batches:
                    self._materialized = empty_batch(self.output_schema)
                elif len(batches) == 1:
                    self._materialized = batches[0]
                else:
                    self._materialized = concat_batches(
                        batches, self.output_schema)
            size = self._materialized.device_size_bytes()
            self.metrics[PARTITION_SIZE].add(size)
            obs_events.emit("exchange", exec="BroadcastExchangeExec",
                            op_id=self._op_id, bytes=size)
        return self._materialized

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        yield self.materialize()

    def node_description(self):
        return "BroadcastExchangeExec"


class ShuffledHashJoinExec(TpuExec):
    """Per-partition hash join over two shuffle exchanges (reference
    GpuShuffledHashJoinExec.scala). Both children are hash-partitioned on
    the join keys with the SAME partitioning, so rows with equal keys
    colocate on one shard; the union of per-partition joins is globally
    exact — including outer sides, because an unmatched row can only ever
    match within its own partition.

    One inner HashJoinExec instance is reused across partitions (its jit
    caches key on batch shapes, which repeat across shards)."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = "inner",
                 build_side: str = "right",
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        from .joins import HashJoinExec
        self.join_type = join_type
        self._lscan = _ReplayScanExec(left.output_schema)
        self._rscan = _ReplayScanExec(right.output_schema)
        self._join = HashJoinExec(self._lscan, self._rscan, left_keys,
                                  right_keys, join_type,
                                  build_side=build_side, condition=condition)

    @property
    def output_schema(self) -> Schema:
        return self._join.output_schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        # lazy zip over PARTITION STREAMS: only one partition pair is
        # resident at a time, and within it the stream side's pieces
        # flow through the inner join one batch at a time (round 5 —
        # a skewed shard is no longer concatenated whole; the build side
        # still materializes its partition, as any hash build must)
        build_right = self._join.build_side == "right"
        # adaptive skew shield (ISSUE 19): arm the STREAM-side host
        # exchange — its skewed partitions split into sub-read probe
        # streams against this join's replicated per-partition build,
        # and an armed splitter keeps that exchange off the ICI lane
        stream_child = self.children[0] if build_right \
            else self.children[1]
        build_child = self.children[1] if build_right \
            else self.children[0]
        if isinstance(stream_child, HostShuffleExchangeExec):
            stream_child._adaptive_probe_split = True
        # single-build conversion (ISSUE 19 decision 2, converse): when
        # the build side's exchange MEASURES small at write time, the
        # per-partition zip collapses to one single-build probe pass —
        # the build replays whole (it fits by measurement) and the
        # probe side's exchange is skipped entirely (its subtree
        # streams straight into the probe). Correct because the
        # partitioned join's union is the whole join; only row order
        # changes.
        build_gens = None
        if isinstance(stream_child, HostShuffleExchangeExec) \
                and isinstance(build_child, HostShuffleExchangeExec):
            from . import adaptive
            from ..config import ADAPTIVE_ENABLED
            conf = build_child._conf
            cap = adaptive.auto_broadcast_max(conf) \
                if conf.get(ADAPTIVE_ENABLED) else -1
            if cap >= 0 and adaptive.consult(
                    conf, op=type(self).__name__, op_id=self._op_id):
                build_gens = list(build_child.execute_partitions())
                measured = build_child._adaptive_write_bytes
                if measured is not None and measured <= cap:
                    adaptive.note_decision(
                        "single_build_convert", op=type(self).__name__,
                        op_id=self._op_id, measured_bytes=measured,
                        threshold=cap)
                    batches = [b for g in build_gens for b in g]
                    probe = stream_child.child.execute()
                    if build_right:
                        self._rscan._batches = batches
                        self._lscan.set_stream(probe)
                    else:
                        self._lscan._batches = batches
                        self._rscan.set_stream(probe)
                    yield from self._join.execute()
                    return
        if build_gens is None:
            lit_ = self.children[0].execute_partitions()
            rit = self.children[1].execute_partitions()
        elif build_right:
            lit_ = self.children[0].execute_partitions()
            rit = iter(build_gens)
        else:
            lit_ = iter(build_gens)
            rit = self.children[1].execute_partitions()
        while True:
            lp = next(lit_, None)
            rp = next(rit, None)
            if (lp is None) != (rp is None):
                raise AssertionError(
                    "both sides must use the same partitioning")
            if lp is None:
                return
            if build_right:
                self._lscan.set_stream(lp)
                self._rscan._batches = list(rp)
            else:
                self._lscan._batches = list(lp)
                self._rscan.set_stream(rp)
            yield from self._join.execute()

    def node_description(self):
        return f"ShuffledHashJoinExec[{self.join_type}]"


class _ReplayScanExec(TpuExec):
    """Leaf fed per partition by ShuffledHashJoinExec: either a
    materialized batch list (`_batches`, for the build side) or a lazy
    one-shot generator (`set_stream`, for the stream side — pieces flow
    through the join without whole-shard concatenation)."""

    def __init__(self, schema: Schema):
        super().__init__()
        self._schema = schema
        self._batches: List[ColumnarBatch] = []
        self._stream = None

    def set_stream(self, gen) -> None:
        self._stream = gen
        self._batches = []

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        if self._stream is not None:
            gen, self._stream = self._stream, None
            yield from gen
            return
        yield from self._batches
