"""Exchange execs — planner-produced repartitioning over the device mesh
(reference GpuShuffleExchangeExecBase.scala:167 planning entry,
prepareBatchShuffleDependency:277 device-side split, and the shuffle-plugin
UCX transport; SURVEY §2.5).

TPU-first redesign: no shuffle service, no serialized blocks. An exchange
is ONE compiled SPMD program over the mesh — evaluate the partition key
expressions on device, hash-partition rows (Spark-exact murmur3 pmod),
`lax.all_to_all` over the ICI axis, compact the received rows. XLA lowers
the collective to ICI neighbor exchanges with no host involvement.

Receive-buffer sizing (review finding r1: the worst-case default was
n_parts × capacity): a histogram program measures the actual max partition
load and max string byte length across all devices first — ONE host sync
per exchange, amortized over the whole stage — so the slot capacity fits
the data and fixed-width string lanes can never truncate.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..columnar.batch import ColumnarBatch, empty_batch
from ..columnar.column import StringColumn, bucket_capacity
from ..expr.core import Expression
from ..ops.basic import active_mask
from ..ops.strings import string_lengths
from ..parallel.exchange import exchange_columns, partition_ids
from ..parallel.mesh import DATA_AXIS, active_mesh, mesh_axis_size
from ..types import Schema
from .base import NUM_INPUT_BATCHES, NUM_INPUT_ROWS, OP_TIME, TpuExec
from .basic import InMemoryScanExec, bind_projection
from .coalesce import concat_batches

PARTITION_SIZE = "dataSize"  # reference GpuShuffleExchangeExecBase metric


def _squeeze0(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _expand0(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


class ShuffleExchangeExec(TpuExec):
    """Hash-repartition child output across the mesh so rows with equal
    partition-key values colocate on one device shard.

    With no active mesh (or a 1-device mesh) the exchange is the identity —
    the single-partition plan needs no data movement. Otherwise it emits
    exactly `n_partitions` batches, one per device shard (empty shards
    included, so consumers may zip the two sides of a join)."""

    def __init__(self, partition_exprs: Sequence[Expression], child: TpuExec,
                 mesh=None):
        super().__init__(child)
        self.partition_exprs = list(partition_exprs)
        self._mesh = mesh if mesh is not None else active_mesh()
        self._bound = bind_projection(self.partition_exprs,
                                      child.output_schema)
        self._jit_measure = jax.jit(self._measure_kernel)
        self._steps = {}

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return (NUM_INPUT_BATCHES, NUM_INPUT_ROWS, PARTITION_SIZE)

    @property
    def n_partitions(self) -> int:
        return 1 if self._mesh is None else mesh_axis_size(self._mesh)

    # -- kernels -----------------------------------------------------------
    def _local_pid(self, local: ColumnarBatch, n: int):
        keys = [e.columnar_eval(local) for e in self._bound]
        return partition_ids(keys, local.num_rows, local.capacity, n)

    def _measure_kernel(self, stacked):
        """Per-device partition histogram + max string byte length. Runs
        vmapped over the device axis (it is pure per-device measurement —
        no collective), one host sync for both scalars."""
        n = self.n_partitions

        def per_dev(local: ColumnarBatch):
            pid = self._local_pid(local, n)
            ones = jnp.where(pid < n, jnp.int32(1), jnp.int32(0))
            counts = jax.ops.segment_sum(ones, pid.astype(jnp.int32),
                                         num_segments=n + 1)
            max_count = jnp.max(counts[:n])
            max_len = jnp.int32(0)
            act = active_mask(local.num_rows, local.capacity)
            for c in local.columns:
                if isinstance(c, StringColumn):
                    lens = string_lengths(c)
                    max_len = jnp.maximum(
                        max_len, jnp.max(jnp.where(act, lens, 0)))
            return max_count, max_len

        max_count, max_len = jax.vmap(per_dev)(stacked)
        return jnp.max(max_count), jnp.max(max_len)

    def _get_step(self, cap: int, slot_cap: int, width: int):
        key = (cap, slot_cap, width)
        step = self._steps.get(key)
        if step is not None:
            return step
        n = self.n_partitions
        schema = self.output_schema

        def spmd(stacked):
            local = _squeeze0(stacked)
            pid = self._local_pid(local, n)
            cols, n_recv = exchange_columns(
                list(local.columns), (), local.num_rows, local.capacity,
                DATA_AXIS, n, slot_cap=slot_cap, string_width=width,
                pid=pid)
            return _expand0(ColumnarBatch(cols, n_recv, schema))

        step = jax.jit(jax.shard_map(
            spmd, mesh=self._mesh, in_specs=P(DATA_AXIS),
            out_specs=P(DATA_AXIS), check_vma=False))
        self._steps[key] = step
        return step

    # -- drive -------------------------------------------------------------
    def internal_execute(self) -> Iterator[ColumnarBatch]:
        from ..parallel.distributed import stack_batches, unstack_batches

        n = self.n_partitions
        schema = self.output_schema
        in_batches = self.metrics[NUM_INPUT_BATCHES]
        in_rows = self.metrics[NUM_INPUT_ROWS]
        batches: List[ColumnarBatch] = []
        for b in self.child.execute():
            in_batches.add(1)
            if b._host_rows is not None:
                in_rows.add(b._host_rows)
            else:
                in_rows.add_device(b.num_rows)
            batches.append(b)
        if n == 1:
            yield from batches
            return

        with self.metrics[OP_TIME].ns_timer():
            # round-robin batches onto device shards, one batch per device
            groups = [batches[d::n] for d in range(n)]
            per_dev = []
            for g in groups:
                if not g:
                    per_dev.append(empty_batch(schema))
                elif len(g) == 1:
                    per_dev.append(g[0])
                else:
                    per_dev.append(concat_batches(g, schema))
            cap = max(b.capacity for b in per_dev)
            per_dev = [b.sized_to(cap) for b in per_dev]
            stacked = stack_batches(per_dev)

            max_count, max_len = self._jit_measure(stacked)
            # one host sync per exchange: size the receive buffer to the
            # measured max partition load, and string lanes to the measured
            # max byte length (truncation structurally impossible)
            slot_cap = min(bucket_capacity(max(int(max_count), 1)), cap)
            width = max(8, (int(max_len) + 7) // 8 * 8)
            self.metrics[PARTITION_SIZE].add(int(max_count))

            out = self._get_step(cap, slot_cap, width)(stacked)
            yield from unstack_batches(out, n)

    def node_description(self):
        return (f"ShuffleExchangeExec[n={self.n_partitions}, "
                f"keys={self.partition_exprs!r}]")


class BroadcastExchangeExec(TpuExec):
    """Materialize the child once as a single device-resident batch and
    replay it to every consumer execution (reference
    GpuBroadcastExchangeExec.scala:352: the build side is collected,
    serialized once, and kept device-resident on every executor).

    On a TPU mesh the replication itself is free at this layer: the batch
    lives in HBM and multi-chip consumers read it replicated (an
    all-gather-free broadcast — the stream side never moves at all, which
    is the entire point of a broadcast join)."""

    def __init__(self, child: TpuExec):
        super().__init__(child)
        self._materialized: Optional[ColumnarBatch] = None

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def additional_metrics(self):
        return ("broadcastTime", PARTITION_SIZE)

    def materialize(self) -> ColumnarBatch:
        if self._materialized is None:
            with self.metrics["broadcastTime"].ns_timer():
                batches = list(self.child.execute())
                if not batches:
                    self._materialized = empty_batch(self.output_schema)
                elif len(batches) == 1:
                    self._materialized = batches[0]
                else:
                    self._materialized = concat_batches(
                        batches, self.output_schema)
            self.metrics[PARTITION_SIZE].add(
                self._materialized.device_size_bytes())
        return self._materialized

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        yield self.materialize()

    def node_description(self):
        return "BroadcastExchangeExec"


class ShuffledHashJoinExec(TpuExec):
    """Per-partition hash join over two shuffle exchanges (reference
    GpuShuffledHashJoinExec.scala). Both children are hash-partitioned on
    the join keys with the SAME partitioning, so rows with equal keys
    colocate on one shard; the union of per-partition joins is globally
    exact — including outer sides, because an unmatched row can only ever
    match within its own partition.

    One inner HashJoinExec instance is reused across partitions (its jit
    caches key on batch shapes, which repeat across shards)."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = "inner",
                 build_side: str = "right",
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        from .joins import HashJoinExec
        self.join_type = join_type
        self._lscan = InMemoryScanExec([], left.output_schema)
        self._rscan = InMemoryScanExec([], right.output_schema)
        self._join = HashJoinExec(self._lscan, self._rscan, left_keys,
                                  right_keys, join_type,
                                  build_side=build_side, condition=condition)

    @property
    def output_schema(self) -> Schema:
        return self._join.output_schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        lparts = list(self.children[0].execute())
        rparts = list(self.children[1].execute())
        assert len(lparts) == len(rparts), \
            "both sides must use the same partitioning"
        for lp, rp in zip(lparts, rparts):
            self._lscan._batches = [lp]
            self._rscan._batches = [rp]
            yield from self._join.execute()

    def node_description(self):
        return f"ShuffledHashJoinExec[{self.join_type}]"
