"""WindowExec — reference GpuWindowExec.scala:146 and its specializations
(running, double-pass, bounded, unbounded-to-unbounded). One exec here:
every frame shape lowers to segmented scans over partition-sorted rows
(ops/window.py), so the reference's four execution strategies collapse
into one compiled program per window-expression set.

Frame coverage: ROWS frames with any bounds (sum/count/avg via prefix
differences; min/max via the sparse-table sliding-extrema kernel,
ops/window.bounded_min_max); RANGE frames support the default (UNBOUNDED
PRECEDING..CURRENT ROW with ties) shape. Whole input is windowed as one
concatenated batch — partition-boundary batching rides the out-of-core
sort work.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column
from ..expr.core import Expression
from ..expr.windowexprs import (
    DenseRank, FirstValue, Lag, LastValue, Rank, RowNumber, WindowAgg,
    WindowExpression, WindowFrame,
)
from ..ops.basic import active_mask, gather_column, sanitize
from ..ops.sort import (
    SortOrder, group_segment_ids, order_key_lanes, sort_permutation,
    string_words_for,
)
from ..ops.window import (
    bounded_min_max, lag_lead, rank_dense_rank, row_number, running_min_max,
    segment_ends, segment_starts, whole_partition_broadcast,
    windowed_sum_count,
)
from ..types import DoubleType, IntegerType, LongType, Schema, StructField
from ..obs.dispatch import instrument
from .base import (DISPATCH_METRICS, GATHER_METRICS, GATHER_TIME,
                   NUM_GATHERS, OP_TIME,
                   TpuExec)
from .basic import bind_projection, eval_projection, projection_schema
from .coalesce import concat_batches
from .sort import resolve_sort_orders


class _StreamSourceExec(TpuExec):
    """Leaf yielding batches from a generator (keeps the window's sort
    input streaming instead of materialized)."""

    def __init__(self, schema: Schema, gen):
        super().__init__()
        self._schema = schema
        self._gen = gen

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        yield from self._gen


class WindowExec(TpuExec):
    def __init__(self, window_exprs: Sequence[Tuple[WindowExpression, str]],
                 child: TpuExec):
        super().__init__(child)
        self.window_exprs = list(window_exprs)
        in_schema = child.output_schema
        # all specs must share partition/order in one exec (the planner
        # splits differing specs into stacked WindowExecs, like Spark)
        spec0 = self.window_exprs[0][0].spec
        for we, _ in self.window_exprs:
            assert we.spec.partition_by == spec0.partition_by
            assert we.spec.order_by == spec0.order_by
        self.spec = spec0

        # pre-projection: child cols + partition keys + order keys + inputs
        from ..expr.core import col
        self._pre_exprs: List[Expression] = [col(n) for n in in_schema.names]
        self._n_child = len(in_schema.fields)
        self._part_slots = []
        for e in self.spec.partition_by:
            self._part_slots.append(len(self._pre_exprs))
            self._pre_exprs.append(e.alias(f"_wpart{len(self._part_slots)}"))
        self._order_slots = []
        self._order_dirs = []
        for o in self.spec.order_by:
            e, asc = o[0], o[1] if len(o) > 1 else True
            nf = o[2] if len(o) > 2 else None
            self._order_slots.append(len(self._pre_exprs))
            self._order_dirs.append((asc, nf))
            self._pre_exprs.append(e.alias(f"_word{len(self._order_slots)}"))
        self._input_slots: List[List[int]] = []
        for we, _ in self.window_exprs:
            slots = []
            for e in we.fn.inputs:
                slots.append(len(self._pre_exprs))
                self._pre_exprs.append(e.alias(f"_win{len(self._pre_exprs)}"))
            self._input_slots.append(slots)
        self._pre_bound = bind_projection(self._pre_exprs, in_schema)
        self._pre_schema = projection_schema(self._pre_exprs, in_schema)
        self._jit_window = instrument(self._window_kernel,
                                      label="WindowExec.window",
                                      owner=self, static_argnums=(1,))
        from ..ops.gather import GatherTracker
        self._gather_track = GatherTracker(self.metrics[NUM_GATHERS],
                                           self.metrics[GATHER_TIME])
        self._jit_lps = None
        self._jit_fpl = None
        self._jit_carry_update = None
        self._jit_pre = instrument(
            lambda b: eval_projection(self._pre_bound, b,
                                      self._pre_schema),
            label="WindowExec.pre_project", owner=self)

    @property
    def output_schema(self) -> Schema:
        fields = list(self.child.output_schema.fields)
        for i, (we, name) in enumerate(self.window_exprs):
            in_types = [self._pre_schema.fields[s].data_type
                        for s in self._input_slots[i]]
            fields.append(StructField(name, we.fn.result_type(in_types)))
        return Schema(tuple(fields))

    def additional_metrics(self):
        return GATHER_METRICS + DISPATCH_METRICS

    def _dispatch_window(self, batch: ColumnarBatch, words: int
                         ) -> ColumnarBatch:
        """The one gather-tracked window-kernel dispatch point."""
        with self._gather_track.observe((batch.capacity, words)):
            return self._jit_window(batch, words)

    # -- kernel ------------------------------------------------------------
    def _window_kernel(self, batch: ColumnarBatch, words: int
                       ) -> ColumnarBatch:
        cap = batch.capacity
        n = batch.num_rows
        part_cols = [batch.columns[s] for s in self._part_slots]
        order_cols = [batch.columns[s] for s in self._order_slots]

        orders = [SortOrder(s) for s in self._part_slots] + [
            SortOrder(s, asc, nf) for s, (asc, nf)
            in zip(self._order_slots, self._order_dirs)]
        perm = sort_permutation(batch.columns, orders, n, cap, words)
        # round 8: the partition-sort permutation moves the whole batch
        # through the gather engine — ONE packed row gather for the
        # fixed-width columns instead of one gather per column
        from ..ops.gather import gather_batch_columns
        sorted_cols = gather_batch_columns(batch.columns, perm)
        sorted_parts = [sorted_cols[s] for s in self._part_slots]
        sorted_orders = [sorted_cols[s] for s in self._order_slots]

        if self._part_slots:
            seg, _ = group_segment_ids(sorted_parts, n, cap, words)
        else:
            act = active_mask(n, cap)
            seg = jnp.where(act, 0, cap)

        # order-key boundary mask (first row of each distinct order key)
        if self._order_slots:
            lanes = order_key_lanes(
                sorted_orders, [SortOrder(i) for i in range(len(sorted_orders))],
                n, cap, words)[1:]
            ob = jnp.zeros((cap,), jnp.bool_)
            for lane in lanes:
                ob = ob | (lane != jnp.roll(lane, 1))
            ob = ob.at[0].set(True)
            # per-row last index of its order group (for RANGE-with-ties)
            gid = jnp.cumsum((ob | jnp.concatenate(
                [jnp.ones(1, jnp.bool_), seg[1:] != seg[:-1]])).astype(jnp.int32)) - 1
            gid = jnp.where(active_mask(n, cap), gid, cap)
            positions = jnp.arange(cap, dtype=jnp.int32)
            glast = jax.ops.segment_max(positions, gid, num_segments=cap)
            group_last = jnp.clip(glast[jnp.clip(gid, 0, cap - 1)], 0, cap - 1)
        else:
            ob = None
            group_last = None

        out_cols = list(sorted_cols[: self._n_child])
        out_schema = self.output_schema
        for i, (we, name) in enumerate(self.window_exprs):
            fn = we.fn
            res_type = out_schema.fields[self._n_child + i].data_type
            ins = [sorted_cols[s] for s in self._input_slots[i]]
            col = self._eval_fn(fn, we.spec.frame, ins, seg, ob, group_last,
                                n, cap, res_type, sorted_orders)
            out_cols.append(sanitize(col, n))
        return ColumnarBatch(out_cols, n, out_schema)

    def _eval_fn(self, fn, frame, ins, seg, order_boundary, group_last,
                 n, cap, res_type, sorted_orders=()) -> Column:
        ones = jnp.ones((cap,), jnp.bool_)
        if isinstance(fn, RowNumber):
            return Column(row_number(seg, n, cap), ones, res_type)
        if isinstance(fn, DenseRank):
            _, dense = rank_dense_rank(order_boundary, seg, n, cap)
            return Column(dense, ones, res_type)
        if isinstance(fn, Rank):
            rank, _ = rank_dense_rank(order_boundary, seg, n, cap)
            return Column(rank, ones, res_type)
        if isinstance(fn, Lag):  # covers Lead (negated offset)
            out, same_seg = lag_lead(ins[0], seg, n, cap, fn.offset)
            if fn.default is not None:
                # default only where the offset row does NOT exist; an
                # existing-but-null offset row stays NULL (Spark)
                fill = jnp.full((cap,), fn.default, out.data.dtype)
                data = jnp.where(same_seg, out.data, fill)
                valid = out.validity | ~same_seg
                return Column(data, valid, res_type)
            return out
        if isinstance(fn, LastValue):
            idx = group_last if group_last is not None \
                else segment_ends(seg, cap)
            return gather_column(ins[0], idx)
        if isinstance(fn, FirstValue):
            return gather_column(ins[0], segment_starts(seg, cap))
        assert isinstance(fn, WindowAgg), fn
        # frame resolution: default = RANGE UNBOUNDED..CURRENT (with ties)
        # when ordered, whole partition otherwise
        range_ties = frame.kind == "default" and self._order_slots
        if frame.kind == "default":
            preceding, following = (None, 0) if self._order_slots \
                else (None, None)
        else:
            preceding, following = frame.preceding, frame.following

        values = ins[0] if ins else None
        if frame.kind == "range" and not (preceding is None
                                          and following is None):
            # bounded RANGE frame: value-offset bounds over the single
            # numeric order key (Spark's analyzer enforces exactly one)
            assert len(self._order_slots) == 1, \
                "bounded RANGE frame requires exactly one order expression"
            from ..ops.window import (range_frame_bounds, range_min_max,
                                      range_sum_count)
            asc, nf = self._order_dirs[0]
            if nf is None:
                nf = asc  # Spark default: asc => nulls first
            lo, hi = range_frame_bounds(sorted_orders[0], seg, n, cap,
                                        preceding, following, asc, nf)
            if fn.op in ("sum", "count", "avg"):
                if values is None:
                    data = jnp.ones((cap,), jnp.int64)
                    valid = active_mask(n, cap)
                else:
                    data, valid = values.data, values.validity
                s, c = range_sum_count(data, valid, seg, n, cap, lo, hi)
                if fn.op == "count":
                    return Column(c.astype(jnp.int64), ones, res_type)
                if fn.op == "avg":
                    ok = c > 0
                    d = s.astype(jnp.float64) / jnp.where(ok, c, 1)
                    return Column(jnp.where(ok, d, 0.0), ok, res_type)
                return Column(s.astype(res_type.jnp_dtype), c > 0, res_type)
            assert fn.op in ("min", "max"), fn.op
            data, valid = range_min_max(values.data, values.validity, n,
                                        cap, lo, hi, fn.op == "max")
            return Column(data.astype(values.data.dtype), valid, res_type)
        if fn.op in ("sum", "count", "avg"):
            if values is None:
                data = jnp.ones((cap,), jnp.int64)
                valid = active_mask(n, cap)
            else:
                data, valid = values.data, values.validity
            s, c = windowed_sum_count(data, valid, seg, n, cap,
                                      preceding, following)
            if range_ties and group_last is not None:
                s = s[group_last]
                c = c[group_last]
            if fn.op == "count":
                return Column(c.astype(jnp.int64), ones, res_type)
            if fn.op == "avg":
                ok = c > 0
                d = s.astype(jnp.float64) / jnp.where(ok, c, 1)
                return Column(jnp.where(ok, d, 0.0), ok, res_type)
            return Column(s.astype(res_type.jnp_dtype), c > 0, res_type)
        # min/max
        if preceding is None and following is None:
            neutral_is_max = fn.op == "max"
            # whole partition: segment reduce + broadcast
            from .aggregate import groupby_aggregate  # reuse reduce machinery
            red_fn = jax.ops.segment_max if fn.op == "max" \
                else jax.ops.segment_min
            vals = values.data
            if jnp.issubdtype(vals.dtype, jnp.floating):
                neutral = jnp.full((), -jnp.inf if fn.op == "max" else jnp.inf,
                                   vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                neutral = jnp.full((), info.min if fn.op == "max"
                                   else info.max, vals.dtype)
            act = active_mask(n, cap)
            v = jnp.where(values.validity & act, vals, neutral)
            red = red_fn(v, seg, num_segments=cap)
            cnt = jax.ops.segment_sum((values.validity & act).astype(jnp.int32),
                                      seg, num_segments=cap)
            data = whole_partition_broadcast(red, seg, cap)
            valid = whole_partition_broadcast(cnt, seg, cap) > 0
            return Column(data, valid, res_type)
        if preceding is None and following == 0:
            data, valid = running_min_max(values.data, values.validity, seg,
                                          n, cap, fn.op == "max")
            if range_ties and group_last is not None:
                data = data[group_last]
                valid = valid[group_last]
            return Column(data.astype(values.data.dtype), valid, res_type)
        # bounded frames: sparse-table sliding extrema (reference
        # GpuBatchedBoundedWindowExec.scala:220)
        data, valid = bounded_min_max(values.data, values.validity, seg,
                                      n, cap, preceding, following,
                                      fn.op == "max")
        return Column(data.astype(values.data.dtype), valid, res_type)

    # -- giant-partition two-pass (reference
    # GpuUnboundedToUnboundedAggWindowExec.scala:1155) ---------------------
    # When one partition outgrows the chunk budget AND every window
    # expression is a whole-partition aggregate, hold only tiny carry
    # STATE (sum/count/min/max scalars) plus spillable row pieces; pass 2
    # replays the pieces appending the broadcast final values. Peak device
    # memory = one chunk, not the partition.
    TWO_PASS_THRESHOLD_ROWS = 1 << 21

    def _whole_partition_aggs(self):
        """(op, input slot or None) per expr if EVERY window expression is
        a whole-partition numeric aggregate, else None."""
        out = []
        for i, (we, _) in enumerate(self.window_exprs):
            fn = we.fn
            if not isinstance(fn, WindowAgg) or fn.op not in (
                    "sum", "count", "avg", "min", "max"):
                return None
            fr = we.spec.frame
            whole = (fr.kind == "default" and not self._order_slots) or \
                (fr.kind in ("rows", "range") and fr.preceding is None
                 and fr.following is None)
            if not whole:
                return None
            slots = self._input_slots[i]
            if slots:
                from ..columnar.column import Column as _C
                ft = self._pre_schema.fields[slots[0]].data_type
                from ..types import (ByteType, DoubleType, FloatType,
                                     IntegerType, LongType, ShortType)
                if not isinstance(ft, (ByteType, ShortType, IntegerType,
                                       LongType, FloatType, DoubleType)):
                    return None
            out.append((fn.op, slots[0] if slots else None))
        return out

    class _PartitionCarry:
        """Running whole-partition aggregate state + spilled row pieces
        for ONE partition streaming through multiple chunks."""

        def __init__(self, exec_, aggs):
            self._exec = exec_
            self._aggs = aggs
            self._pieces: List = []
            self._state = None  # per-agg (sum, cnt, mn, mx) device scalars
            # the compiled update kernel lives on the exec (aggs are fixed
            # per exec), so successive giant partitions share it
            if getattr(exec_, "_jit_carry_update", None) is None:
                exec_._jit_carry_update = instrument(
                    self._update_kernel,
                    label="WindowExec.carry_update", owner=exec_)
            self._jit_update = exec_._jit_carry_update

        def _update_kernel(self, batch: ColumnarBatch, state):
            out = []
            act = active_mask(batch.num_rows, batch.capacity)
            for (op, slot), st in zip(self._aggs, state):
                s, c, mn, mx = st
                if slot is None:
                    c = c + jnp.sum(act, dtype=jnp.int64)
                    out.append((s, c, mn, mx))
                    continue
                col = batch.columns[slot]
                valid = col.validity & act
                # widen BEFORE the where: an i64 sentinel stuffed into an
                # i32 lane truncates to -1/0 and poisons the extrema
                if jnp.issubdtype(col.data.dtype, jnp.floating):
                    v = col.data.astype(jnp.float64)
                    lo_sent, hi_sent = jnp.inf, -jnp.inf
                else:
                    v = col.data.astype(jnp.int64)
                    info = jnp.iinfo(jnp.int64)
                    lo_sent, hi_sent = info.max, info.min
                s = s + jnp.sum(jnp.where(valid, v, jnp.zeros((), v.dtype)))
                c = c + jnp.sum(valid, dtype=jnp.int64)
                mn = jnp.minimum(mn, jnp.min(jnp.where(valid, v, lo_sent)))
                mx = jnp.maximum(mx, jnp.max(jnp.where(valid, v, hi_sent)))
                out.append((s, c, mn, mx))
            return tuple(out)

        def _zero_state(self, batch: ColumnarBatch):
            st = []
            for op, slot in self._aggs:
                flt = slot is not None and jnp.issubdtype(
                    batch.columns[slot].data.dtype, jnp.floating)
                s = jnp.float64(0.0) if flt else jnp.int64(0)
                mn = jnp.float64(jnp.inf) if flt \
                    else jnp.int64(jnp.iinfo(jnp.int64).max)
                mx = jnp.float64(-jnp.inf) if flt \
                    else jnp.int64(jnp.iinfo(jnp.int64).min)
                st.append((s, jnp.int64(0), mn, mx))
            return tuple(st)

        def add(self, piece: ColumnarBatch):
            from ..memory.spillable import SpillableBatch
            if self._state is None:
                self._state = self._zero_state(piece)
            self._state = self._jit_update(piece, self._state)
            self._pieces.append(SpillableBatch.from_batch(piece))

        def finalize(self) -> Iterator[ColumnarBatch]:
            ex = self._exec
            out_schema = ex.output_schema
            n_child = ex._n_child
            state = self._state
            for sp in self._pieces:
                piece = sp.get_batch()
                cap = piece.capacity
                n = piece.num_rows
                act = active_mask(n, cap)
                cols = list(piece.columns[:n_child])
                for i, ((op, slot), st) in enumerate(
                        zip(self._aggs, state)):
                    s, c, mn, mx = st
                    rt = out_schema.fields[n_child + i].data_type
                    if op == "count":
                        data, ok = jnp.broadcast_to(c, (cap,)), \
                            jnp.broadcast_to(jnp.bool_(True), (cap,))
                    elif op == "avg":
                        d = s.astype(jnp.float64) / jnp.maximum(c, 1)
                        data = jnp.broadcast_to(d, (cap,))
                        ok = jnp.broadcast_to(c > 0, (cap,))
                    elif op == "sum":
                        data = jnp.broadcast_to(
                            s.astype(rt.jnp_dtype), (cap,))
                        ok = jnp.broadcast_to(c > 0, (cap,))
                    else:
                        v = mn if op == "min" else mx
                        data = jnp.broadcast_to(
                            v.astype(rt.jnp_dtype), (cap,))
                        ok = jnp.broadcast_to(c > 0, (cap,))
                    cols.append(sanitize(
                        Column(data, ok & act, rt), n))
                yield ColumnarBatch(cols, n, out_schema)
                sp.release()
                sp.close()
            self._pieces = []

    def _part_key_match(self, columns, words: int, ref_cols, ref_idx):
        """(cap,) bool: row's partition key equals ref_cols' key at
        ref_idx. ref_cols holds ONE column per partition slot (possibly
        the same batch's columns). Shared by the last-partition split and
        the carry-continuation check — the string-lane gotchas (exact
        prefix lanes at `words`; null rows compare by validity alone, the
        underlying bytes may be arbitrary) live in exactly one place."""
        from ..columnar.column import StringColumn
        from ..ops.sort import _numeric_order_key, string_prefix_lanes
        from ..ops.strings import string_lengths

        cap = columns[self._part_slots[0]].capacity if self._part_slots \
            else 0
        same = jnp.ones((cap,), jnp.bool_)
        for c, r in zip((columns[s] for s in self._part_slots), ref_cols):
            if isinstance(c, StringColumn):
                for lane, rlane in zip(string_prefix_lanes(c, words),
                                       string_prefix_lanes(r, words)):
                    lane = jnp.where(c.validity, lane, 0)
                    rlane = jnp.where(r.validity, rlane, 0)
                    same = same & (lane == rlane[ref_idx])
                lens = jnp.where(c.validity, string_lengths(c), 0)
                rlens = jnp.where(r.validity, string_lengths(r), 0)
                same = same & (lens == rlens[ref_idx])
                same = same & (c.validity == r.validity[ref_idx])
            else:
                from ..ops.sort import numeric_order_lanes
                for lane, rlane in zip(numeric_order_lanes(c),
                                       numeric_order_lanes(r)):
                    lane = jnp.where(c.validity, lane,
                                     jnp.zeros((), lane.dtype))
                    rlane = jnp.where(r.validity, rlane,
                                      jnp.zeros((), rlane.dtype))
                    same = same & (lane == rlane[ref_idx])
                same = same & (c.validity == r.validity[ref_idx])
        return same

    def _first_partition_len(self, batch: ColumnarBatch, words: int,
                             ref_cols) -> int:
        """Host int: number of leading rows whose partition key equals the
        CARRY partition's key (ref_cols, one 1-row column per partition
        slot) — NOT the batch's own first key, which would fold a fresh
        partition into the carry when a chunk boundary lands exactly on
        the giant partition's end."""
        if self._jit_fpl is None:
            def fpl(b: ColumnarBatch, w: int, refs):
                n = b.num_rows
                cap = b.capacity
                same = self._part_key_match(b.columns, w, refs, 0)
                act = active_mask(n, cap)
                idx = jnp.arange(cap, dtype=jnp.int32)
                nm = jnp.min(jnp.where(act & ~same, idx, cap))
                return jnp.minimum(nm, n)

            self._jit_fpl = instrument(fpl,
                                       label="WindowExec.first_part_len",
                                       owner=self, static_argnums=(1,))
        return int(self._jit_fpl(batch, words, ref_cols))

    # -- drive -------------------------------------------------------------
    def _last_partition_start(self, batch: ColumnarBatch,
                              words: int) -> int:
        """Host int: index of the first row of the LAST partition key in
        a (partition, order)-sorted batch. One tiny device sync per
        chunk — the price of partition-aligned batching."""
        if self._jit_lps is None:
            def lps(b: ColumnarBatch, w: int):
                n = b.num_rows
                cap = b.capacity
                last = jnp.clip(n - 1, 0, cap - 1)
                same = self._part_key_match(
                    b.columns, w, [b.columns[s] for s in self._part_slots],
                    last)
                act = active_mask(n, cap)
                # first index i such that rows i..n-1 all match the last
                # key: max over non-matching active rows + 1
                idx = jnp.arange(cap, dtype=jnp.int32)
                nm = jnp.max(jnp.where(act & ~same, idx, -1))
                return nm + 1

            self._jit_lps = instrument(lps,
                                       label="WindowExec.last_part_start",
                                       owner=self, static_argnums=(1,))
        return int(self._jit_lps(batch, words))

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        try:
            yield from self._execute_window()
        finally:
            self._gather_track.emit_event(type(self).__name__,
                                          self._op_id)

    def _execute_window(self) -> Iterator[ColumnarBatch]:
        """Partition-aware batched drive (replaces the r2 concat-all):
        the pre-projected input streams through the out-of-core sort on
        (partition, order) keys; each sorted chunk is windowed
        independently after holding back its final (possibly incomplete)
        partition, which is prepended to the next chunk. Memory peak =
        sort budget + largest single partition (the reference's
        GpuBatchedBoundedWindowExec/GpuRunningWindowExec bound the same
        way). Without partition keys the whole input is one partition
        and degrades to a single batch, as before."""
        from ..columnar.column import bucket_capacity
        from ..ops.basic import slice_rows
        from .sort import SortExec

        with self.metrics[OP_TIME].ns_timer():
            source = _StreamSourceExec(
                self._pre_schema,
                (self._jit_pre(b) for b in self.child.execute()))
            if not self._part_slots:
                batches = list(source.execute())
                if not batches:
                    return
                merged = concat_batches(batches, self._pre_schema)
                words = string_words_for(
                    merged.columns, self._part_slots + self._order_slots)
                yield self._dispatch_window(merged, words)
                return

            orders = [SortOrder(s) for s in self._part_slots] + [
                SortOrder(s, asc, nf) for s, (asc, nf)
                in zip(self._order_slots, self._order_dirs)]
            sorter = SortExec(orders, source)
            held: ColumnarBatch = None
            carry = None
            two_pass_aggs = self._whole_partition_aggs()
            saw = False
            for chunk in sorter.execute():
                saw = True
                if carry is not None:
                    # an active giant partition: rows continuing it fold
                    # into the carry state; the first foreign key closes it
                    cw = string_words_for(
                        chunk.columns, self._part_slots + self._order_slots)
                    cw = max(cw, carry_words)
                    flen = self._first_partition_len(chunk, cw, carry_ref)
                    nch = chunk.num_rows_host
                    if flen >= nch:
                        carry.add(chunk)
                        continue
                    if flen > 0:
                        hcap = bucket_capacity(max(flen, 1))
                        carry.add(ColumnarBatch(
                            [slice_rows(c, jnp.int32(0), jnp.int32(flen),
                                        hcap) for c in chunk.columns],
                            flen, self._pre_schema))
                    yield from carry.finalize()
                    carry = None
                    rest_n = nch - flen
                    rcap = bucket_capacity(max(rest_n, 1))
                    chunk = ColumnarBatch(
                        [slice_rows(c, jnp.int32(flen), jnp.int32(rest_n),
                                    rcap) for c in chunk.columns],
                        rest_n, self._pre_schema)
                if held is not None and held.num_rows_host > 0:
                    cur = concat_batches([held, chunk], self._pre_schema)
                else:
                    cur = chunk
                n = cur.num_rows_host
                cur_words = string_words_for(
                    cur.columns, self._part_slots + self._order_slots)
                split = self._last_partition_start(cur, cur_words)
                if split <= 0:
                    # one giant partition so far: switch to carry state if
                    # every expression is a whole-partition aggregate,
                    # else keep growing (concat fallback)
                    if two_pass_aggs is not None and \
                            n > self.TWO_PASS_THRESHOLD_ROWS:
                        carry = self._PartitionCarry(self, two_pass_aggs)
                        carry.add(cur)
                        # 1-row reference key identifying the carried
                        # partition (continuation checks compare against
                        # THIS, not an incoming chunk's own first row)
                        carry_ref = [
                            slice_rows(cur.columns[s], jnp.int32(0),
                                       jnp.int32(1), bucket_capacity(1))
                            for s in self._part_slots]
                        carry_words = cur_words
                        held = None
                    else:
                        held = cur
                    continue
                ready_cap = bucket_capacity(max(split, 1))
                ready = ColumnarBatch(
                    [slice_rows(c, jnp.int32(0), jnp.int32(split),
                                ready_cap) for c in cur.columns],
                    split, self._pre_schema)
                tail_n = n - split
                tail_cap = bucket_capacity(max(tail_n, 1))
                held = ColumnarBatch(
                    [slice_rows(c, jnp.int32(split), jnp.int32(tail_n),
                                tail_cap) for c in cur.columns],
                    tail_n, self._pre_schema)
                # cur_words stays exact for the prefix slice: reuse it
                # instead of paying a second measuring sync per chunk
                yield self._dispatch_window(ready, cur_words)
            if not saw:
                return
            if carry is not None:
                yield from carry.finalize()
            elif held is not None and held.num_rows_host > 0:
                words = string_words_for(
                    held.columns, self._part_slots + self._order_slots)
                yield self._dispatch_window(held, words)
