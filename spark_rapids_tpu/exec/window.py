"""WindowExec — reference GpuWindowExec.scala:146 and its specializations
(running, double-pass, bounded, unbounded-to-unbounded). One exec here:
every frame shape lowers to segmented scans over partition-sorted rows
(ops/window.py), so the reference's four execution strategies collapse
into one compiled program per window-expression set.

Frame coverage: ROWS frames with any bounds (sum/count/avg via prefix
differences; min/max via the sparse-table sliding-extrema kernel,
ops/window.bounded_min_max); RANGE frames support the default (UNBOUNDED
PRECEDING..CURRENT ROW with ties) shape. Whole input is windowed as one
concatenated batch — partition-boundary batching rides the out-of-core
sort work.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column
from ..expr.core import Expression
from ..expr.windowexprs import (
    DenseRank, FirstValue, Lag, LastValue, Rank, RowNumber, WindowAgg,
    WindowExpression, WindowFrame,
)
from ..ops.basic import active_mask, gather_column, sanitize
from ..ops.sort import (
    SortOrder, group_segment_ids, order_key_lanes, sort_permutation,
    string_words_for,
)
from ..ops.window import (
    bounded_min_max, lag_lead, rank_dense_rank, row_number, running_min_max,
    segment_ends, segment_starts, whole_partition_broadcast,
    windowed_sum_count,
)
from ..types import DoubleType, IntegerType, LongType, Schema, StructField
from .base import OP_TIME, TpuExec
from .basic import bind_projection, eval_projection, projection_schema
from .coalesce import concat_batches
from .sort import resolve_sort_orders


class _StreamSourceExec(TpuExec):
    """Leaf yielding batches from a generator (keeps the window's sort
    input streaming instead of materialized)."""

    def __init__(self, schema: Schema, gen):
        super().__init__()
        self._schema = schema
        self._gen = gen

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        yield from self._gen


class WindowExec(TpuExec):
    def __init__(self, window_exprs: Sequence[Tuple[WindowExpression, str]],
                 child: TpuExec):
        super().__init__(child)
        self.window_exprs = list(window_exprs)
        in_schema = child.output_schema
        # all specs must share partition/order in one exec (the planner
        # splits differing specs into stacked WindowExecs, like Spark)
        spec0 = self.window_exprs[0][0].spec
        for we, _ in self.window_exprs:
            assert we.spec.partition_by == spec0.partition_by
            assert we.spec.order_by == spec0.order_by
        self.spec = spec0

        # pre-projection: child cols + partition keys + order keys + inputs
        from ..expr.core import col
        self._pre_exprs: List[Expression] = [col(n) for n in in_schema.names]
        self._n_child = len(in_schema.fields)
        self._part_slots = []
        for e in self.spec.partition_by:
            self._part_slots.append(len(self._pre_exprs))
            self._pre_exprs.append(e.alias(f"_wpart{len(self._part_slots)}"))
        self._order_slots = []
        self._order_dirs = []
        for o in self.spec.order_by:
            e, asc = o[0], o[1] if len(o) > 1 else True
            nf = o[2] if len(o) > 2 else None
            self._order_slots.append(len(self._pre_exprs))
            self._order_dirs.append((asc, nf))
            self._pre_exprs.append(e.alias(f"_word{len(self._order_slots)}"))
        self._input_slots: List[List[int]] = []
        for we, _ in self.window_exprs:
            slots = []
            for e in we.fn.inputs:
                slots.append(len(self._pre_exprs))
                self._pre_exprs.append(e.alias(f"_win{len(self._pre_exprs)}"))
            self._input_slots.append(slots)
        self._pre_bound = bind_projection(self._pre_exprs, in_schema)
        self._pre_schema = projection_schema(self._pre_exprs, in_schema)
        self._jit_window = jax.jit(self._window_kernel, static_argnums=(1,))
        self._jit_lps = None
        self._jit_pre = jax.jit(lambda b: eval_projection(
            self._pre_bound, b, self._pre_schema))

    @property
    def output_schema(self) -> Schema:
        fields = list(self.child.output_schema.fields)
        for i, (we, name) in enumerate(self.window_exprs):
            in_types = [self._pre_schema.fields[s].data_type
                        for s in self._input_slots[i]]
            fields.append(StructField(name, we.fn.result_type(in_types)))
        return Schema(tuple(fields))

    # -- kernel ------------------------------------------------------------
    def _window_kernel(self, batch: ColumnarBatch, words: int
                       ) -> ColumnarBatch:
        cap = batch.capacity
        n = batch.num_rows
        part_cols = [batch.columns[s] for s in self._part_slots]
        order_cols = [batch.columns[s] for s in self._order_slots]

        orders = [SortOrder(s) for s in self._part_slots] + [
            SortOrder(s, asc, nf) for s, (asc, nf)
            in zip(self._order_slots, self._order_dirs)]
        perm = sort_permutation(batch.columns, orders, n, cap, words)
        sorted_cols = [gather_column(c, perm) for c in batch.columns]
        sorted_parts = [sorted_cols[s] for s in self._part_slots]
        sorted_orders = [sorted_cols[s] for s in self._order_slots]

        if self._part_slots:
            seg, _ = group_segment_ids(sorted_parts, n, cap, words)
        else:
            act = active_mask(n, cap)
            seg = jnp.where(act, 0, cap)

        # order-key boundary mask (first row of each distinct order key)
        if self._order_slots:
            lanes = order_key_lanes(
                sorted_orders, [SortOrder(i) for i in range(len(sorted_orders))],
                n, cap, words)[1:]
            ob = jnp.zeros((cap,), jnp.bool_)
            for lane in lanes:
                ob = ob | (lane != jnp.roll(lane, 1))
            ob = ob.at[0].set(True)
            # per-row last index of its order group (for RANGE-with-ties)
            gid = jnp.cumsum((ob | jnp.concatenate(
                [jnp.ones(1, jnp.bool_), seg[1:] != seg[:-1]])).astype(jnp.int32)) - 1
            gid = jnp.where(active_mask(n, cap), gid, cap)
            positions = jnp.arange(cap, dtype=jnp.int32)
            glast = jax.ops.segment_max(positions, gid, num_segments=cap)
            group_last = jnp.clip(glast[jnp.clip(gid, 0, cap - 1)], 0, cap - 1)
        else:
            ob = None
            group_last = None

        out_cols = list(sorted_cols[: self._n_child])
        out_schema = self.output_schema
        for i, (we, name) in enumerate(self.window_exprs):
            fn = we.fn
            res_type = out_schema.fields[self._n_child + i].data_type
            ins = [sorted_cols[s] for s in self._input_slots[i]]
            col = self._eval_fn(fn, we.spec.frame, ins, seg, ob, group_last,
                                n, cap, res_type)
            out_cols.append(sanitize(col, n))
        return ColumnarBatch(out_cols, n, out_schema)

    def _eval_fn(self, fn, frame, ins, seg, order_boundary, group_last,
                 n, cap, res_type) -> Column:
        ones = jnp.ones((cap,), jnp.bool_)
        if isinstance(fn, RowNumber):
            return Column(row_number(seg, n, cap), ones, res_type)
        if isinstance(fn, DenseRank):
            _, dense = rank_dense_rank(order_boundary, seg, n, cap)
            return Column(dense, ones, res_type)
        if isinstance(fn, Rank):
            rank, _ = rank_dense_rank(order_boundary, seg, n, cap)
            return Column(rank, ones, res_type)
        if isinstance(fn, Lag):  # covers Lead (negated offset)
            out, same_seg = lag_lead(ins[0], seg, n, cap, fn.offset)
            if fn.default is not None:
                # default only where the offset row does NOT exist; an
                # existing-but-null offset row stays NULL (Spark)
                fill = jnp.full((cap,), fn.default, out.data.dtype)
                data = jnp.where(same_seg, out.data, fill)
                valid = out.validity | ~same_seg
                return Column(data, valid, res_type)
            return out
        if isinstance(fn, LastValue):
            idx = group_last if group_last is not None \
                else segment_ends(seg, cap)
            return gather_column(ins[0], idx)
        if isinstance(fn, FirstValue):
            return gather_column(ins[0], segment_starts(seg, cap))
        assert isinstance(fn, WindowAgg), fn
        # frame resolution: default = RANGE UNBOUNDED..CURRENT (with ties)
        # when ordered, whole partition otherwise
        range_ties = frame.kind == "default" and self._order_slots
        if frame.kind == "default":
            preceding, following = (None, 0) if self._order_slots \
                else (None, None)
        else:
            preceding, following = frame.preceding, frame.following

        values = ins[0] if ins else None
        if fn.op in ("sum", "count", "avg"):
            if values is None:
                data = jnp.ones((cap,), jnp.int64)
                valid = active_mask(n, cap)
            else:
                data, valid = values.data, values.validity
            s, c = windowed_sum_count(data, valid, seg, n, cap,
                                      preceding, following)
            if range_ties and group_last is not None:
                s = s[group_last]
                c = c[group_last]
            if fn.op == "count":
                return Column(c.astype(jnp.int64), ones, res_type)
            if fn.op == "avg":
                ok = c > 0
                d = s.astype(jnp.float64) / jnp.where(ok, c, 1)
                return Column(jnp.where(ok, d, 0.0), ok, res_type)
            return Column(s.astype(res_type.jnp_dtype), c > 0, res_type)
        # min/max
        if preceding is None and following is None:
            neutral_is_max = fn.op == "max"
            # whole partition: segment reduce + broadcast
            from .aggregate import groupby_aggregate  # reuse reduce machinery
            red_fn = jax.ops.segment_max if fn.op == "max" \
                else jax.ops.segment_min
            vals = values.data
            if jnp.issubdtype(vals.dtype, jnp.floating):
                neutral = jnp.full((), -jnp.inf if fn.op == "max" else jnp.inf,
                                   vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                neutral = jnp.full((), info.min if fn.op == "max"
                                   else info.max, vals.dtype)
            act = active_mask(n, cap)
            v = jnp.where(values.validity & act, vals, neutral)
            red = red_fn(v, seg, num_segments=cap)
            cnt = jax.ops.segment_sum((values.validity & act).astype(jnp.int32),
                                      seg, num_segments=cap)
            data = whole_partition_broadcast(red, seg, cap)
            valid = whole_partition_broadcast(cnt, seg, cap) > 0
            return Column(data, valid, res_type)
        if preceding is None and following == 0:
            data, valid = running_min_max(values.data, values.validity, seg,
                                          n, cap, fn.op == "max")
            if range_ties and group_last is not None:
                data = data[group_last]
                valid = valid[group_last]
            return Column(data.astype(values.data.dtype), valid, res_type)
        # bounded frames: sparse-table sliding extrema (reference
        # GpuBatchedBoundedWindowExec.scala:220)
        data, valid = bounded_min_max(values.data, values.validity, seg,
                                      n, cap, preceding, following,
                                      fn.op == "max")
        return Column(data.astype(values.data.dtype), valid, res_type)

    # -- drive -------------------------------------------------------------
    def _last_partition_start(self, batch: ColumnarBatch,
                              words: int) -> int:
        """Host int: index of the first row of the LAST partition key in
        a (partition, order)-sorted batch. One tiny device sync per
        chunk — the price of partition-aligned batching."""
        if self._jit_lps is None:
            from ..ops.sort import _numeric_order_key

            def lps(b: ColumnarBatch, w: int):
                n = b.num_rows
                cap = b.capacity
                last = jnp.clip(n - 1, 0, cap - 1)
                same = jnp.ones((cap,), jnp.bool_)
                for s in self._part_slots:
                    c = b.columns[s]
                    from ..columnar.column import StringColumn
                    if isinstance(c, StringColumn):
                        from ..ops.sort import string_prefix_lanes
                        from ..ops.strings import string_lengths
                        # prefix lanes are exact at `w` (string_words_for);
                        # null rows compare by validity alone (their
                        # underlying bytes may be arbitrary)
                        for lane in string_prefix_lanes(c, w):
                            lane = jnp.where(c.validity, lane, 0)
                            same = same & (lane == lane[last])
                        lens = jnp.where(c.validity, string_lengths(c), 0)
                        same = same & (lens == lens[last])
                        same = same & (c.validity == c.validity[last])
                    else:
                        lane = _numeric_order_key(c)
                        lane = jnp.where(c.validity, lane,
                                         jnp.zeros((), lane.dtype))
                        same = same & (lane == lane[last]) \
                            & (c.validity == c.validity[last])
                act = active_mask(n, cap)
                # first index i such that rows i..n-1 all match the last
                # key: max over non-matching active rows + 1
                idx = jnp.arange(cap, dtype=jnp.int32)
                nm = jnp.max(jnp.where(act & ~same, idx, -1))
                return nm + 1

            self._jit_lps = jax.jit(lps, static_argnums=(1,))
        return int(self._jit_lps(batch, words))

    def internal_execute(self) -> Iterator[ColumnarBatch]:
        """Partition-aware batched drive (replaces the r2 concat-all):
        the pre-projected input streams through the out-of-core sort on
        (partition, order) keys; each sorted chunk is windowed
        independently after holding back its final (possibly incomplete)
        partition, which is prepended to the next chunk. Memory peak =
        sort budget + largest single partition (the reference's
        GpuBatchedBoundedWindowExec/GpuRunningWindowExec bound the same
        way). Without partition keys the whole input is one partition
        and degrades to a single batch, as before."""
        from ..columnar.column import bucket_capacity
        from ..ops.basic import slice_rows
        from .sort import SortExec

        with self.metrics[OP_TIME].ns_timer():
            source = _StreamSourceExec(
                self._pre_schema,
                (self._jit_pre(b) for b in self.child.execute()))
            if not self._part_slots:
                batches = list(source.execute())
                if not batches:
                    return
                merged = concat_batches(batches, self._pre_schema)
                words = string_words_for(
                    merged.columns, self._part_slots + self._order_slots)
                yield self._jit_window(merged, words)
                return

            orders = [SortOrder(s) for s in self._part_slots] + [
                SortOrder(s, asc, nf) for s, (asc, nf)
                in zip(self._order_slots, self._order_dirs)]
            sorter = SortExec(orders, source)
            held: ColumnarBatch = None
            saw = False
            for chunk in sorter.execute():
                saw = True
                if held is not None and held.num_rows_host > 0:
                    cur = concat_batches([held, chunk], self._pre_schema)
                else:
                    cur = chunk
                n = cur.num_rows_host
                cur_words = string_words_for(
                    cur.columns, self._part_slots + self._order_slots)
                split = self._last_partition_start(cur, cur_words)
                if split <= 0:
                    held = cur  # one giant partition so far: keep growing
                    continue
                ready_cap = bucket_capacity(max(split, 1))
                ready = ColumnarBatch(
                    [slice_rows(c, jnp.int32(0), jnp.int32(split),
                                ready_cap) for c in cur.columns],
                    split, self._pre_schema)
                tail_n = n - split
                tail_cap = bucket_capacity(max(tail_n, 1))
                held = ColumnarBatch(
                    [slice_rows(c, jnp.int32(split), jnp.int32(tail_n),
                                tail_cap) for c in cur.columns],
                    tail_n, self._pre_schema)
                # cur_words stays exact for the prefix slice: reuse it
                # instead of paying a second measuring sync per chunk
                yield self._jit_window(ready, cur_words)
            if not saw:
                return
            if held is not None and held.num_rows_host > 0:
                words = string_words_for(
                    held.columns, self._part_slots + self._order_slots)
                yield self._jit_window(held, words)
