"""Task-level metrics roll-up — reference GpuTaskMetrics
(GpuTaskMetrics.scala:81-103: semWaitTimeNs, retryCount,
splitAndRetryCount, spill/readSpill sizes accumulated per task and
published into Spark task metrics).

Standalone, a "task" is one driven query: `query_snapshot()` captures
the process-global accumulators (admission-semaphore wait, OOM-retry
counters, spill volumes) before execution, and `query_summary()` diffs
them after and rolls the per-operator metric registries of the executed
TpuExec tree into one flat per-query dict. The session API surfaces it
as `TpuSession.last_query_metrics()` after every `DataFrame.collect()`
(ISSUE 1 satellite, VERDICT Missing #8).

Shape of the summary:
- task-scoped globals (diffed):  semWaitTimeNs, retryCount,
  splitAndRetryCount, spilledDeviceBytes, spilledHostBytes
- per-metric sums over the operator tree:  total.<metricName>
- per-operator breakdown:  ops.<Path>.<metricName>  (same addressing as
  TpuExec.all_metrics)
"""

from __future__ import annotations

from typing import Dict

from .base import TpuExec


def query_snapshot() -> Dict[str, int]:
    """Process-global accumulators BEFORE a query, for delta-ing."""
    from ..memory.catalog import buffer_catalog
    from ..memory.retry import task_retry_counts
    from ..memory.semaphore import tpu_semaphore
    retry, split_retry = task_retry_counts()
    cat = buffer_catalog()
    return {
        "semWaitTimeNs": tpu_semaphore().total_wait_ns,
        "retryCount": retry,
        "splitAndRetryCount": split_retry,
        "spilledDeviceBytes": cat.spilled_device_bytes,
        "spilledHostBytes": cat.spilled_host_bytes,
    }


def query_summary(root: TpuExec,
                  before: Dict[str, int] | None = None) -> Dict[str, int]:
    """Roll one executed plan's metrics into a per-query summary.

    `before`: a query_snapshot() taken before execution; the summary
    reports the DELTA of each global accumulator (what THIS query spent,
    the analog of per-task attribution in GpuTaskMetrics). Without it
    the raw running totals are reported.
    """
    after = query_snapshot()
    out: Dict[str, int] = {}
    for k, v in after.items():
        out[k] = v - (before or {}).get(k, 0)

    per_op = root.all_metrics()
    totals: Dict[str, int] = {}
    for path, value in per_op.items():
        name = path.rsplit(".", 1)[1]
        totals[name] = totals.get(name, 0) + value
    for name in sorted(totals):
        out[f"total.{name}"] = totals[name]
    for path in sorted(per_op):
        out[f"ops.{path}"] = per_op[path]
    return out
