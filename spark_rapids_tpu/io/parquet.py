"""Parquet scan + write (reference GpuParquetScan.scala readers at
:1860/:2051/:2739, writer GpuParquetFileFormat.scala:167).

Read path: footer-driven row-group slicing (each row group is one decode
task), decoded by pyarrow's C++ reader on a prefetch thread pool
(MULTITHREADED analog), uploaded as device columns. Column pruning via
`columns`. Row-group pruning evaluates pushed-down simple predicates
(col <op> literal conjuncts, extracted by the planner from the Filter
above the scan) against footer min/max/null-count statistics — pruned
groups are never decoded; `row_groups_read`/`row_groups_pruned` record
the effect. The COALESCING reader mode stitches small row groups into one
host table per ~batch_rows before upload (reference
GpuMultiFileReader.scala:830), halving per-batch upload overhead for
many-small-files layouts.

Write path: host materialization -> pyarrow writer, with Spark-style
dynamic partitioning (partition_by -> key=value directories, reference
GpuFileFormatDataWriter dynamic partitioning)."""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks

#: decode threads (reference spark.rapids.sql.multiThreadedRead.numThreads)
DEFAULT_NUM_THREADS = 8
#: rows per emitted device batch before coalescing
DEFAULT_BATCH_ROWS = 1 << 20

#: pushed predicate: (column name, op, literal) with op in the set below
_PRUNE_OPS = ("<", "<=", ">", ">=", "==", "is_null", "is_not_null")


def _stats_can_skip(stats, op: str, value) -> bool:
    """True iff footer statistics PROVE no row in the group can satisfy
    the predicate (missing/partial stats never prune)."""
    if stats is None:
        return False
    if op == "is_null":
        return stats.null_count == 0 if stats.null_count is not None \
            else False
    if op == "is_not_null":
        nc = stats.null_count
        nv = stats.num_values
        return nv == 0 if (nc is not None and nv is not None) else False
    if not stats.has_min_max:
        return False
    mn, mx = stats.min, stats.max
    if mn is None or mx is None:
        return False
    try:
        if op == "==":
            return value < mn or value > mx
        if op == "<":
            return mn >= value
        if op == "<=":
            return mn > value
        if op == ">":
            return mx <= value
        if op == ">=":
            return mx < value
    except TypeError:
        return False  # incomparable (e.g. bytes stats vs str literal)
    return False


class ParquetSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 columns: Optional[Sequence[str]] = None,
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS,
                 filters: Optional[Sequence[Tuple[str, str, object]]] = None,
                 reader_type: Optional[str] = None):
        import pyarrow.parquet as pq
        self.paths = expand_paths(path)
        assert self.paths, f"no parquet files at {path!r}"
        self.columns = list(columns) if columns is not None else None
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self.filters = list(filters or [])
        self._conf = conf
        if reader_type is None and conf is not None:
            from ..config import PARQUET_READER_TYPE
            reader_type = conf.get(PARQUET_READER_TYPE)
        self.reader_type = (reader_type or "MULTITHREADED").upper()
        arrow_schema = pq.read_schema(self.paths[0])
        fields = []
        for name in (self.columns or arrow_schema.names):
            f = arrow_schema.field(name)
            fields.append(StructField(f.name, from_arrow(f.type), f.nullable))
        self.schema = Schema(tuple(fields))
        #: observability: updated by the last batches() drive; shared with
        #: with_filters() copies so the user-held source sees the effect
        self.scan_stats = {"row_groups_read": 0, "row_groups_pruned": 0}

    @property
    def row_groups_read(self) -> int:
        return self.scan_stats["row_groups_read"]

    @property
    def row_groups_pruned(self) -> int:
        return self.scan_stats["row_groups_pruned"]

    def with_filters(self, filters: Sequence[Tuple[str, str, object]]
                     ) -> "ParquetSource":
        """Planner pushdown hook: a copy of this source that prunes row
        groups with the given conjuncts (the Filter stays above the scan
        for exactness — stats only prove absence, never presence). A
        shallow copy: the schema/path work from __init__ (footer read) is
        NOT repeated."""
        out = ParquetSource.__new__(ParquetSource)
        out.__dict__.update(self.__dict__)
        out.filters = list(self.filters) + list(filters)
        return out

    def estimated_size_bytes(self) -> int:
        """Broadcast-planning size estimate: on-disk bytes (compressed, so
        an underestimate like Spark's file-size statistics)."""
        return sum(os.path.getsize(p) for p in self.paths)

    def _read_dictionary(self) -> Optional[List[str]]:
        """Columns pyarrow should hand back AS dictionary arrays instead
        of casting the Parquet dictionary pages away (ISSUE 18): the
        scanned string/binary columns, when the encoded-execution lane
        is on. None keeps the plain decode."""
        from ..config import SCAN_ENCODED, active_conf
        from ..types import BinaryType, StringType
        conf = self._conf if self._conf is not None else active_conf()
        if not conf.get(SCAN_ENCODED):
            return None
        names = [f.name for f in self.schema.fields
                 if isinstance(f.data_type, (StringType, BinaryType))]
        return names or None

    def _group_pruned(self, md, rg: int, name_to_idx) -> bool:
        row_group = md.row_group(rg)
        for (name, op, value) in self.filters:
            ci = name_to_idx.get(name)
            if ci is None:
                continue
            stats = row_group.column(ci).statistics
            if _stats_can_skip(stats, op, value):
                return True
        return False

    def batches(self) -> Iterator[ColumnarBatch]:
        import pyarrow.parquet as pq

        tasks = []
        self.scan_stats["row_groups_read"] = 0
        self.scan_stats["row_groups_pruned"] = 0
        # LEGACY rebase: footer stats are hybrid-Julian day numbers while
        # pushed filter literals are proleptic-Gregorian — comparing them
        # could prune groups whose REBASED rows match, so stats pruning is
        # disabled entirely under LEGACY (the reference does the same)
        from ..config import PARQUET_REBASE_MODE_READ
        legacy_rebase = (self._conf is not None and
                         self._conf.get(PARQUET_REBASE_MODE_READ).upper()
                         == "LEGACY")
        may_prune = bool(self.filters) and not legacy_rebase
        read_dict = self._read_dictionary()
        for p in self.paths:
            pf = pq.ParquetFile(p)
            md = pf.metadata
            name_to_idx = {md.schema.column(i).name: i
                           for i in range(md.num_columns)}
            for rg in range(md.num_row_groups):
                if may_prune and self._group_pruned(md, rg, name_to_idx):
                    self.scan_stats["row_groups_pruned"] += 1
                    continue
                self.scan_stats["row_groups_read"] += 1

                def decode(p=p, rg=rg):
                    # fresh handle per task: ParquetFile is not thread-safe
                    return pq.ParquetFile(
                        p, read_dictionary=read_dict).read_row_group(
                        rg, columns=self.columns)
                tasks.append(decode)
            if md.num_row_groups == 0:
                tasks.append(lambda p=p: pq.read_table(
                    p, columns=self.columns, read_dictionary=read_dict))
        if self.reader_type == "COALESCING":
            out = self._coalescing_drive(tasks)
        else:
            out = (b for table in threaded_chunks(tasks, self.num_threads)
                   for b in arrow_to_batches(table, self.batch_rows))
        yield from self._maybe_rebase(out, legacy_rebase)

    def _maybe_rebase(self, batches: Iterator[ColumnarBatch],
                      legacy: bool) -> Iterator[ColumnarBatch]:
        """LEGACY datetimeRebaseModeInRead: files written in the hybrid
        Julian calendar get their DATE/TIMESTAMP columns rebased to
        proleptic Gregorian on device (reference datetimeRebaseUtils +
        JNI DateTimeRebase; kernels in ops/rebase.py). `legacy` comes
        from the ONE mode parse in batches() — the same flag that
        disabled stats pruning, so the two can never diverge."""
        from ..types import DateType, TimestampNTZType, TimestampType
        if not legacy:
            yield from batches
            return
        from ..columnar.column import Column
        from ..ops.rebase import (rebase_julian_to_gregorian_days,
                                  rebase_julian_to_gregorian_micros)
        for b in batches:
            cols = []
            for c, f in zip(b.columns, b.schema.fields):
                if isinstance(f.data_type, DateType):
                    cols.append(Column(
                        rebase_julian_to_gregorian_days(
                            c.data.astype("int64")).astype(c.data.dtype),
                        c.validity, c.dtype))
                elif isinstance(f.data_type,
                                (TimestampType, TimestampNTZType)):
                    cols.append(Column(
                        rebase_julian_to_gregorian_micros(c.data),
                        c.validity, c.dtype))
                else:
                    cols.append(c)
            yield b.with_columns(cols, b.schema)

    def _coalescing_drive(self, tasks) -> Iterator[ColumnarBatch]:
        """Stitch decoded row groups host-side into ~batch_rows tables
        before the (expensive) device upload (reference COALESCING reader,
        GpuMultiFileReader.scala:830)."""
        import pyarrow as pa
        pending: List = []
        pending_rows = 0
        for table in threaded_chunks(tasks, self.num_threads):
            pending.append(table)
            pending_rows += table.num_rows
            if pending_rows >= self.batch_rows:
                yield from arrow_to_batches(pa.concat_tables(pending),
                                            self.batch_rows)
                pending, pending_rows = [], 0
        if pending:
            yield from arrow_to_batches(pa.concat_tables(pending),
                                        self.batch_rows)


def write_parquet(df, path, partition_by: Optional[Sequence[str]] = None):
    """DataFrame -> parquet file/directory with optional hive-style
    partitioning."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = df.to_arrow()
    if not partition_by:
        if os.path.isdir(path) or str(path).endswith("/"):
            os.makedirs(path, exist_ok=True)
            pq.write_table(table, os.path.join(path, "part-00000.parquet"))
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            pq.write_table(table, path)
        return
    import pyarrow.dataset as ds
    os.makedirs(path, exist_ok=True)
    ds.write_dataset(table, path, format="parquet",
                     partitioning=list(partition_by),
                     partitioning_flavor="hive",
                     existing_data_behavior="overwrite_or_ignore")
