"""Parquet scan + write (reference GpuParquetScan.scala readers at
:1860/:2051/:2739, writer GpuParquetFileFormat.scala:167).

Read path: footer-driven row-group slicing (each row group is one decode
task, the granularity the reference stitches in its COALESCING reader),
decoded by pyarrow's C++ reader on a prefetch thread pool (MULTITHREADED
analog), uploaded as device columns. Column pruning via `columns`;
row-group pruning via min/max statistics against simple predicates
(the reference's predicate pushdown).

Write path: host materialization -> pyarrow writer, with Spark-style
dynamic partitioning (partition_by -> key=value directories, reference
GpuFileFormatDataWriter dynamic partitioning)."""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks

#: decode threads (reference spark.rapids.sql.multiThreadedRead.numThreads)
DEFAULT_NUM_THREADS = 8
#: rows per emitted device batch before coalescing
DEFAULT_BATCH_ROWS = 1 << 20


class ParquetSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 columns: Optional[Sequence[str]] = None,
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        import pyarrow.parquet as pq
        self.paths = expand_paths(path)
        assert self.paths, f"no parquet files at {path!r}"
        self.columns = list(columns) if columns is not None else None
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        arrow_schema = pq.read_schema(self.paths[0])
        fields = []
        for name in (self.columns or arrow_schema.names):
            f = arrow_schema.field(name)
            fields.append(StructField(f.name, from_arrow(f.type), f.nullable))
        self.schema = Schema(tuple(fields))

    def estimated_size_bytes(self) -> int:
        """Broadcast-planning size estimate: on-disk bytes (compressed, so
        an underestimate like Spark's file-size statistics)."""
        import os
        return sum(os.path.getsize(p) for p in self.paths)

    def batches(self) -> Iterator[ColumnarBatch]:
        import pyarrow.parquet as pq

        tasks = []
        for p in self.paths:
            pf = pq.ParquetFile(p)
            for rg in range(pf.metadata.num_row_groups):
                def decode(p=p, rg=rg):
                    # fresh handle per task: ParquetFile is not thread-safe
                    return pq.ParquetFile(p).read_row_group(
                        rg, columns=self.columns)
                tasks.append(decode)
            if pf.metadata.num_row_groups == 0:
                tasks.append(lambda p=p: pq.read_table(p,
                                                      columns=self.columns))
        for table in threaded_chunks(tasks, self.num_threads):
            yield from arrow_to_batches(table, self.batch_rows)


def write_parquet(df, path, partition_by: Optional[Sequence[str]] = None):
    """DataFrame -> parquet file/directory with optional hive-style
    partitioning."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = df.to_arrow()
    if not partition_by:
        if os.path.isdir(path) or str(path).endswith("/"):
            os.makedirs(path, exist_ok=True)
            pq.write_table(table, os.path.join(path, "part-00000.parquet"))
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            pq.write_table(table, path)
        return
    import pyarrow.dataset as ds
    os.makedirs(path, exist_ok=True)
    ds.write_dataset(table, path, format="parquet",
                     partitioning=list(partition_by),
                     partitioning_flavor="hive",
                     existing_data_behavior="overwrite_or_ignore")
