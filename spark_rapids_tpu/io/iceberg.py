"""Iceberg table integration (reference: sql-plugin's Java iceberg/
package + IcebergProvider.scala — DSv2 scan over Iceberg metadata; SURVEY
§2.7 #48). Minimal modern subset: format-version-1 tables, snapshot scan
through the metadata chain

    metadata/vN.metadata.json → snapshot.manifest-list (avro)
      → manifests (avro, nested data_file records) → parquet data files

decoded entirely with the engine's own avro row codec (io/avro.py) and
read through the parquet source. An append-only writer produces the same
chain so round-trip tests need no external Iceberg library; positional/
equality deletes and schema evolution are out of scope (tagged loudly).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Iterator, List, Optional

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import (BooleanType, DataType, DateType, DoubleType, FloatType,
                     IntegerType, LongType, Schema, StringType, StructField,
                     TimestampType)
from .avro import read_avro_rows, write_avro_rows

_TYPE_TO_ICE = {LongType: "long", IntegerType: "int", DoubleType: "double",
                FloatType: "float", BooleanType: "boolean",
                StringType: "string", DateType: "date",
                TimestampType: "timestamp"}
_ICE_TO_TYPE = {v: k() for k, v in _TYPE_TO_ICE.items()}


def _schema_from_iceberg(fields: List[dict]) -> Schema:
    out = []
    for f in fields:
        t = f["type"]
        if not isinstance(t, str) or t not in _ICE_TO_TYPE:
            raise ValueError(
                f"unsupported iceberg type {t!r} for {f['name']!r} "
                "(nested/decimal types pending)")
        out.append(StructField(f["name"], _ICE_TO_TYPE[t],
                               not f.get("required", False)))
    return Schema(tuple(out))


# avro schemas for the metadata chain (the required v1 subset)
_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"]},
    ]}

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

_STATUS_ADDED = 1
_STATUS_DELETED = 2


class IcebergTable:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self.meta_dir = os.path.join(self.path, "metadata")

    # -- metadata chain ----------------------------------------------------
    def current_metadata_path(self) -> str:
        hint = os.path.join(self.meta_dir, "version-hint.text")
        if os.path.exists(hint):
            with open(hint) as f:
                v = int(f.read().strip())
            return os.path.join(self.meta_dir, f"v{v}.metadata.json")
        versions = sorted(
            int(n[1:].split(".")[0])
            for n in os.listdir(self.meta_dir)
            if n.startswith("v") and n.endswith(".metadata.json"))
        if not versions:
            raise FileNotFoundError(
                f"{self.path!r} has no iceberg metadata")
        return os.path.join(self.meta_dir,
                            f"v{versions[-1]}.metadata.json")

    def metadata(self) -> dict:
        with open(self.current_metadata_path()) as f:
            return json.load(f)

    def schema(self) -> Schema:
        md = self.metadata()
        if "schemas" in md:
            sid = md.get("current-schema-id", 0)
            fields = next(s for s in md["schemas"]
                          if s.get("schema-id", 0) == sid)["fields"]
        else:
            fields = md["schema"]["fields"]
        return _schema_from_iceberg(fields)

    def data_files(self, snapshot_id: Optional[int] = None) -> List[str]:
        md = self.metadata()
        snap_id = snapshot_id if snapshot_id is not None \
            else md.get("current-snapshot-id")
        if snap_id is None or snap_id == -1:
            return []
        snap = next(s for s in md.get("snapshots", [])
                    if s["snapshot-id"] == snap_id)
        _, manifests = read_avro_rows(self._local(snap["manifest-list"]))
        files: List[str] = []
        for m in manifests:
            _, entries = read_avro_rows(self._local(m["manifest_path"]))
            for e in entries:
                if e["status"] == _STATUS_DELETED:
                    continue
                df = e["data_file"]
                if df["file_format"].upper() != "PARQUET":
                    raise ValueError(
                        f"unsupported data file format "
                        f"{df['file_format']!r}")
                files.append(self._local(df["file_path"]))
        return files

    def _local(self, uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri


class IcebergSource:
    """Scan source over the current snapshot (plugs into LogicalScan)."""

    def __init__(self, path: str, conf: Optional[RapidsConf] = None,
                 snapshot_id: Optional[int] = None):
        self.table = IcebergTable(path)
        self.schema = self.table.schema()
        self._conf = conf
        self._files = self.table.data_files(snapshot_id)
        self.filters: List = []

    def with_filters(self, filters) -> "IcebergSource":
        out = IcebergSource.__new__(IcebergSource)
        out.__dict__.update(self.__dict__)
        out.filters = list(self.filters) + list(filters)
        return out

    def estimated_size_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self._files)

    def batches(self) -> Iterator[ColumnarBatch]:
        if not self._files:
            return
        from .parquet import ParquetSource
        src = ParquetSource(self._files, self._conf,
                            columns=list(self.schema.names),
                            filters=self.filters)
        yield from src.batches()


def write_iceberg(df, path: str, mode: str = "append") -> None:
    """DataFrame → iceberg v1 table (append/overwrite): parquet data file
    + manifest + manifest list + next metadata.json + version hint."""
    import pyarrow.parquet as pq
    path = os.path.abspath(path)
    meta_dir = os.path.join(path, "metadata")
    data_dir = os.path.join(path, "data")
    os.makedirs(meta_dir, exist_ok=True)
    os.makedirs(data_dir, exist_ok=True)

    tbl = IcebergTable(path)
    try:
        md = tbl.metadata()
        version = int(os.path.basename(tbl.current_metadata_path())
                      [1:].split(".")[0])
    except FileNotFoundError:
        md = None
        version = 0

    fields = []
    for i, f in enumerate(df.schema.fields):
        t = _TYPE_TO_ICE.get(type(f.data_type))
        if t is None:
            raise ValueError(
                f"iceberg write: unsupported type "
                f"{f.data_type.simple_name()}")
        fields.append({"id": i + 1, "name": f.name, "required": False,
                       "type": t})

    table = df.to_arrow()
    data_path = os.path.join(data_dir,
                             f"{uuid.uuid4().hex}.parquet")
    pq.write_table(table, data_path)

    snap_id = int(time.time() * 1000) + version
    manifest_path = os.path.join(meta_dir,
                                 f"{uuid.uuid4().hex}-m0.avro")
    write_avro_rows(manifest_path, _MANIFEST_ENTRY_SCHEMA, [{
        "status": _STATUS_ADDED, "snapshot_id": snap_id,
        "data_file": {
            "file_path": data_path, "file_format": "PARQUET",
            "record_count": table.num_rows,
            "file_size_in_bytes": os.path.getsize(data_path)}}])

    # carry forward prior manifests on append
    prior_manifests: List[dict] = []
    if md is not None and mode == "append":
        cur = md.get("current-snapshot-id")
        if cur is not None and cur != -1:
            snap = next(s for s in md["snapshots"]
                        if s["snapshot-id"] == cur)
            _, prior_manifests = read_avro_rows(
                tbl._local(snap["manifest-list"]))
    list_path = os.path.join(
        meta_dir, f"snap-{snap_id}-1-{uuid.uuid4().hex}.avro")
    write_avro_rows(list_path, _MANIFEST_LIST_SCHEMA, prior_manifests + [{
        "manifest_path": manifest_path,
        "manifest_length": os.path.getsize(manifest_path),
        "partition_spec_id": 0, "added_snapshot_id": snap_id}])

    snapshots = (md.get("snapshots", []) if md is not None
                 and mode == "append" else [])
    new_md = {
        "format-version": 1,
        "table-uuid": (md or {}).get("table-uuid", str(uuid.uuid4())),
        "location": path,
        "last-updated-ms": int(time.time() * 1000),
        "last-column-id": len(fields),
        "schema": {"type": "struct", "fields": fields},
        "partition-spec": [],
        "current-snapshot-id": snap_id,
        "snapshots": snapshots + [{
            "snapshot-id": snap_id,
            "timestamp-ms": int(time.time() * 1000),
            "manifest-list": list_path,
            "summary": {"operation": "append"}}],
    }
    version += 1
    with open(os.path.join(meta_dir, f"v{version}.metadata.json"),
              "w") as f:
        json.dump(new_md, f, indent=2)
    with open(os.path.join(meta_dir, "version-hint.text"), "w") as f:
        f.write(str(version))
