"""IO layer (reference §2.6): multi-file readers and writers.

Architecture note vs the reference: cuDF decodes parquet/ORC bytes ON the
GPU (Table.readParquet, GpuParquetScan.scala:2619). TPUs expose no byte-
level device decode path, so file formats decode on the HOST (pyarrow's
vectorized C++ readers) into pinned buffers and upload as device columns —
while keeping the reference's performance-critical structure: the
MULTITHREADED cloud-reader pattern (parallel fetch+decode ahead of the
device pipeline, GpuMultiFileReader.scala:345) and row-group-granular
slicing so batches hit the target size."""

from .parquet import ParquetSource, write_parquet  # noqa: F401
from .csv import CsvSource  # noqa: F401
from .json import JsonSource  # noqa: F401
