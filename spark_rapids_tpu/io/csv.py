"""CSV scan + write (reference GpuCSVScan.scala /
GpuTextBasedPartitionReader.scala: host line framing + device parse; here
pyarrow's C++ CSV reader does the framing+parse on the prefetch pool,
producing device columns).

Spark option coverage: header, sep/delimiter, quote, escape, comment
(raw-line prefilter, exact Spark semantics), nullValue, mode
(PERMISSIVE/DROPMALFORMED = skip unparseable rows, FAILFAST = raise;
there is no columnNameOfCorruptRecord sink yet, so PERMISSIVE behaves as
DROPMALFORMED with a skipped-row counter)."""

from __future__ import annotations

from typing import Iterator, List, Optional

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow, to_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks
from .parquet import DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS


class CsvSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 schema: Optional[Schema] = None, header: bool = True,
                 delimiter: str = ",", quote: str = '"',
                 escape: Optional[str] = None, comment: Optional[str] = None,
                 null_value: str = "",
                 mode: str = "PERMISSIVE",
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        self.paths = expand_paths(path)
        assert self.paths, f"no csv files at {path!r}"
        self.header = header
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape
        self.comment = comment
        self.null_value = null_value
        self.mode = mode.upper()
        assert self.mode in ("PERMISSIVE", "DROPMALFORMED", "FAILFAST"), mode
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self._user_schema = schema
        #: rows skipped by PERMISSIVE/DROPMALFORMED in the last drive
        #: (incremented from prefetch threads — guarded by a lock)
        self.malformed_rows = 0
        import threading
        self._count_lock = threading.Lock()
        if schema is not None:
            self.schema = schema
        else:
            table = self._read_one(self.paths[0])
            self.schema = Schema(tuple(
                StructField(f.name, from_arrow(f.type), f.nullable)
                for f in table.schema))

    def _read_one(self, path):
        import pyarrow.csv as pacsv
        read_opts = pacsv.ReadOptions(
            autogenerate_column_names=not self.header,
            column_names=None if self.header else
            (list(self._user_schema.names) if self._user_schema else None))

        def on_invalid(row):
            with self._count_lock:
                self.malformed_rows += 1
            return "skip"

        parse_opts = pacsv.ParseOptions(
            delimiter=self.delimiter,
            quote_char=self.quote if self.quote else False,
            escape_char=self.escape if self.escape else False,
            invalid_row_handler=(on_invalid
                                 if self.mode != "FAILFAST" else None))
        # Spark's default nullValue is the empty string ONLY — nulling the
        # literal words "null"/"NULL" would corrupt real string data
        null_values = [self.null_value]
        kw = dict(
            strings_can_be_null=True,  # Spark: empty field -> null
            null_values=null_values,
            true_values=["true", "True", "TRUE"],
            false_values=["false", "False", "FALSE"],
        )
        if self._user_schema is not None:
            kw["column_types"] = {f.name: to_arrow(f.data_type)
                                  for f in self._user_schema.fields}
        convert = pacsv.ConvertOptions(**kw)
        src = path
        if self.comment:
            # pyarrow has no comment-char support; Spark treats only RAW
            # lines starting with the char as comments (a quoted first
            # field like "#tag" is data) — prefilter the raw bytes
            import io
            comment_b = self.comment.encode()
            # only lines whose FIRST character is the comment char are
            # comments (Spark/univocity); no lstrip
            with open(path, "rb") as f:
                kept = [ln for ln in f if not ln.startswith(comment_b)]
            src = io.BytesIO(b"".join(kept))
        return pacsv.read_csv(src, read_options=read_opts,
                              parse_options=parse_opts,
                              convert_options=convert)

    def estimated_size_bytes(self) -> int:
        import os
        return sum(os.path.getsize(p) for p in self.paths)

    def batches(self) -> Iterator[ColumnarBatch]:
        self.malformed_rows = 0
        tasks = [lambda p=p: self._read_one(p) for p in self.paths]
        for table in threaded_chunks(tasks, self.num_threads):
            if self._user_schema is not None:
                table = table.select(list(self._user_schema.names))
            yield from arrow_to_batches(table, self.batch_rows)


def write_csv(df, path, header: bool = True, delimiter: str = ","):
    """DataFrame -> CSV file (reference GpuCSVFileFormat writer path)."""
    import os

    import pyarrow.csv as pacsv

    table = df.to_arrow()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    pacsv.write_csv(table, path, write_options=pacsv.WriteOptions(
        include_header=header, delimiter=delimiter))
