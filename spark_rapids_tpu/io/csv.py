"""CSV scan (reference GpuCSVScan.scala / GpuTextBasedPartitionReader.scala:
host line framing + device parse; here pyarrow's C++ CSV reader does the
framing+parse on the prefetch pool, producing device columns)."""

from __future__ import annotations

from typing import Iterator, List, Optional

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow, to_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks
from .parquet import DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS


class CsvSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 schema: Optional[Schema] = None, header: bool = True,
                 delimiter: str = ",",
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        self.paths = expand_paths(path)
        assert self.paths, f"no csv files at {path!r}"
        self.header = header
        self.delimiter = delimiter
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self._user_schema = schema
        if schema is not None:
            self.schema = schema
        else:
            table = self._read_one(self.paths[0])
            self.schema = Schema(tuple(
                StructField(f.name, from_arrow(f.type), f.nullable)
                for f in table.schema))

    def _read_one(self, path):
        import pyarrow.csv as pacsv
        read_opts = pacsv.ReadOptions(
            autogenerate_column_names=not self.header,
            column_names=None if self.header else
            (list(self._user_schema.names) if self._user_schema else None))
        parse_opts = pacsv.ParseOptions(delimiter=self.delimiter)
        # Spark CSV semantics: empty field -> null (also for strings)
        convert = pacsv.ConvertOptions(strings_can_be_null=True)
        if self._user_schema is not None:
            convert = pacsv.ConvertOptions(
                strings_can_be_null=True,
                column_types={f.name: to_arrow(f.data_type)
                              for f in self._user_schema.fields})
        return pacsv.read_csv(path, read_options=read_opts,
                              parse_options=parse_opts,
                              convert_options=convert)

    def batches(self) -> Iterator[ColumnarBatch]:
        tasks = [lambda p=p: self._read_one(p) for p in self.paths]
        for table in threaded_chunks(tasks, self.num_threads):
            if self._user_schema is not None:
                table = table.select(list(self._user_schema.names))
            yield from arrow_to_batches(table, self.batch_rows)
