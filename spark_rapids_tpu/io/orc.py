"""ORC scan + write (reference GpuOrcScan.scala / GpuOrcFileFormat:
footer-driven stripe slicing + device decode; here pyarrow's C++ ORC
reader decodes stripes on the prefetch pool, uploaded as device columns).

Stripe-per-task granularity mirrors the parquet row-group reader; column
pruning via `columns`."""

from __future__ import annotations

import os
from typing import Iterator, Optional, Sequence

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks
from .parquet import DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS


class OrcSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 columns: Optional[Sequence[str]] = None,
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        import pyarrow.orc as paorc
        self.paths = expand_paths(path)
        assert self.paths, f"no orc files at {path!r}"
        self.columns = list(columns) if columns is not None else None
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        f = paorc.ORCFile(self.paths[0])
        arrow_schema = f.schema
        fields = []
        for name in (self.columns or arrow_schema.names):
            fld = arrow_schema.field(name)
            fields.append(StructField(fld.name, from_arrow(fld.type),
                                      fld.nullable))
        self.schema = Schema(tuple(fields))

    def estimated_size_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self.paths)

    def batches(self) -> Iterator[ColumnarBatch]:
        import pyarrow.orc as paorc

        tasks = []
        for p in self.paths:
            f = paorc.ORCFile(p)
            n = f.nstripes
            for s in range(n):
                def decode(p=p, s=s):
                    return paorc.ORCFile(p).read_stripe(
                        s, columns=self.columns)
                tasks.append(decode)
            if n == 0:
                tasks.append(lambda p=p: paorc.ORCFile(p).read(
                    columns=self.columns))
        for item in threaded_chunks(tasks, self.num_threads):
            import pyarrow as pa
            table = pa.Table.from_batches([item]) \
                if isinstance(item, pa.RecordBatch) else item
            yield from arrow_to_batches(table, self.batch_rows)


def write_orc(df, path):
    """DataFrame -> ORC file (reference GpuOrcFileFormat writer)."""
    import pyarrow.orc as paorc

    table = df.to_arrow()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    paorc.write_table(table, path)
