"""ORC scan + write (reference GpuOrcScan.scala:1455-1546 /
GpuOrcFileFormat).

Round-5 parity rework: the scan prunes stripes with prove-absence
semantics from the file's own StripeStatistics (parsed by io/orc_meta —
pyarrow exposes stripe counts but not the statistics values), pushes
column and predicate selection, supports the COALESCING reader shape,
and reports pruning counters, mirroring io/parquet.py's surface so the
planner's pushdown hook (`with_filters`) treats both formats alike.
Decode itself rides pyarrow's C++ ORC reader on the prefetch pool,
uploaded as device columns; stripe-per-task granularity mirrors the
parquet row-group reader."""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks
from .orc_meta import OrcFileMeta
from .parquet import (
    DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS, _stats_can_skip,
)


def _to_stat_literal(value) -> object:
    """Convert a pushed literal to the domain ORC statistics use
    (dates are day numbers; everything else compares as-is)."""
    import datetime as dt
    if isinstance(value, dt.date) and not isinstance(value, dt.datetime):
        return (value - dt.date(1970, 1, 1)).days
    return value


class OrcSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 columns: Optional[Sequence[str]] = None,
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS,
                 filters: Optional[Sequence[Tuple[str, str, object]]] = None,
                 reader_type: Optional[str] = None):
        import pyarrow.orc as paorc
        self.paths = expand_paths(path)
        assert self.paths, f"no orc files at {path!r}"
        self.columns = list(columns) if columns is not None else None
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self.filters = list(filters or [])
        self._conf = conf
        self.reader_type = (reader_type or "MULTITHREADED").upper()
        f = paorc.ORCFile(self.paths[0])
        arrow_schema = f.schema
        fields = []
        for name in (self.columns or arrow_schema.names):
            fld = arrow_schema.field(name)
            fields.append(StructField(fld.name, from_arrow(fld.type),
                                      fld.nullable))
        self.schema = Schema(tuple(fields))
        #: observability (mirrors ParquetSource.scan_stats; the reference's
        #: ORC scan metrics are the stripe read/skip counters)
        self.scan_stats = {"stripes_read": 0, "stripes_pruned": 0}

    @property
    def stripes_read(self) -> int:
        return self.scan_stats["stripes_read"]

    @property
    def stripes_pruned(self) -> int:
        return self.scan_stats["stripes_pruned"]

    def with_filters(self, filters: Sequence[Tuple[str, str, object]]
                     ) -> "OrcSource":
        """Planner pushdown hook (same contract as ParquetSource): stats
        only prove absence, never presence — the Filter stays above."""
        out = OrcSource.__new__(OrcSource)
        out.__dict__.update(self.__dict__)
        out.filters = list(self.filters) + list(filters)
        return out

    def estimated_size_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self.paths)

    def _stripe_pruned(self, per_name) -> bool:
        for (name, op, value) in self.filters:
            stats = per_name.get(name)
            if stats is None:
                continue
            if _stats_can_skip(stats, op, _to_stat_literal(value)):
                return True
        return False

    def batches(self) -> Iterator[ColumnarBatch]:
        import pyarrow.orc as paorc

        tasks = []
        self.scan_stats["stripes_read"] = 0
        self.scan_stats["stripes_pruned"] = 0
        may_prune = bool(self.filters)
        for p in self.paths:
            f = paorc.ORCFile(p)
            n = f.nstripes
            meta = OrcFileMeta(p) if may_prune and n > 0 else None
            stats = meta.stripe_stats if meta is not None and meta.ok \
                else []
            for s in range(n):
                if s < len(stats) and self._stripe_pruned(stats[s]):
                    self.scan_stats["stripes_pruned"] += 1
                    continue
                self.scan_stats["stripes_read"] += 1

                def decode(p=p, s=s):
                    # fresh handle per task: ORCFile is not thread-safe
                    return paorc.ORCFile(p).read_stripe(
                        s, columns=self.columns)
                tasks.append(decode)
            if n == 0:
                tasks.append(lambda p=p: paorc.ORCFile(p).read(
                    columns=self.columns))
        import pyarrow as pa

        def tables():
            for item in threaded_chunks(tasks, self.num_threads):
                yield pa.Table.from_batches([item]) \
                    if isinstance(item, pa.RecordBatch) else item

        if self.reader_type == "COALESCING":
            yield from self._coalescing_drive(tables())
        else:
            for table in tables():
                yield from arrow_to_batches(table, self.batch_rows)

    def _coalescing_drive(self, tables) -> Iterator[ColumnarBatch]:
        """Stitch decoded stripes host-side into ~batch_rows tables before
        the device upload (reference COALESCING reader shape,
        GpuMultiFileReader.scala:830)."""
        import pyarrow as pa
        pending: List = []
        pending_rows = 0
        for table in tables:
            pending.append(table)
            pending_rows += table.num_rows
            if pending_rows >= self.batch_rows:
                yield from arrow_to_batches(pa.concat_tables(pending),
                                            self.batch_rows)
                pending, pending_rows = [], 0
        if pending:
            yield from arrow_to_batches(pa.concat_tables(pending),
                                        self.batch_rows)


def write_orc(df, path, compression: Optional[str] = None,
              stripe_size: Optional[int] = None):
    """DataFrame -> ORC file (reference GpuOrcFileFormat writer)."""
    import pyarrow.orc as paorc

    table = df.to_arrow()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    kw = {}
    if compression is not None:
        kw["compression"] = compression
    if stripe_size is not None:
        kw["stripe_size"] = stripe_size
    paorc.write_table(table, path, **kw)
