"""JSON-lines scan (reference GpuJsonReadCommon.scala / JSON scan in L3:
host line framing + device parse via JSONUtils JNI; here pyarrow's C++
JSON reader on the prefetch pool)."""

from __future__ import annotations

from typing import Iterator, Optional

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow, to_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks
from .parquet import DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS


class JsonSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 schema: Optional[Schema] = None,
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        self.paths = expand_paths(path)
        assert self.paths, f"no json files at {path!r}"
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self._user_schema = schema
        if schema is not None:
            self.schema = schema
        else:
            table = self._read_one(self.paths[0])
            self.schema = Schema(tuple(
                StructField(f.name, from_arrow(f.type), f.nullable)
                for f in table.schema))

    def _read_one(self, path):
        import pyarrow.json as pajson
        parse = None
        if self._user_schema is not None:
            import pyarrow as pa
            parse = pajson.ParseOptions(explicit_schema=pa.schema(
                [(f.name, to_arrow(f.data_type))
                 for f in self._user_schema.fields]))
        return pajson.read_json(path, parse_options=parse)

    def batches(self) -> Iterator[ColumnarBatch]:
        tasks = [lambda p=p: self._read_one(p) for p in self.paths]
        for table in threaded_chunks(tasks, self.num_threads):
            if self._user_schema is not None:
                table = table.select(list(self._user_schema.names))
            yield from arrow_to_batches(table, self.batch_rows)
