"""JSON-lines scan + write (reference GpuJsonReadCommon.scala / JSON scan
in L3: host line framing + device parse via JSONUtils JNI; here pyarrow's
C++ JSON reader on the prefetch pool).

mode: PERMISSIVE (default, Spark) drops lines pyarrow cannot parse by
re-framing the file line-by-line on the host and parsing only well-formed
records (counted in `malformed_rows`); FAILFAST surfaces the parse
error."""

from __future__ import annotations

from typing import Iterator, Optional

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import Schema, StructField, from_arrow, to_arrow
from .multifile import arrow_to_batches, expand_paths, threaded_chunks
from .parquet import DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS


class JsonSource:
    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 schema: Optional[Schema] = None,
                 mode: str = "PERMISSIVE",
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        self.paths = expand_paths(path)
        assert self.paths, f"no json files at {path!r}"
        self.mode = mode.upper()
        assert self.mode in ("PERMISSIVE", "DROPMALFORMED", "FAILFAST"), mode
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self._user_schema = schema
        #: lines dropped by PERMISSIVE mode in the last batches() drive
        #: (incremented from prefetch threads — guarded by a lock)
        self.malformed_rows = 0
        import threading
        self._count_lock = threading.Lock()
        if schema is not None:
            self.schema = schema
        else:
            table = self._read_one(self.paths[0])
            self.schema = Schema(tuple(
                StructField(f.name, from_arrow(f.type), f.nullable)
                for f in table.schema))

    def _parse_options(self):
        import pyarrow.json as pajson
        if self._user_schema is not None:
            import pyarrow as pa
            return pajson.ParseOptions(explicit_schema=pa.schema(
                [(f.name, to_arrow(f.data_type))
                 for f in self._user_schema.fields]))
        return None

    def _read_one(self, path):
        import pyarrow.json as pajson
        try:
            return pajson.read_json(path,
                                    parse_options=self._parse_options())
        except Exception:
            if self.mode == "FAILFAST":
                raise
            return self._read_permissive(path)

    def _read_permissive(self, path):
        """Line-framed recovery: parse each line independently, drop the
        malformed ones (Spark PERMISSIVE without a corrupt-record sink)."""
        import io
        import json as pyjson

        import pyarrow as pa
        import pyarrow.json as pajson

        good = []
        with open(path, "rb") as f:
            for line in f:
                s = line.strip()
                if not s:
                    continue
                try:
                    pyjson.loads(s)
                    good.append(s)
                except ValueError:
                    with self._count_lock:
                        self.malformed_rows += 1
        if not good:
            # every line malformed: zero rows (needs an explicit schema —
            # there is nothing left to infer from)
            if self._user_schema is None:
                raise ValueError(
                    f"{path}: no parseable JSON lines and no explicit "
                    "schema to shape an empty result")
            return pa.table({f.name: pa.array([], to_arrow(f.data_type))
                             for f in self._user_schema.fields})
        buf = io.BytesIO(b"\n".join(good))
        return pajson.read_json(buf, parse_options=self._parse_options())

    def estimated_size_bytes(self) -> int:
        import os
        return sum(os.path.getsize(p) for p in self.paths)

    def batches(self) -> Iterator[ColumnarBatch]:
        self.malformed_rows = 0
        tasks = [lambda p=p: self._read_one(p) for p in self.paths]
        for table in threaded_chunks(tasks, self.num_threads):
            if self._user_schema is not None:
                table = table.select(list(self._user_schema.names))
            yield from arrow_to_batches(table, self.batch_rows)


def write_json(df, path):
    """DataFrame -> JSON-lines file (Spark df.write.json)."""
    import json as pyjson
    import os

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    d = df.to_pydict()
    names = list(d.keys())
    n = len(d[names[0]]) if names else 0
    with open(path, "w") as f:
        for i in range(n):
            row = {k: d[k][i] for k in names if d[k][i] is not None}
            f.write(pyjson.dumps(row) + "\n")
