"""Avro container-file scan + write (reference GpuAvroScan.scala with its
own in-repo AvroDataFileReader.scala block reader — the reference also
decodes Avro without an external library, and so does this module: the
object-container framing and binary encoding are implemented from the
Avro 1.11 spec).

Supported: null/deflate codecs, records of primitive fields, nullable
unions ([null, T] / [T, null]), enums (as strings), fixed (as binary),
arrays of primitives, and the common logical types (date,
timestamp-micros/millis). Block-per-task decode on the prefetch pool,
like the parquet/ORC readers.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import (BINARY, BOOLEAN, DATE, DOUBLE, FLOAT, INT, LONG,
                     STRING, TIMESTAMP, DataType, Schema, StructField)
from .multifile import expand_paths, threaded_chunks
from .parquet import DEFAULT_BATCH_ROWS, DEFAULT_NUM_THREADS

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# binary decoding primitives (Avro spec: zigzag varints, little-endian fp)
# ---------------------------------------------------------------------------

class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def long(self) -> int:
        buf, p = self.buf, self.pos
        shift = 0
        acc = 0
        while True:
            b = buf[p]
            p += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        self.pos = p
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def bytes_(self) -> bytes:
        n = self.long()
        out = self.buf[self.pos: self.pos + n]
        self.pos += n
        return out

    def fixed(self, n: int) -> bytes:
        out = self.buf[self.pos: self.pos + n]
        self.pos += n
        return out

    def float_(self) -> float:
        (v,) = struct.unpack_from("<f", self.buf, self.pos)
        self.pos += 4
        return v

    def double(self) -> float:
        (v,) = struct.unpack_from("<d", self.buf, self.pos)
        self.pos += 8
        return v

    def boolean(self) -> bool:
        v = self.buf[self.pos] != 0
        self.pos += 1
        return v


def _read_meta_map(r: _Reader) -> Dict[str, bytes]:
    out: Dict[str, bytes] = {}
    while True:
        count = r.long()
        if count == 0:
            return out
        if count < 0:
            r.long()  # block byte size, unused
            count = -count
        for _ in range(count):
            k = r.bytes_().decode("utf-8")
            out[k] = r.bytes_()


# ---------------------------------------------------------------------------
# schema mapping
# ---------------------------------------------------------------------------

class _FieldDec:
    """One record field: engine type + (decoder, nullable, null_index)."""

    def __init__(self, name: str, dtype: DataType, kind: str,
                 nullable: bool, null_first: bool, size: int = 0,
                 scale_to_micros: int = 1):
        self.name = name
        self.dtype = dtype
        self.kind = kind            # long/int/float/double/boolean/string/
        #                             bytes/fixed/enum/array:<k>
        self.nullable = nullable
        self.null_first = null_first
        self.size = size            # for fixed
        self.symbols: List[str] = []  # for enum
        self.scale_to_micros = scale_to_micros
        self.elem: Optional["_FieldDec"] = None


def _map_avro_type(name: str, t) -> _FieldDec:
    nullable = False
    null_first = True
    if isinstance(t, list):  # union
        branches = [b for b in t if b != "null"]
        if len(branches) != 1 or len(t) > 2:
            raise ValueError(f"unsupported avro union for {name!r}: {t}")
        nullable = True
        null_first = t[0] == "null"
        t = branches[0]
    logical = t.get("logicalType") if isinstance(t, dict) else None
    base = t.get("type") if isinstance(t, dict) else t
    fd = None
    if logical == "date" and base == "int":
        fd = _FieldDec(name, DATE, "int", nullable, null_first)
    elif logical in ("timestamp-micros", "timestamp-millis") \
            and base == "long":
        fd = _FieldDec(name, TIMESTAMP, "long", nullable, null_first,
                       scale_to_micros=1 if logical.endswith("micros")
                       else 1000)
    elif base == "long":
        fd = _FieldDec(name, LONG, "long", nullable, null_first)
    elif base == "int":
        fd = _FieldDec(name, INT, "int", nullable, null_first)
    elif base == "float":
        fd = _FieldDec(name, FLOAT, "float", nullable, null_first)
    elif base == "double":
        fd = _FieldDec(name, DOUBLE, "double", nullable, null_first)
    elif base == "boolean":
        fd = _FieldDec(name, BOOLEAN, "boolean", nullable, null_first)
    elif base == "string":
        fd = _FieldDec(name, STRING, "string", nullable, null_first)
    elif base == "bytes":
        fd = _FieldDec(name, BINARY, "bytes", nullable, null_first)
    elif base == "fixed":
        fd = _FieldDec(name, BINARY, "fixed", nullable, null_first,
                       size=int(t["size"]))
    elif base == "enum":
        fd = _FieldDec(name, STRING, "enum", nullable, null_first)
        fd.symbols = list(t["symbols"])
    elif base == "array":
        elem = _map_avro_type(name + ".elem", t["items"])
        if elem.nullable or elem.kind.startswith("array"):
            raise ValueError(
                f"unsupported nested avro array for {name!r}")
        from ..types import ArrayType
        fd = _FieldDec(name, ArrayType(elem.dtype), "array", nullable,
                       null_first)
        fd.elem = elem
    if fd is None:
        raise ValueError(f"unsupported avro type for {name!r}: {t}")
    return fd


def _decode_scalar(r: _Reader, fd: _FieldDec):
    k = fd.kind
    if k in ("long", "int"):
        v = r.long()
        return v * fd.scale_to_micros if fd.scale_to_micros != 1 else v
    if k == "double":
        return r.double()
    if k == "float":
        return r.float_()
    if k == "boolean":
        return r.boolean()
    if k == "string":
        return r.bytes_().decode("utf-8")
    if k == "bytes":
        return r.bytes_()
    if k == "fixed":
        return r.fixed(fd.size)
    if k == "enum":
        return fd.symbols[r.long()]
    if k == "array":
        out = []
        while True:
            count = r.long()
            if count == 0:
                return out
            if count < 0:
                r.long()
                count = -count
            for _ in range(count):
                out.append(_decode_scalar(r, fd.elem))
    raise AssertionError(k)


def _decode_field(r: _Reader, fd: _FieldDec):
    if fd.nullable:
        idx = r.long()
        is_null = (idx == 0) if fd.null_first else (idx == 1)
        if is_null:
            return None
    return _decode_scalar(r, fd)


# ---------------------------------------------------------------------------
# source
# ---------------------------------------------------------------------------

class AvroSource:
    """Avro object-container scan (reference GpuAvroScan.scala +
    AvroDataFileReader.scala block reader)."""

    def __init__(self, path, conf: Optional[RapidsConf] = None,
                 columns: Optional[Sequence[str]] = None,
                 num_threads: int = DEFAULT_NUM_THREADS,
                 batch_rows: int = DEFAULT_BATCH_ROWS):
        self.paths = expand_paths(path)
        assert self.paths, f"no avro files at {path!r}"
        self.num_threads = num_threads
        self.batch_rows = batch_rows
        self._codec, schema_json = self._read_header(self.paths[0])
        rec = json.loads(schema_json)
        self._schema_json = rec
        if rec.get("type") != "record":
            raise ValueError("top-level avro schema must be a record")
        self._fields = [_map_avro_type(f["name"], f["type"])
                        for f in rec["fields"]]
        if columns is not None:
            by_name = {fd.name: i for i, fd in enumerate(self._fields)}
            self._projected = [by_name[n] for n in columns]
        else:
            self._projected = list(range(len(self._fields)))
        self.schema = Schema(tuple(
            StructField(self._fields[i].name, self._fields[i].dtype,
                        self._fields[i].nullable)
            for i in self._projected))

    @staticmethod
    def _read_header(path: str):
        """Parse only the header (metadata map + sync), reading the file
        in bounded chunks — construction must not pull a multi-GB data
        file into memory."""
        data = b""
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 16)
                data += chunk
                if data[:4] != _MAGIC[: min(4, len(data))]:
                    raise ValueError(
                        f"{path!r} is not an avro container file")
                try:
                    r = _Reader(data, 4)
                    meta = _read_meta_map(r)
                    r.fixed(16)  # sync marker must be present too
                    if r.pos > len(data):
                        raise IndexError  # short slice: need more bytes
                    break
                except IndexError:
                    if not chunk:
                        raise ValueError(
                            f"truncated avro header in {path!r}")
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {codec!r}")
        return codec, meta["avro.schema"]

    def estimated_size_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self.paths)

    def _file_blocks(self, path: str
                     ) -> Iterator[Tuple[int, bytes, str]]:
        """(row_count, raw block bytes, codec) per data block. Codec and
        schema are PER-FILE properties: each file's own header is parsed;
        a schema that diverges from the scan schema is rejected rather
        than misdecoded."""
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != _MAGIC:
            raise ValueError(f"{path!r} is not an avro container file")
        r = _Reader(data, 4)
        meta = _read_meta_map(r)
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {codec!r} in {path!r}")
        if json.loads(meta["avro.schema"]) != self._schema_json:
            raise ValueError(
                f"avro schema mismatch: {path!r} differs from "
                f"{self.paths[0]!r}")
        sync = r.fixed(16)
        while r.pos < len(data):
            rows = r.long()
            nbytes = r.long()
            block = r.fixed(nbytes)
            marker = r.fixed(16)
            assert marker == sync, f"bad sync marker in {path!r}"
            yield rows, block, codec

    def _decode_block(self, rows: int, block: bytes, codec: str
                      ) -> List[List]:
        if codec == "deflate":
            block = zlib.decompress(block, wbits=-15)
        r = _Reader(block)
        cols: List[List] = [[] for _ in self._projected]
        slot_of = {fi: s for s, fi in enumerate(self._projected)}
        for _ in range(rows):
            for i, fd in enumerate(self._fields):
                v = _decode_field(r, fd)
                s = slot_of.get(i)
                if s is not None:
                    cols[s].append(v)
        return cols

    def _decode_file(self, path: str) -> Tuple[int, List[List]]:
        """One file read+decoded inside the task (lazy like the parquet
        reader: only `paths` live in task closures, so peak host memory
        is one file per pool thread, not the whole dataset)."""
        total = 0
        cols: List[List] = [[] for _ in self._projected]
        for rows, block, codec in self._file_blocks(path):
            part = self._decode_block(rows, block, codec)
            for dst, src in zip(cols, part):
                dst.extend(src)
            total += rows
        return total, cols

    def batches(self) -> Iterator[ColumnarBatch]:
        tasks = [lambda p=p: self._decode_file(p) for p in self.paths]
        pending: List[List] = [[] for _ in self._projected]
        pending_rows = 0
        for rows, cols in threaded_chunks(tasks, self.num_threads):
            for dst, src in zip(pending, cols):
                dst.extend(src)
            pending_rows += rows
            if pending_rows >= self.batch_rows:
                yield self._flush(pending)
                pending = [[] for _ in self._projected]
                pending_rows = 0
        if pending_rows or not tasks:
            yield self._flush(pending)

    def _flush(self, cols: List[List]) -> ColumnarBatch:
        data = {f.name: c for f, c in zip(self.schema.fields, cols)}
        return ColumnarBatch.from_pydict(data, self.schema)


# ---------------------------------------------------------------------------
# writer (test/tooling surface; the reference is read-only for Avro too)
# ---------------------------------------------------------------------------

_WRITE_KINDS = {"bigint": ("long", "long"), "int": ("int", "int"),
                "smallint": ("int", "int"), "tinyint": ("int", "int"),
                "double": ("double", "double"), "float": ("float", "float"),
                "boolean": ("boolean", "boolean"),
                "string": ("string", "string"),
                "date": ({"type": "int", "logicalType": "date"}, "int"),
                "timestamp": ({"type": "long",
                               "logicalType": "timestamp-micros"}, "long")}


def _zigzag(v: int) -> bytes:
    acc = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
    out = bytearray()
    while True:
        b = acc & 0x7F
        acc >>= 7
        if acc:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def write_avro(df, path, codec: str = "deflate"):
    """DataFrame -> one avro container file."""
    schema = df.schema
    fields_json = []
    kinds = []
    for f in schema.fields:
        base, kind = _WRITE_KINDS.get(f.data_type.simple_name(),
                                      (None, None))
        if base is None:
            raise ValueError(
                f"avro write: unsupported type {f.data_type.simple_name()}")
        fields_json.append({"name": f.name, "type": ["null", base]})
        kinds.append(kind)
    schema_json = json.dumps({"type": "record", "name": "row",
                              "fields": fields_json})
    sync = os.urandom(16)
    rows = df.collect()
    body = bytearray()
    for row in rows:
        for v, kind in zip(row, kinds):
            if v is None:
                body += _zigzag(0)
                continue
            body += _zigzag(1)
            if kind in ("long", "int"):
                body += _zigzag(int(v))
            elif kind == "double":
                body += struct.pack("<d", float(v))
            elif kind == "float":
                body += struct.pack("<f", float(v))
            elif kind == "boolean":
                body.append(1 if v else 0)
            else:
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                body += _zigzag(len(b)) + b
    payload = bytes(body)
    if codec == "deflate":
        payload = zlib.compress(payload)[2:-4]  # raw DEFLATE
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        meta = {"avro.schema": schema_json.encode(),
                "avro.codec": codec.encode()}
        f.write(_zigzag(len(meta)))
        for k, v in meta.items():
            kb = k.encode()
            f.write(_zigzag(len(kb)) + kb + _zigzag(len(v)) + v)
        f.write(_zigzag(0))
        f.write(sync)
        if rows:
            f.write(_zigzag(len(rows)) + _zigzag(len(payload)))
            f.write(payload)
            f.write(sync)


# ---------------------------------------------------------------------------
# generic row codec (nested records/maps/arrays) — the metadata-file
# surface: Iceberg manifest lists/manifests are avro files of nested
# records (io/iceberg.py), decoded row-wise on the host like the
# reference's AvroDataFileReader-based metadata paths.
# ---------------------------------------------------------------------------

def _decode_generic(r: _Reader, t):
    if isinstance(t, list):  # union
        idx = r.long()
        branch = t[idx]
        return None if branch == "null" else _decode_generic(r, branch)
    base = t.get("type") if isinstance(t, dict) else t
    if base == "record":
        return {f["name"]: _decode_generic(r, f["type"])
                for f in t["fields"]}
    if base == "array":
        out = []
        while True:
            c = r.long()
            if c == 0:
                return out
            if c < 0:
                r.long()
                c = -c
            for _ in range(c):
                out.append(_decode_generic(r, t["items"]))
    if base == "map":
        out = {}
        while True:
            c = r.long()
            if c == 0:
                return out
            if c < 0:
                r.long()
                c = -c
            for _ in range(c):
                k = r.bytes_().decode("utf-8")
                out[k] = _decode_generic(r, t["values"])
    if base in ("long", "int"):
        return r.long()
    if base == "double":
        return r.double()
    if base == "float":
        return r.float_()
    if base == "boolean":
        return r.boolean()
    if base == "string":
        return r.bytes_().decode("utf-8")
    if base == "bytes":
        return r.bytes_()
    if base == "fixed":
        return r.fixed(int(t["size"]))
    if base == "enum":
        return t["symbols"][r.long()]
    raise ValueError(f"unsupported avro type {t!r}")


def _encode_generic(out: bytearray, t, v):
    if isinstance(t, list):  # union: first matching branch
        if v is None and "null" in t:
            out += _zigzag(t.index("null"))
            return
        for i, b in enumerate(t):
            if b != "null":
                out += _zigzag(i)
                _encode_generic(out, b, v)
                return
        raise ValueError(f"no union branch for {v!r} in {t!r}")
    base = t.get("type") if isinstance(t, dict) else t
    if base == "record":
        for f in t["fields"]:
            _encode_generic(out, f["type"], v[f["name"]])
    elif base == "array":
        if v:
            out += _zigzag(len(v))
            for item in v:
                _encode_generic(out, t["items"], item)
        out += _zigzag(0)
    elif base == "map":
        if v:
            out += _zigzag(len(v))
            for k, item in v.items():
                kb = k.encode("utf-8")
                out += _zigzag(len(kb)) + kb
                _encode_generic(out, t["values"], item)
        out += _zigzag(0)
    elif base in ("long", "int"):
        out += _zigzag(int(v))
    elif base == "double":
        out += struct.pack("<d", float(v))
    elif base == "float":
        out += struct.pack("<f", float(v))
    elif base == "boolean":
        out.append(1 if v else 0)
    elif base == "string":
        b = v.encode("utf-8")
        out += _zigzag(len(b)) + b
    elif base == "bytes":
        out += _zigzag(len(v)) + bytes(v)
    else:
        raise ValueError(f"unsupported avro type {t!r}")


def read_avro_rows(path: str):
    """(schema_json_dict, rows as dicts) — full recursive decode."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != _MAGIC:
        raise ValueError(f"{path!r} is not an avro container file")
    r = _Reader(data, 4)
    meta = _read_meta_map(r)
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    schema = json.loads(meta["avro.schema"])
    sync = r.fixed(16)
    rows = []
    while r.pos < len(data):
        n = r.long()
        nbytes = r.long()
        block = r.fixed(nbytes)
        assert r.fixed(16) == sync, f"bad sync marker in {path!r}"
        if codec == "deflate":
            block = zlib.decompress(block, wbits=-15)
        br = _Reader(block)
        for _ in range(n):
            rows.append(_decode_generic(br, schema))
    return schema, rows


def write_avro_rows(path: str, schema: dict, rows) -> None:
    """Rows (dicts) → one avro container file under `schema`."""
    body = bytearray()
    for row in rows:
        _encode_generic(body, schema, row)
    payload = zlib.compress(bytes(body))[2:-4]
    sync = os.urandom(16)
    schema_b = json.dumps(schema).encode()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(_zigzag(2))
        for k, v in (("avro.schema", schema_b),
                     ("avro.codec", b"deflate")):
            kb = k.encode()
            f.write(_zigzag(len(kb)) + kb + _zigzag(len(v)) + v)
        f.write(_zigzag(0))
        f.write(sync)
        if rows:
            f.write(_zigzag(len(rows)) + _zigzag(len(payload)))
            f.write(payload)
            f.write(sync)
