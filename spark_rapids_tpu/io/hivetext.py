"""Hive delimited-text tables (reference GpuHiveTableScanExec /
GpuHiveTextFileFormat under org/apache/spark/sql/hive/rapids/; SURVEY
§2.7 #48): LazySimpleSerDe defaults — field delimiter \\x01 (^A), row
delimiter \\n, NULL sentinel '\\N', no quoting — with the same textual
value formats Hive uses (lowercase true/false, plain decimal floats).
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf
from ..types import (BooleanType, DataType, DoubleType, FloatType,
                     IntegerType, LongType, Schema, StringType)
from .multifile import expand_paths

NULL = r"\N"
FIELD_DELIM = "\x01"


def _parse(raw: str, dt: DataType):
    if raw == NULL:
        return None
    if isinstance(dt, (LongType, IntegerType)):
        try:
            return int(raw)
        except ValueError:
            return None  # Hive: malformed numeric reads as NULL
    if isinstance(dt, (DoubleType, FloatType)):
        try:
            return float(raw)
        except ValueError:
            return None
    if isinstance(dt, BooleanType):
        return raw.lower() == "true" if raw.lower() in ("true", "false") \
            else None
    return raw


def _fmt(v, dt: DataType) -> str:
    if v is None:
        return NULL
    if isinstance(dt, BooleanType):
        return "true" if v else "false"
    if isinstance(dt, (DoubleType, FloatType)):
        return repr(float(v))
    return str(v)


class HiveTextSource:
    def __init__(self, path, schema: Schema,
                 conf: Optional[RapidsConf] = None,
                 field_delim: str = FIELD_DELIM,
                 batch_rows: int = 1 << 17):
        self.paths = expand_paths(path)
        assert self.paths, f"no files at {path!r}"
        self.schema = schema
        self.field_delim = field_delim
        self.batch_rows = batch_rows

    def estimated_size_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self.paths)

    def batches(self) -> Iterator[ColumnarBatch]:
        fields = self.schema.fields
        cols: List[List] = [[] for _ in fields]
        n = 0
        for p in self.paths:
            with open(p, "r", encoding="utf-8") as f:
                for line in f:
                    parts = line.rstrip("\n").split(self.field_delim)
                    for i, fld in enumerate(fields):
                        raw = parts[i] if i < len(parts) else NULL
                        cols[i].append(_parse(raw, fld.data_type))
                    n += 1
                    if n >= self.batch_rows:
                        yield self._flush(cols)
                        cols = [[] for _ in fields]
                        n = 0
        yield self._flush(cols)

    def _flush(self, cols: List[List]) -> ColumnarBatch:
        data = {f.name: c for f, c in zip(self.schema.fields, cols)}
        return ColumnarBatch.from_pydict(data, self.schema)


def write_hive_text(df, path: str, field_delim: str = FIELD_DELIM) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    fields = df.schema.fields
    with open(path, "w", encoding="utf-8") as f:
        for row in df.collect():
            f.write(field_delim.join(
                _fmt(v, fld.data_type) for v, fld in zip(row, fields)))
            f.write("\n")
