"""Bounded IO retry with exponential backoff (ISSUE 4 satellite) — the
engine-side analog of the bench backend-probe retry shipped in PR 1/3:
transient OSErrors in the multi-file readers and the shuffle block fetch
get `spark.rapids.tpu.io.retries` more chances before the failure
surfaces, each retry emitting a structured `io_retry` event.

Only *transient-looking* OSErrors retry: a missing file, a directory in
a file's place or a permission wall will fail identically on every
attempt — retrying those just delays the real error."""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, TypeVar

from ..config import IO_RETRIES, IO_RETRY_BACKOFF_MS, RapidsConf, active_conf
from .. import faults

T = TypeVar("T")

#: OSError subclasses no retry can fix
_NON_TRANSIENT = (FileNotFoundError, IsADirectoryError, NotADirectoryError,
                  PermissionError)

_BACKOFF_CAP_MS = 2000

#: successful-after-retry recoveries (bench chaos record); locked —
#: shuffle/multifile retries run concurrently on pool threads
_recoveries = 0
_recoveries_lock = threading.Lock()


def io_retry_recoveries() -> int:
    return _recoveries


def _backoff_s(what: str, salt: str, attempt: int, base_ms: int) -> float:
    return faults.backoff_s(attempt, base_ms, _BACKOFF_CAP_MS,
                            f"io:{what}:{salt}:{attempt}")


def with_io_retry(fn: Callable[[], T], what: str,
                  conf: Optional[RapidsConf] = None,
                  fault_point: Optional[str] = None,
                  salt: str = "") -> T:
    """Run `fn` with bounded retry on transient OSErrors.

    `conf` must be passed when the caller runs on a pool thread (the
    active conf is thread-local). `fault_point` names a registered
    injection point checked INSIDE the attempt loop, so injected IO
    faults exercise exactly the retry path a real flaky read would.
    `salt` differentiates the backoff jitter between CONCURRENT callers
    of the same `what` (e.g. per shuffle map file + partition): without
    it, N pool threads hitting one flaky mount would sleep identical
    durations and re-herd on every attempt. Keep it a pure function of
    the work item, never a thread id — chaos replays must reproduce
    timing decisions."""
    conf = conf if conf is not None else active_conf()
    retries = max(0, conf.get(IO_RETRIES))
    base_ms = max(1, conf.get(IO_RETRY_BACKOFF_MS))
    attempt = 0
    while True:
        attempt += 1
        try:
            if fault_point is not None:
                # the salt doubles as the injection work-item key: the
                # chaos verdict follows the work item, not pool-thread
                # scheduling (see FaultPlan.decide)
                faults.check(fault_point, key=salt or None)
            result = fn()
        except OSError as e:
            if isinstance(e, _NON_TRANSIENT) or attempt > retries:
                raise
            backoff = _backoff_s(what, salt, attempt, base_ms)
            from ..obs import events as obs_events
            obs_events.emit("io_retry", what=what, attempt=attempt,
                            max_attempts=retries + 1,
                            backoff_ns=int(backoff * 1e9),
                            error=f"{type(e).__name__}: {e}"[:200])
            time.sleep(backoff)
            continue
        if attempt > 1:
            global _recoveries
            with _recoveries_lock:
                _recoveries += 1
        return result
