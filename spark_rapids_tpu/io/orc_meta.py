"""ORC footer/metadata parsing for stripe-statistics pruning.

pyarrow's ORC bindings expose stripe COUNTS but not the statistics
values, so this module reads them straight from the file: the ORC
physical layout (postscript -> footer -> metadata with per-stripe
ColumnStatistics) is defined by the public Apache ORC specification's
protobuf schema; the few message/field numbers used here are transcribed
from that spec. Reference analog: GpuOrcScan's use of the ORC reader's
StripeStatistics for predicate pushdown (GpuOrcScan.scala:1455-1546 —
behavior parity, independent implementation).

Only what pruning needs is decoded: varints, length-delimited submessages
and the int/double/string/date statistics kinds. Unknown fields are
skipped by wire type, unsupported compression codecs yield NO statistics
(callers must treat missing stats as unprunable — prove-absence only).
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def _skip(buf: bytes, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wt == _WT_I64:
        return pos + 8
    if wt == _WT_LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wt == _WT_I32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wt}")


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value_or_bytes) over a message."""
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        fno, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            v, pos = _read_varint(buf, pos)
            yield fno, wt, v
        elif wt == _WT_LEN:
            n, pos = _read_varint(buf, pos)
            yield fno, wt, buf[pos:pos + n]
            pos += n
        elif wt == _WT_I64:
            yield fno, wt, buf[pos:pos + 8]
            pos += 8
        elif wt == _WT_I32:
            yield fno, wt, buf[pos:pos + 4]
            pos += 4
        else:
            pos = _skip(buf, pos, wt)


class ColumnStats:
    """Normalized per-stripe, per-column statistics with the same duck
    shape parquet stats expose (so io/parquet._stats_can_skip applies
    verbatim)."""

    __slots__ = ("num_values", "null_count", "min", "max", "has_min_max")

    def __init__(self, num_values=None, null_count=None,
                 mn=None, mx=None):
        self.num_values = num_values
        self.null_count = null_count
        self.min = mn
        self.max = mx
        self.has_min_max = mn is not None and mx is not None


def _parse_column_stats(buf: bytes, total_rows: Optional[int]
                        ) -> ColumnStats:
    num_values = None
    has_null = None
    mn = mx = None
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == _WT_VARINT:          # numberOfValues
            num_values = v
        elif fno == 10 and wt == _WT_VARINT:       # hasNull
            has_null = bool(v)
        elif fno == 2 and wt == _WT_LEN:           # intStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == _WT_VARINT:
                    mn = _zigzag(v2)
                elif f2 == 2 and w2 == _WT_VARINT:
                    mx = _zigzag(v2)
        elif fno == 3 and wt == _WT_LEN:           # doubleStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == _WT_I64:
                    mn = struct.unpack("<d", v2)[0]
                elif f2 == 2 and w2 == _WT_I64:
                    mx = struct.unpack("<d", v2)[0]
        elif fno == 4 and wt == _WT_LEN:           # stringStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == _WT_LEN:
                    mn = v2.decode("utf-8", "surrogateescape")
                elif f2 == 2 and w2 == _WT_LEN:
                    mx = v2.decode("utf-8", "surrogateescape")
        elif fno == 7 and wt == _WT_LEN:           # dateStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == _WT_VARINT:
                    mn = _zigzag(v2)
                elif f2 == 2 and w2 == _WT_VARINT:
                    mx = _zigzag(v2)
    null_count = None
    if has_null is False:
        null_count = 0
    elif has_null is True and num_values is not None \
            and total_rows is not None:
        null_count = max(total_rows - num_values, 1)
    return ColumnStats(num_values, null_count, mn, mx)


def _decompress_section(raw: bytes, codec: int) -> Optional[bytes]:
    """ORC compressed section: concatenated blocks with a 3-byte header
    (chunk_len << 1 | is_original). Codec 0 = NONE (raw bytes), 1 = ZLIB
    (raw deflate). Anything else -> None (caller skips pruning)."""
    if codec == 0:
        return raw
    if codec != 1:
        return None
    out = bytearray()
    pos = 0
    while pos + 3 <= len(raw):
        hdr = raw[pos] | (raw[pos + 1] << 8) | (raw[pos + 2] << 16)
        pos += 3
        n = hdr >> 1
        chunk = raw[pos:pos + n]
        pos += n
        if hdr & 1:  # original (stored uncompressed)
            out += chunk
        else:
            out += zlib.decompress(chunk, -15)
    return bytes(out)


class OrcFileMeta:
    """Parsed ORC tail: top-level column name -> stats index mapping,
    per-stripe row counts and per-stripe ColumnStats."""

    def __init__(self, path: str):
        self.stripe_stats: List[Dict[str, ColumnStats]] = []
        self.stripe_rows: List[int] = []
        self.ok = False
        try:
            self._parse(path)
            self.ok = True
        except Exception:  # noqa: BLE001 — any parse issue = no pruning
            self.stripe_stats = []

    def _parse(self, path: str) -> None:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            tail_len = min(size, 1 << 18)
            f.seek(size - tail_len)
            tail = f.read(tail_len)
        ps_len = tail[-1]
        ps = tail[-1 - ps_len:-1]
        footer_len = metadata_len = 0
        codec = 0
        for fno, wt, v in _fields(ps):
            if fno == 1 and wt == _WT_VARINT:
                footer_len = v
            elif fno == 2 and wt == _WT_VARINT:
                codec = v
            elif fno == 5 and wt == _WT_VARINT:
                metadata_len = v
        need = 1 + ps_len + footer_len + metadata_len
        if need > len(tail):
            with open(path, "rb") as f:
                f.seek(size - need)
                tail = f.read(need)
        footer_raw = tail[-1 - ps_len - footer_len:-1 - ps_len]
        meta_raw = tail[-1 - ps_len - footer_len - metadata_len:
                        -1 - ps_len - footer_len]
        footer = _decompress_section(footer_raw, codec)
        meta = _decompress_section(meta_raw, codec)
        if footer is None or meta is None:
            raise ValueError("unsupported ORC compression codec")

        # footer: types (field 4, depth-first) give the name -> stats
        # column mapping; stripes (field 3) give per-stripe row counts
        types: List[Tuple[List[int], List[str]]] = []
        for fno, wt, v in _fields(footer):
            if fno == 4 and wt == _WT_LEN:     # Type
                subtypes: List[int] = []
                names: List[str] = []
                for f2, w2, v2 in _fields(v):
                    if f2 == 2 and w2 == _WT_VARINT:
                        subtypes.append(v2)
                    elif f2 == 2 and w2 == _WT_LEN:
                        # packed repeated uint32
                        p = 0
                        while p < len(v2):
                            u, p = _read_varint(v2, p)
                            subtypes.append(u)
                    elif f2 == 3 and w2 == _WT_LEN:
                        names.append(v2.decode("utf-8"))
                types.append((subtypes, names))
            elif fno == 3 and wt == _WT_LEN:   # StripeInformation
                for f2, w2, v2 in _fields(v):
                    if f2 == 5 and w2 == _WT_VARINT:
                        self.stripe_rows.append(v2)
        if not types:
            raise ValueError("no types in footer")
        root_subtypes, root_names = types[0]
        name_to_stat_idx = dict(zip(root_names, root_subtypes))

        idx = 0
        for fno, wt, v in _fields(meta):
            if fno != 1 or wt != _WT_LEN:      # StripeStatistics
                continue
            rows = self.stripe_rows[idx] if idx < len(self.stripe_rows) \
                else None
            cols: List[bytes] = [v2 for f2, w2, v2 in _fields(v)
                                 if f2 == 1 and w2 == _WT_LEN]
            per_name: Dict[str, ColumnStats] = {}
            for name, ci in name_to_stat_idx.items():
                if ci < len(cols):
                    per_name[name] = _parse_column_stats(cols[ci], rows)
            self.stripe_stats.append(per_name)
            idx += 1
