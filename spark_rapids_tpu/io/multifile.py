"""Multi-file reader base — the reference's three-reader framework
(GpuMultiFileReader.scala: PERFILE, MULTITHREADED :345, COALESCING :830).

The MULTITHREADED pattern is the default here: a thread pool decodes the
next chunks on host while the device pipeline consumes the current batch,
hiding IO/decode latency exactly like the reference hides S3 fetch+footer
parse. COALESCING falls out of the chunk iterator: small files/row groups
feed the downstream CoalesceBatchesExec instead of a bespoke stitcher.
"""

from __future__ import annotations

import glob
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, List, Sequence

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf


def expand_paths(path) -> List[str]:
    """file | directory | glob | list of any of those -> ordered file list."""
    if isinstance(path, (list, tuple)):
        out: List[str] = []
        for p in path:
            out.extend(expand_paths(p))
        return out
    path = os.fspath(path)
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if not f.startswith((".", "_")))
    if any(ch in path for ch in "*?["):
        return sorted(glob.glob(path))
    return [path]


def threaded_chunks(tasks: Sequence[Callable[[], "object"]],
                    num_threads: int) -> Iterator["object"]:
    """Decode `tasks` with a bounded look-ahead pool, yielding in order
    (the multithreaded cloud reader: fetch ahead, emit in sequence)."""
    if num_threads <= 1 or len(tasks) <= 1:
        for t in tasks:
            yield t()
        return
    with ThreadPoolExecutor(max_workers=num_threads) as pool:
        window = 2 * num_threads
        futures = [pool.submit(t) for t in tasks[:window]]
        next_submit = window
        for i in range(len(tasks)):
            yield futures[i].result()
            futures[i] = None  # release
            if next_submit < len(tasks):
                futures.append(pool.submit(tasks[next_submit]))
                next_submit += 1


def arrow_to_batches(table, target_rows: int) -> Iterator[ColumnarBatch]:
    """Split a host arrow table into device batches of ~target_rows."""
    n = table.num_rows
    if n == 0:
        yield ColumnarBatch.from_arrow(table)
        return
    for start in range(0, n, target_rows):
        yield ColumnarBatch.from_arrow(table.slice(start, target_rows))
