"""Multi-file reader base — the reference's three-reader framework
(GpuMultiFileReader.scala: PERFILE, MULTITHREADED :345, COALESCING :830).

The MULTITHREADED pattern is the default here: a thread pool decodes the
next chunks on host while the device pipeline consumes the current batch,
hiding IO/decode latency exactly like the reference hides S3 fetch+footer
parse. COALESCING falls out of the chunk iterator: small files/row groups
feed the downstream CoalesceBatchesExec instead of a bespoke stitcher.
"""

from __future__ import annotations

import glob
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, List, Optional, Sequence

from ..columnar.batch import ColumnarBatch
from ..config import (MULTITHREADED_READ_FETCH_AHEAD,
                      MULTITHREADED_READ_NUM_THREADS, RapidsConf,
                      active_conf)


def expand_paths(path) -> List[str]:
    """file | directory | glob | list of any of those -> ordered file list."""
    if isinstance(path, (list, tuple)):
        out: List[str] = []
        for p in path:
            out.extend(expand_paths(p))
        return out
    path = os.fspath(path)
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if not f.startswith((".", "_")))
    if any(ch in path for ch in "*?["):
        return sorted(glob.glob(path))
    return [path]


#: ONE process-wide decode pool shared by every scan (ISSUE 3
#: satellite): per-call pools multiplied thread counts once pipeline
#: producer threads drove several scans at once, and paid pool
#: setup/teardown per batches() drive. Sized by
#: spark.rapids.sql.multiThreadedRead.numThreads; grows (never shrinks)
#: if a later conf asks for more.
_pool: Optional[ThreadPoolExecutor] = None
_pool_size = 0
_pool_lock = threading.Lock()
#: replaced-on-growth pools, kept alive for their in-flight drives
_retired: list = []


def shared_read_pool(num_threads: Optional[int] = None
                     ) -> ThreadPoolExecutor:
    """The process-wide multi-file decode pool (lazily created)."""
    global _pool, _pool_size
    if num_threads is None:
        num_threads = active_conf().get(MULTITHREADED_READ_NUM_THREADS)
    num_threads = max(1, int(num_threads))
    with _pool_lock:
        if _pool is None or num_threads > _pool_size:
            # grow-only, and the old pool is RETIRED, never shut down:
            # an in-flight threaded_chunks drive still submits to its
            # captured pool reference — shutdown() would raise
            # RuntimeError mid-scan. Growth is a rare conf event; a
            # retired pool's idle workers are an accepted cost.
            if _pool is not None:
                _retired.append(_pool)
            _pool = ThreadPoolExecutor(
                max_workers=num_threads,
                thread_name_prefix="multifile-read")
            _pool_size = num_threads
        return _pool


def fetch_ahead_window(num_threads: int,
                       conf: Optional[RapidsConf] = None) -> int:
    """Decode tasks a reader keeps in flight ahead of its consumer
    (spark.rapids.sql.multiThreadedRead.fetchAheadWindow; 0 = the
    classic 2 x numThreads)."""
    conf = conf if conf is not None else active_conf()
    window = conf.get(MULTITHREADED_READ_FETCH_AHEAD)
    return window if window > 0 else 2 * max(1, num_threads)


def threaded_chunks(tasks: Sequence[Callable[[], "object"]],
                    num_threads: int,
                    window: Optional[int] = None) -> Iterator["object"]:
    """Decode `tasks` with a bounded look-ahead window on the shared
    pool, yielding in order (the multithreaded cloud reader: fetch
    ahead, emit in sequence). Every decode task runs under bounded IO
    retry (io/retrying.py): a transient OSError — a flaky mount, an
    object-store hiccup, an injected `io.multifile_read` fault — backs
    off and re-reads instead of killing the scan."""
    from .retrying import with_io_retry
    from ..obs import events as obs_events
    conf = active_conf()  # captured HERE: pool threads see default conf
    # the query id too (ISSUE 12): the shared pool serves every query,
    # so io_retry events from a decode task must carry the SUBMITTING
    # thread's attribution, not the pool thread's empty TLS
    qid = obs_events.current_query_id()

    def retrying(t: Callable[[], "object"], i: int) -> "object":
        # per-chunk jitter salt: concurrent decode tasks on one flaky
        # mount must not back off in lockstep
        return obs_events.with_query_id(
            qid, with_io_retry, t, "multifile_read", conf=conf,
            fault_point="io.multifile_read", salt=str(i))

    if num_threads <= 1 or len(tasks) <= 1:
        for i, t in enumerate(tasks):
            yield retrying(t, i)
        return
    pool = shared_read_pool(max(
        num_threads, conf.get(MULTITHREADED_READ_NUM_THREADS)))
    if window is None:
        window = fetch_ahead_window(num_threads)
    futures = [pool.submit(retrying, t, i)
               for i, t in enumerate(tasks[:window])]
    next_submit = window
    try:
        for i in range(len(tasks)):
            yield futures[i].result()
            futures[i] = None  # release
            if next_submit < len(tasks):
                futures.append(pool.submit(retrying, tasks[next_submit],
                                           next_submit))
                next_submit += 1
    finally:
        # abandoned mid-drive (limit/short-circuit): cancel what never
        # started so the shared pool isn't left decoding dead work
        for f in futures:
            if f is not None:
                f.cancel()


def arrow_to_batches(table, target_rows: int) -> Iterator[ColumnarBatch]:
    """Split a host arrow table into device batches of ~target_rows.
    The slice offset keys each batch's upload for seeded chaos (the
    work item is the row range, not the thread that happens to decode
    it)."""
    n = table.num_rows
    if n == 0:
        yield ColumnarBatch.from_arrow(table, fault_key="scan:0")
        return
    for start in range(0, n, target_rows):
        yield ColumnarBatch.from_arrow(table.slice(start, target_rows),
                                       fault_key=f"scan:{start}")
