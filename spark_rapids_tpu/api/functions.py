"""Column functions — the pyspark.sql.functions analog, resolving to the
engine's expression and aggregate classes."""

from __future__ import annotations

from ..expr import arithmetic, conditional, hashexprs, stringexprs
from ..expr.aggexprs import (
    Average, Count, First, Last, Max, Min, StddevPop, StddevSamp, Sum,
    VariancePop, VarianceSamp,
)
from ..expr.core import Expression, col, lit  # noqa: F401


def _e(x) -> Expression:
    return x if isinstance(x, Expression) else (col(x) if isinstance(x, str)
                                                else lit(x))


# aggregates ---------------------------------------------------------------
def sum(x):  # noqa: A001
    return Sum(_e(x))


def count(x=None):
    return Count(_e(x)) if x is not None else Count()


def avg(x):
    return Average(_e(x))


mean = avg


def min(x):  # noqa: A001
    return Min(_e(x))


def max(x):  # noqa: A001
    return Max(_e(x))


def first(x):
    return First(_e(x))


def last(x):
    return Last(_e(x))


def stddev(x):
    return StddevSamp(_e(x))


stddev_samp = stddev


def stddev_pop(x):
    return StddevPop(_e(x))


def variance(x):
    return VarianceSamp(_e(x))


var_samp = variance


def var_pop(x):
    return VariancePop(_e(x))


# scalar functions ---------------------------------------------------------
def coalesce(*xs):
    return conditional.Coalesce(*[_e(x) for x in xs])


def when(cond, value):
    return conditional.CaseWhen([( _e(cond), _e(value))], None)


def abs(x):  # noqa: A001
    return arithmetic.Abs(_e(x))


def length(x):
    return stringexprs.Length(_e(x))


def upper(x):
    return stringexprs.Upper(_e(x))


def lower(x):
    return stringexprs.Lower(_e(x))


def substring(x, pos, length_):
    return stringexprs.Substring(_e(x), pos, length_)


def hash(*xs):  # noqa: A001
    return hashexprs.Murmur3Hash(*[_e(x) for x in xs])


def xxhash64(*xs):
    return hashexprs.XxHash64(*[_e(x) for x in xs])
