"""Column functions — the pyspark.sql.functions analog, resolving to the
engine's expression and aggregate classes."""

from __future__ import annotations

from ..expr import arithmetic, conditional, hashexprs, stringexprs
from ..expr.aggexprs import (
    Average, Count, First, Last, Max, Min, StddevPop, StddevSamp, Sum,
    VariancePop, VarianceSamp,
)
from ..expr.core import Expression, col, lit  # noqa: F401


def _e(x) -> Expression:
    return x if isinstance(x, Expression) else (col(x) if isinstance(x, str)
                                                else lit(x))


# aggregates ---------------------------------------------------------------
def sum(x):  # noqa: A001
    return Sum(_e(x))


def count(x=None):
    return Count(_e(x)) if x is not None else Count()


def avg(x):
    return Average(_e(x))


mean = avg


def min(x):  # noqa: A001
    return Min(_e(x))


def max(x):  # noqa: A001
    return Max(_e(x))


def udf(fn=None, *, return_type=None):
    from ..expr.udf import udf as _udf
    return _udf(fn, return_type=return_type)


def percentile(x, percentage):
    from ..expr.aggexprs import Percentile
    return Percentile(_e(x), percentage)


def approx_percentile(x, percentage, accuracy=None):
    from ..expr.aggexprs import ApproxPercentile
    return ApproxPercentile(_e(x), percentage, accuracy)


def collect_list(x):
    from ..expr.aggexprs import CollectList
    return CollectList(_e(x))


def collect_set(x):
    from ..expr.aggexprs import CollectSet
    return CollectSet(_e(x))


def first(x, ignore_nulls=False):
    return First(_e(x), ignore_nulls=ignore_nulls)


def last(x, ignore_nulls=False):
    return Last(_e(x), ignore_nulls=ignore_nulls)


def stddev(x):
    return StddevSamp(_e(x))


stddev_samp = stddev


def stddev_pop(x):
    return StddevPop(_e(x))


def variance(x):
    return VarianceSamp(_e(x))


var_samp = variance


def var_pop(x):
    return VariancePop(_e(x))


# scalar functions ---------------------------------------------------------
def coalesce(*xs):
    return conditional.Coalesce(*[_e(x) for x in xs])


def when(cond, value):
    return conditional.CaseWhen([( _e(cond), _e(value))], None)


def abs(x):  # noqa: A001
    return arithmetic.Abs(_e(x))


def length(x):
    return stringexprs.Length(_e(x))


def upper(x):
    return stringexprs.Upper(_e(x))


def lower(x):
    return stringexprs.Lower(_e(x))


def substring(x, pos, length_):
    return stringexprs.Substring(_e(x), pos, length_)


def trim(x, trim_str=None):
    return stringexprs.StringTrim(_e(x), trim_str)


def ltrim(x, trim_str=None):
    return stringexprs.StringTrimLeft(_e(x), trim_str)


def rtrim(x, trim_str=None):
    return stringexprs.StringTrimRight(_e(x), trim_str)


def lpad(x, length_, pad=" "):
    return stringexprs.StringLPad(_e(x), length_, pad)


def rpad(x, length_, pad=" "):
    return stringexprs.StringRPad(_e(x), length_, pad)


def repeat(x, n):
    return stringexprs.StringRepeat(_e(x), n)


def reverse(x):
    return stringexprs.Reverse(_e(x))


def initcap(x):
    return stringexprs.InitCap(_e(x))


def locate(substr, x, pos=1):
    return stringexprs.StringLocate(substr, _e(x), pos)


def instr(x, substr):
    return stringexprs.StringLocate(substr, _e(x), 1)


def replace(x, search, replacement=""):
    return stringexprs.StringReplace(_e(x), search, replacement)


def concat(*xs):
    return stringexprs.Concat(*[_e(x) for x in xs])


def concat_ws(sep, *xs):
    return stringexprs.ConcatWs(sep, *[_e(x) for x in xs])


def translate(x, from_str, to_str):
    return stringexprs.StringTranslate(_e(x), from_str, to_str)


def ascii(x):  # noqa: A001
    return stringexprs.Ascii(_e(x))


def chr(x):  # noqa: A001
    return stringexprs.Chr(_e(x))


def left(x, n):
    return stringexprs.Left(_e(x), n)


def right(x, n):
    return stringexprs.Right(_e(x), n)


def octet_length(x):
    return stringexprs.OctetLength(_e(x))


def bit_length(x):
    return stringexprs.BitLength(_e(x))


def contains(x, needle):
    return stringexprs.Contains(_e(x), needle)


def startswith(x, prefix):
    return stringexprs.StartsWith(_e(x), prefix)


def endswith(x, suffix):
    return stringexprs.EndsWith(_e(x), suffix)


def rlike(x, pattern):
    return stringexprs.RLike(_e(x), pattern)


def like(x, pattern, escape_char="\\"):
    return stringexprs.Like(_e(x), pattern, escape_char)


def nvl(a, b):
    return conditional.Nvl(_e(a), _e(b))


ifnull = nvl


def nvl2(a, b, c):
    return conditional.Nvl2(_e(a), _e(b), _e(c))


def nullif(a, b):
    return conditional.NullIf(_e(a), _e(b))


# collections ----------------------------------------------------------------
def size(x):
    from ..expr import collectionexprs
    return collectionexprs.Size(_e(x))


def array_contains(x, value):
    from ..expr import collectionexprs
    return collectionexprs.ArrayContains(_e(x), value)


def element_at(x, index):
    # ElementAt dispatches on the child's RESOLVED type (map lookup vs
    # array index), so expression indices work for both (ADVICE r3 #1)
    from ..expr import collectionexprs
    return collectionexprs.ElementAt(_e(x), index)


# maps -----------------------------------------------------------------------
def create_map(*cols):
    from ..expr import mapexprs
    return mapexprs.CreateMap(*[_e(c) for c in cols])


def map_keys(x):
    from ..expr import mapexprs
    return mapexprs.MapKeys(_e(x))


def map_values(x):
    from ..expr import mapexprs
    return mapexprs.MapValues(_e(x))


def map_contains_key(x, key):
    from ..expr import mapexprs
    return mapexprs.MapContainsKey(_e(x), key)


def get_map_value(x, key):
    from ..expr import mapexprs
    k = _e(key) if not isinstance(key, (str, int, float)) else key
    return mapexprs.GetMapValue(_e(x), k)


def element_at_key(x, key):
    """element_at over a MAP with a non-literal (column) key."""
    return get_map_value(x, key)


def get_array_item(x, index):
    from ..expr import collectionexprs
    return collectionexprs.GetArrayItem(_e(x), index)


def sort_array(x, asc=True):
    from ..expr import collectionexprs
    return collectionexprs.SortArray(_e(x), asc)


def array_min(x):
    from ..expr import collectionexprs
    return collectionexprs.ArrayMin(_e(x))


def array_max(x):
    from ..expr import collectionexprs
    return collectionexprs.ArrayMax(_e(x))


def array(*xs):
    from ..expr import collectionexprs
    return collectionexprs.CreateArray(*[_e(x) for x in xs])


def hash(*xs):  # noqa: A001
    return hashexprs.Murmur3Hash(*[_e(x) for x in xs])


def xxhash64(*xs):
    return hashexprs.XxHash64(*[_e(x) for x in xs])


# window functions -----------------------------------------------------------
def row_number():
    from ..expr.windowexprs import RowNumber
    return RowNumber()


def rank():
    from ..expr.windowexprs import Rank
    return Rank()


def dense_rank():
    from ..expr.windowexprs import DenseRank
    return DenseRank()


def lag(x, offset=1, default=None):
    from ..expr.windowexprs import Lag
    return Lag(_e(x), offset, default)


def lead(x, offset=1, default=None):
    from ..expr.windowexprs import Lead
    return Lead(_e(x), offset, default)


def window_sum(x):
    from ..expr.windowexprs import WindowAgg
    return WindowAgg("sum", _e(x))


def window_min(x):
    from ..expr.windowexprs import WindowAgg
    return WindowAgg("min", _e(x))


def window_max(x):
    from ..expr.windowexprs import WindowAgg
    return WindowAgg("max", _e(x))


def window_count(x=None):
    from ..expr.windowexprs import WindowAgg
    return WindowAgg("count", _e(x) if x is not None else None)


def window_avg(x):
    from ..expr.windowexprs import WindowAgg
    return WindowAgg("avg", _e(x))


def first_value(x):
    from ..expr.windowexprs import FirstValue
    return FirstValue(_e(x))


def last_value(x):
    from ..expr.windowexprs import LastValue
    return LastValue(_e(x))


# datetime functions ---------------------------------------------------------
def year(x):
    from ..expr.datetimeexprs import Year
    return Year(_e(x))


def month(x):
    from ..expr.datetimeexprs import Month
    return Month(_e(x))


def dayofmonth(x):
    from ..expr.datetimeexprs import DayOfMonth
    return DayOfMonth(_e(x))


def dayofweek(x):
    from ..expr.datetimeexprs import DayOfWeek
    return DayOfWeek(_e(x))


def dayofyear(x):
    from ..expr.datetimeexprs import DayOfYear
    return DayOfYear(_e(x))


def quarter(x):
    from ..expr.datetimeexprs import Quarter
    return Quarter(_e(x))


def hour(x):
    from ..expr.datetimeexprs import Hour
    return Hour(_e(x))


def minute(x):
    from ..expr.datetimeexprs import Minute
    return Minute(_e(x))


def second(x):
    from ..expr.datetimeexprs import Second
    return Second(_e(x))


def date_add(x, n):
    from ..expr.datetimeexprs import DateAdd
    return DateAdd(_e(x), _e(n))


def date_sub(x, n):
    from ..expr.datetimeexprs import DateAdd
    return DateAdd(_e(x), _e(n), negate=True)


def datediff(end, start):
    from ..expr.datetimeexprs import DateDiff
    return DateDiff(_e(end), _e(start))


def add_months(x, n):
    from ..expr.datetimeexprs import AddMonths
    return AddMonths(_e(x), _e(n))


def last_day(x):
    from ..expr.datetimeexprs import LastDay
    return LastDay(_e(x))


def trunc(x, unit):
    from ..expr.datetimeexprs import TruncDate
    return TruncDate(_e(x), unit)



def from_utc_timestamp(x, tz):
    from ..expr.datetimeexprs import FromUTCTimestamp
    return FromUTCTimestamp(_e(x), tz)


def to_utc_timestamp(x, tz):
    from ..expr.datetimeexprs import ToUTCTimestamp
    return ToUTCTimestamp(_e(x), tz)


# bitwise / shifts --------------------------------------------------------
def shiftleft(x, n):
    from ..expr.bitwise import ShiftLeft
    return ShiftLeft(_e(x), _e(n))


def shiftright(x, n):
    from ..expr.bitwise import ShiftRight
    return ShiftRight(_e(x), _e(n))


def shiftrightunsigned(x, n):
    from ..expr.bitwise import ShiftRightUnsigned
    return ShiftRightUnsigned(_e(x), _e(n))


def bitwise_not(x):
    from ..expr.bitwise import BitwiseNot
    return BitwiseNot(_e(x))


# JSON / URL / string long tail (host-tier expressions) -------------------
def get_json_object(x, path):
    from ..expr.jsonexprs import GetJsonObject
    return GetJsonObject(_e(x), path)


def parse_url(x, part, key=None):
    from ..expr.urlexprs import ParseUrl
    return ParseUrl(_e(x), part, key)


def split(x, pattern, limit=-1):
    from ..expr.stringexprs import StringSplit
    return StringSplit(_e(x), pattern, limit)


def substring_index(x, delim, count):
    from ..expr.stringexprs import SubstringIndex
    return SubstringIndex(_e(x), delim, count)


def find_in_set(needle, s):
    from ..expr.stringexprs import FindInSet
    return FindInSet(_e(needle), _e(s))


def regexp_extract(x, pattern, idx=1):
    from ..expr.stringexprs import RegExpExtract
    return RegExpExtract(_e(x), pattern, idx)


def regexp_replace(x, pattern, replacement):
    from ..expr.stringexprs import RegExpReplace
    return RegExpReplace(_e(x), pattern, replacement)


def format_number(x, d):
    from ..expr.stringexprs import FormatNumber
    return FormatNumber(_e(x), d)


def levenshtein(a, b):
    from ..expr.stringexprs import Levenshtein
    return Levenshtein(_e(a), _e(b))


# higher-order functions + collection long tail ---------------------------
def _lambda_body(fn, *var_names):
    """Build the body expression from a Python lambda over LambdaVar
    placeholders: F.transform(c, lambda x: x + 1)."""
    from ..expr.collectionexprs import LambdaVar
    return fn(*[LambdaVar(n) for n in var_names])


def transform(x, fn):
    from ..expr.collectionexprs import ArrayTransform
    return ArrayTransform(_e(x), _lambda_body(fn, "x"), "x")


def filter_(x, fn):
    from ..expr.collectionexprs import ArrayFilter
    return ArrayFilter(_e(x), _lambda_body(fn, "x"), "x")


def exists(x, fn):
    from ..expr.collectionexprs import ArrayExists
    return ArrayExists(_e(x), _lambda_body(fn, "x"), "x")


def forall(x, fn):
    from ..expr.collectionexprs import ArrayForAll
    return ArrayForAll(_e(x), _lambda_body(fn, "x"), "x")


def aggregate(x, zero, merge, finish=None):
    from ..expr.collectionexprs import ArrayAggregate, LambdaVar
    merge_body = merge(LambdaVar("acc"), LambdaVar("x"))
    finish_body = finish(LambdaVar("acc")) if finish is not None else None
    return ArrayAggregate(_e(x), _e(zero), merge_body, finish_body)


def array_position(x, v):
    from ..expr.collectionexprs import ArrayPosition
    return ArrayPosition(_e(x), _e(v))


def array_remove(x, v):
    from ..expr.collectionexprs import ArrayRemove
    return ArrayRemove(_e(x), _e(v))


def array_distinct(x):
    from ..expr.collectionexprs import ArrayDistinct
    return ArrayDistinct(_e(x))


def slice(x, start, length):  # noqa: A001 - Spark name
    from ..expr.collectionexprs import Slice
    return Slice(_e(x), _e(start), _e(length))


def flatten(x):
    from ..expr.collectionexprs import Flatten
    return Flatten(_e(x))


def arrays_overlap(a, b):
    from ..expr.collectionexprs import ArraysOverlap
    return ArraysOverlap(_e(a), _e(b))


def array_join(x, delim, null_replacement=None):
    from ..expr.collectionexprs import ArrayJoin
    return ArrayJoin(_e(x), delim, null_replacement)


def sequence(start, stop, step=None):
    from ..expr.collectionexprs import Sequence
    return Sequence(_e(start), _e(stop),
                    _e(step) if step is not None else None)


def base64(x):
    from ..expr.stringexprs import Base64Encode
    return Base64Encode(_e(x))


def unbase64(x):
    from ..expr.stringexprs import UnBase64
    return UnBase64(_e(x))


def hex(x):  # noqa: A001 - Spark name
    from ..expr.stringexprs import Hex
    return Hex(_e(x))


def unhex(x):
    from ..expr.stringexprs import Unhex
    return Unhex(_e(x))


def encode(x, charset):
    from ..expr.stringexprs import Encode
    return Encode(_e(x), charset)


def decode(x, charset):
    from ..expr.stringexprs import Decode
    return Decode(_e(x), charset)


def array_repeat(x, n):
    """array_repeat(e, n) (reference GpuArrayRepeat)."""
    from ..expr.collectionexprs import ArrayRepeat
    return ArrayRepeat(_e(x), _e(n))
