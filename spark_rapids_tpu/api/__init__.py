"""User-facing API: session + DataFrame over the logical planner (the
engine's equivalent of the PySpark surface the reference accelerates)."""

from .session import TpuSession  # noqa: F401
from . import functions  # noqa: F401
