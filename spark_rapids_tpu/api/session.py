"""TpuSession + DataFrame — the engine's user surface. The reference keeps
PySpark's API and swaps the physical plan underneath (SQLPlugin +
GpuOverrides); standalone, this session IS the query entry, but the flow
is identical: build a logical plan, run it through TpuOverrides
(wrap -> tag -> convert), execute the TpuExec tree."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..columnar.batch import ColumnarBatch
from ..config import RapidsConf, set_active_conf
from ..expr.aggexprs import AggregateFunction
from ..expr.core import Expression, col, lit, output_name
from ..plan import logical as L
from ..plan.overrides import TpuOverrides
from ..types import Schema


class _InMemorySource:
    def __init__(self, batches: List[ColumnarBatch], schema: Schema):
        self._batches = batches
        self.schema = schema

    def batches(self):
        return list(self._batches)

    def estimated_size_bytes(self) -> int:
        return sum(b.device_size_bytes() for b in self._batches)

    def estimated_num_rows(self) -> int:
        return sum(b.num_rows_host for b in self._batches)


class TpuSession:
    def __init__(self, conf: Optional[Dict] = None,
                 mesh_devices: Optional[int] = None, mesh=None):
        """mesh_devices/mesh: enable distributed planning — group-bys and
        equi-joins compile to partial → ICI all-to-all exchange → final
        SPMD stages over the device mesh (exec/exchange.py). Default: the
        single-partition plan (no exchange nodes)."""
        from .. import faults
        from ..columnar import upload
        from ..obs import dispatch as obs_dispatch
        from ..obs import events as obs_events
        from ..obs import history as obs_history
        from ..obs import telemetry
        from ..parallel.mesh import device_mesh, set_active_mesh
        self.conf = RapidsConf(conf or {})
        set_active_conf(self.conf)
        obs_events.configure(self.conf)
        telemetry.configure(self.conf)
        obs_dispatch.configure(self.conf)
        obs_history.configure(self.conf)
        faults.configure(self.conf)
        # pre-size the upload staging pool's bucket ladder from
        # batchSizeBytes (ISSUE 14 satellite): steady-state scans hit
        # zero grow-on-miss staging allocations
        upload.configure(self.conf)
        if mesh is None and mesh_devices is not None:
            mesh = device_mesh(mesh_devices)
        self.mesh = mesh
        set_active_mesh(mesh)
        #: per-query metric roll-up of the LAST collect() on this
        #: session (exec/task_metrics.py; reference GpuTaskMetrics)
        self._last_query_metrics = None
        #: per-query profile of the LAST collect() (obs/profile.py)
        self._last_query_profile = None
        #: lifecycle-governor ownership token: every governed collect
        #: registers its QueryContext under it, so cancel_query() (from
        #: any thread) can find and cancel THIS session's queries
        self._lifecycle_owner = object()

    def cancel_query(self) -> int:
        """Cooperatively cancel every query this session is currently
        running (exec/lifecycle.py): their cancellation tokens are set,
        each blocked or computing thread raises QueryCancelledError at
        its next batch boundary / wait-loop poll, and the queries
        unwind through their normal try/finally chains — no leaked
        pipeline/spill threads, settled budget and catalog counters.
        Returns the number of queries cancelled (0 = none running)."""
        from ..exec import lifecycle
        return lifecycle.cancel_owner(self._lifecycle_owner)

    def health(self) -> Dict:
        """Engine health surface (exec/lifecycle.py): degradation
        circuit-breaker states per fault domain, governed-query count,
        the cumulative lifecycle counters (cancellations, breaker
        trips, partition-granular vs whole-plan recoveries), the
        workload governor's admission surface — queue depth, admitted
        count, queued/admitted/shed/quota-spill counters
        (exec/workload.py) — the telemetry registry's state + newest
        sample (obs/telemetry.py), and the dispatch ledger's program
        counters with the worst compile-cost programs
        (obs/dispatch.py)."""
        from ..exec import lifecycle
        from ..obs import dispatch, telemetry
        from ..obs import stats as obs_stats
        from ..parallel import heartbeat
        out = lifecycle.health()
        out["telemetry"] = telemetry.health_section()
        out["dispatch"] = dispatch.health_section()
        # peer liveness registry (ISSUE 20): live/dead peers, lifetime
        # purges and blacklisted slots — {"enabled": False} in the
        # default single-process session (no installed manager)
        out["peers"] = heartbeat.health_section()
        # per-priority-class wall-clock percentiles over the telemetry
        # registry's latency ring (ISSUE 17) — {"enabled": False} when
        # telemetry is off
        out["slo"] = telemetry.slo_section()
        # skew pressure + adaptive decisions (ISSUE 19): recent
        # per-exchange max/median ratios and the replanner's decision
        # counters, so operators see what the measured-statistics
        # control plane did without reading the event log
        out["stats"] = obs_stats.health_section()
        return out

    def active_queries(self) -> List[Dict]:
        """Live engine introspection (ISSUE 11): one row per in-flight
        governed query — phase (queued / admitted / executing /
        retrying), the operator currently yielding batches, root-output
        batches/rows produced so far, elapsed and deadline-remaining
        ms, task attempt number, spill count/bytes the query
        experienced, and (under the workload governor) its quota
        used/granted. Assembled lock-light from lifecycle/workload/
        catalog state; `mine` marks the queries this session drives
        (the surface is engine-wide, like health()). Empty when nothing
        is running."""
        from ..exec import lifecycle
        return lifecycle.active_queries(owner=self._lifecycle_owner)

    def last_query_metrics(self):
        """Task-level metrics of the most recent DataFrame.collect():
        semaphore wait, OOM-retry counts, spill volumes (per-query
        deltas) plus per-operator metric sums — the engine's
        GpuTaskMetrics surface (GpuTaskMetrics.scala:81-103). Honors
        spark.rapids.sql.metrics.level (GpuExec.scala:36-47)."""
        return self._last_query_metrics

    def last_query_profile(self):
        """QueryProfile of the most recent DataFrame.collect(): the
        executed plan tree annotated with per-operator metrics, with
        `.text()` (explain-with-metrics, the Spark-SQL-UI analog),
        `.to_json()` and `.top_operators()` renderers (obs/profile.py).
        None before the first collect."""
        return self._last_query_profile

    # -- ingestion ---------------------------------------------------------
    def from_pydict(self, data: Dict, schema: Schema,
                    batch_rows: Optional[int] = None) -> "DataFrame":
        n = len(next(iter(data.values()))) if data else 0
        rows = batch_rows or max(n, 1)
        batches = []
        for s in range(0, max(n, 1), rows):
            chunk = {k: v[s:s + rows] for k, v in data.items()}
            batches.append(ColumnarBatch.from_pydict(chunk, schema))
        return self._df(L.LogicalScan(_InMemorySource(batches, schema)))

    def from_arrow(self, table) -> "DataFrame":
        batch = ColumnarBatch.from_arrow(table)
        return self._df(L.LogicalScan(
            _InMemorySource([batch], batch.schema)))

    def from_batches(self, batches: Sequence[ColumnarBatch],
                     schema: Schema) -> "DataFrame":
        return self._df(L.LogicalScan(_InMemorySource(list(batches), schema)))

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return self._df(L.LogicalRange(start, end, step))

    def read_parquet(self, path) -> "DataFrame":
        from ..io.parquet import ParquetSource
        return self._df(L.LogicalScan(ParquetSource(path, self.conf)))

    def read_csv(self, path, schema: Optional[Schema] = None,
                 header: bool = True, **options) -> "DataFrame":
        from ..io.csv import CsvSource
        return self._df(L.LogicalScan(CsvSource(path, self.conf,
                                                schema=schema,
                                                header=header, **options)))

    def read_json(self, path, schema: Optional[Schema] = None,
                  **options) -> "DataFrame":
        from ..io.json import JsonSource
        return self._df(L.LogicalScan(JsonSource(path, self.conf,
                                                 schema=schema, **options)))

    def read_orc(self, path, columns=None) -> "DataFrame":
        from ..io.orc import OrcSource
        return self._df(L.LogicalScan(OrcSource(path, self.conf,
                                                columns=columns)))

    def read_iceberg(self, path, snapshot_id=None) -> "DataFrame":
        from ..io.iceberg import IcebergSource
        return self._df(L.LogicalScan(IcebergSource(path, self.conf,
                                                    snapshot_id)))

    def read_hive_text(self, path, schema, **options) -> "DataFrame":
        from ..io.hivetext import HiveTextSource
        return self._df(L.LogicalScan(HiveTextSource(path, schema,
                                                     self.conf, **options)))

    def read_delta(self, path, version=None) -> "DataFrame":
        from ..delta import read_delta
        return read_delta(self, path, version)

    def read_avro(self, path, **options) -> "DataFrame":
        from ..io.avro import AvroSource
        return self._df(L.LogicalScan(AvroSource(path, self.conf,
                                                 **options)))

    def _df(self, plan: L.LogicalPlan) -> "DataFrame":
        return DataFrame(plan, self)


def _to_expr(x) -> Expression:
    if isinstance(x, Expression):
        return x
    if isinstance(x, str):
        return col(x)
    return lit(x)


class DataFrame:
    def __init__(self, plan: L.LogicalPlan, session: TpuSession):
        self._plan = plan
        self.session = session

    @property
    def schema(self) -> Schema:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return list(self.schema.names)

    # -- transformations ---------------------------------------------------
    def select(self, *exprs) -> "DataFrame":
        return self._with(L.LogicalProject([_to_expr(e) for e in exprs],
                                           self._plan))

    def with_column(self, name: str, expr) -> "DataFrame":
        exprs = [col(n) for n in self.columns if n != name]
        exprs.append(_to_expr(expr).alias(name))
        return self._with(L.LogicalProject(exprs, self._plan))

    def filter(self, condition) -> "DataFrame":
        return self._with(L.LogicalFilter(_to_expr(condition), self._plan))

    where = filter

    def group_by(self, *keys) -> "GroupedData":
        return GroupedData([_to_expr(k) for k in keys], self)

    groupBy = group_by

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """Spark df.mapInPandas(fn, schema): fn(iterator of pandas
        DataFrames) -> iterator of DataFrames (reference
        GpuMapInBatchExec.scala)."""
        return self._with(L.LogicalMapInBatch(fn, _to_schema(schema),
                                              self._plan))

    mapInPandas = map_in_pandas

    def window_in_pandas(self, partition_by, *wins) -> "DataFrame":
        """Whole-partition pandas window UDFs: each win is (fn, name,
        result_type, input columns...); fn(series...) -> scalar broadcast
        over its partition (reference GpuWindowInPandasExecBase)."""
        parts = [_to_expr(p) for p in (
            partition_by if isinstance(partition_by, (list, tuple))
            else [partition_by])]
        return self._with(L.LogicalWindowInPandas(
            parts, _named_pandas_fns(wins), self._plan))

    def agg(self, *aggs: Tuple[AggregateFunction, str]) -> "DataFrame":
        return GroupedData([], self).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             left_on=None, right_on=None, condition=None) -> "DataFrame":
        if on is not None:
            names = [on] if isinstance(on, str) else list(on)
            if how not in ("left_semi", "left_anti", "existence"):
                # USING-join semantics (Spark): ONE output column per key.
                # Rename the right keys, join, project the dup away; the
                # surviving key is left's (right's for right_outer,
                # coalesced for full_outer).
                return self._using_join(other, names, how, condition)
            lkeys = [col(n) for n in names]
            rkeys = [col(n) for n in names]
        elif left_on is not None:
            lk = [left_on] if not isinstance(left_on, (list, tuple)) else left_on
            rk = [right_on] if not isinstance(right_on, (list, tuple)) else right_on
            lkeys = [_to_expr(k) for k in lk]
            rkeys = [_to_expr(k) for k in rk]
        else:
            lkeys, rkeys = [], []
        return self._with(L.LogicalJoin(self._plan, other._plan, lkeys,
                                        rkeys, how, condition))

    def _using_join(self, other: "DataFrame", names: List[str], how: str,
                    condition) -> "DataFrame":
        from ..expr.conditional import Coalesce
        tmp = {n: f"__using_r_{n}" for n in names}
        rproj = other.select(*[col(n).alias(tmp[n]) if n in tmp else col(n)
                               for n in other.columns])
        joined = L.LogicalJoin(self._plan, rproj._plan,
                               [col(n) for n in names],
                               [col(tmp[n]) for n in names], how, condition)
        out: List[Expression] = []
        for n in names:
            if how == "right_outer":
                out.append(col(tmp[n]).alias(n))
            elif how == "full_outer":
                out.append(Coalesce(col(n), col(tmp[n])).alias(n))
            else:
                out.append(col(n))
        out += [col(n) for n in self.columns if n not in names]
        out += [col(n) for n in other.columns if n not in names]
        return self._with(L.LogicalProject(out, joined))

    def sort(self, *orders) -> "DataFrame":
        norm = []
        for o in orders:
            if isinstance(o, tuple):
                e = _to_expr(o[0])
                norm.append((e,) + tuple(o[1:]))
            else:
                norm.append((_to_expr(o), True))
        return self._with(L.LogicalSort(norm, self._plan))

    order_by = sort
    orderBy = sort

    def limit(self, n: int, offset: int = 0) -> "DataFrame":
        if isinstance(self._plan, L.LogicalSort) and self._plan.limit is None:
            # sort+limit collapses to TopN (reference GpuTopN, limit.scala:351)
            return self._with(L.LogicalSort(self._plan.orders,
                                            self._plan.children[0],
                                            limit=n, offset=offset))
        return self._with(L.LogicalLimit(n, self._plan, offset))

    def with_windows(self, *window_exprs) -> "DataFrame":
        """Append window-function columns: (WindowExpression, name) pairs
        (the pyspark F.xxx().over(w) surface)."""
        named = []
        for i, we in enumerate(window_exprs):
            if isinstance(we, tuple):
                named.append(we)
            else:
                named.append((we, f"{we.fn.name}_{i}"))
        return self._with(L.LogicalWindow(named, self._plan))

    def explode(self, column, alias: str = "col",
                outer: bool = False) -> "DataFrame":
        """One output row per array element; empty/null arrays drop the
        row (outer=True keeps it with a null element). PySpark's
        select(explode(c)) surface, keeping the other columns."""
        return self._with(L.LogicalGenerate(_to_expr(column), self._plan,
                                            outer=outer, elem_name=alias))

    def posexplode(self, column, alias: str = "col", pos_name: str = "pos",
                   outer: bool = False) -> "DataFrame":
        return self._with(L.LogicalGenerate(_to_expr(column), self._plan,
                                            outer=outer, position=True,
                                            elem_name=alias,
                                            pos_name=pos_name))

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._with(L.LogicalUnion(self._plan, other._plan))

    def distinct(self) -> "DataFrame":
        return self._with(L.LogicalAggregate(
            [col(n) for n in self.columns], [], self._plan))

    def repartition(self, n_partitions: int) -> "DataFrame":
        """Round-robin repartition through the host shuffle (Spark
        df.repartition(n); reference GpuRoundRobinPartitioning)."""
        return self._with(L.LogicalRepartition(n_partitions, self._plan,
                                               mode="roundrobin"))

    def coalesce(self, n_partitions: int = 1) -> "DataFrame":
        """Collapse to a single partition (Spark df.coalesce(1);
        reference GpuSinglePartitioning)."""
        assert n_partitions == 1, "only coalesce(1) is supported"
        return self._with(L.LogicalRepartition(1, self._plan,
                                               mode="single"))

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        """Bernoulli sample (Spark df.sample; reference GpuSampleExec)."""
        return self._with(L.LogicalSample(fraction, seed, self._plan))

    def cache(self) -> "DataFrame":
        """Materialize-once columnar cache (reference
        ParquetCachedBatchSerializer / GpuInMemoryTableScanExec): the
        first action on the returned frame runs this plan and stores
        compressed host frames; later actions re-scan the cache. Call
        `.unpersist()` on the returned frame to drop it."""
        from ..exec.cache import CachedRelation
        rel = CachedRelation(self._exec, self.schema)
        out = self._with(L.LogicalScan(rel))
        out._cached_relation = rel
        return out

    def unpersist(self) -> "DataFrame":
        rel = getattr(self, "_cached_relation", None)
        if rel is not None:
            rel.unpersist()
        return self

    # -- actions -----------------------------------------------------------
    def _exec(self):
        from .. import faults
        from ..columnar import upload
        from ..obs import dispatch as obs_dispatch
        from ..obs import events as obs_events
        from ..obs import history as obs_history
        from ..obs import telemetry
        from ..parallel.mesh import set_active_mesh
        set_active_conf(self.session.conf)
        set_active_mesh(self.session.mesh)
        obs_events.configure(self.session.conf)
        telemetry.configure(self.session.conf)
        obs_dispatch.configure(self.session.conf)
        obs_history.configure(self.session.conf)
        faults.configure(self.session.conf)
        upload.configure(self.session.conf)
        return TpuOverrides(self.session.conf).apply(self._plan)

    def collect(self) -> List[tuple]:
        """Materialize results, with task-level re-execution (ISSUE 4):
        a transient failure — an injected/real device error outside the
        OOM lane, a checksum-quarantined spill file or shuffle block, a
        dying IO path past its bounded retries — discards the attempt
        and re-runs the whole plan from the sources, up to
        spark.rapids.tpu.task.maxAttempts times. Every attempt rebuilds
        its exec tree in _collect_once, so attempts share no state.

        Lifecycle governor (ISSUE 6): the whole drive — including every
        retry attempt and its backoff — runs under one QueryContext, so
        spark.rapids.tpu.query.timeoutMs bounds the query's total
        wall-clock and TpuSession.cancel_query() can unwind it
        cooperatively from another thread.

        Workload governor (ISSUE 7): with
        spark.rapids.tpu.workload.enabled the query is admitted through
        the process-wide fair admission queue first — inside the
        governed scope, so the deadline spans queue wait and
        cancel_query() dequeues a queued query (phase admission-wait).
        A shed arrival (queue full / admission timeout / known-degraded
        device) raises QueryAdmissionError fast."""
        import time as _time

        from ..config import PHASES_ENABLED
        from ..exec import lifecycle, workload
        from ..exec.task_retry import with_task_retry
        from ..obs import history as obs_history
        from ..obs import phase as obs_phase
        with lifecycle.governed(self.session.conf,
                                owner=self.session._lifecycle_owner) as ctx:
            # wall-clock phase attribution (ISSUE 17): the ledger spans
            # the WHOLE governed drive — admission wait, every retry
            # attempt and its backoff — so sum(phases) == query wall
            if self.session.conf.get(PHASES_ENABLED):
                obs_phase.attach(ctx)
            # progress watchdog (ISSUE 20): armed only when
            # stall.timeoutMs > 0, after the ledger (its query_stalled
            # event reads the dominant phase mid-flight); stopped in
            # the same finally chain that closes the query books
            from ..exec import speculation_shield
            watchdog = speculation_shield.watchdog_for(
                ctx, self.session.conf)
            # history capsule (ISSUE 17): default-off = this one
            # pointer check; the counter snapshot is read only when a
            # store is actually installed
            store = obs_history.active_store()
            before = obs_history.process_counters() \
                if store is not None else None
            if store is not None:
                # a query failing before its harvest must not write the
                # PREVIOUS query's plan/metrics into its capsule
                self.session._last_query_metrics = None
                self.session._last_query_profile = None
            t0 = _time.perf_counter_ns()
            ok = False
            try:
                with workload.admitted(self.session.conf, ctx):
                    out = with_task_retry(
                        lambda attempt: self._collect_once(),
                        conf=self.session.conf)
                    ok = True
                    return out
            finally:
                if watchdog is not None:
                    watchdog.stop()
                self._finish_query(ctx, ok, store, before,
                                   _time.perf_counter_ns() - t0)

    def _finish_query(self, ctx, ok, store, before, fallback_wall_ns):
        """Query-end observability (ISSUE 17), inside collect's finally
        chain — close the phase ledger, emit the `query_phases` event,
        feed the SLO latency ring, append the history capsule. Must
        never raise (it would mask the query's real exception)."""
        from ..config import WORKLOAD_PRIORITY
        from ..exec.workload import PRIORITIES
        from ..obs import events as obs_events
        from ..obs import history as obs_history
        from ..obs import telemetry
        try:
            priority = str(self.session.conf.get(
                WORKLOAD_PRIORITY)).strip().lower()
            if priority not in PRIORITIES:
                priority = "interactive"
            ledger = getattr(ctx, "phase_ledger", None)
            phases = None
            wall_ns = fallback_wall_ns
            if ledger is not None:
                ledger.finish()
                wall_ns = ledger.wall_ns
                phases = ledger.snapshot()
                # events-plane id (the final attempt's query_scope),
                # NOT ctx.ctx_id: the two counters drift after any
                # retry, and the log must join on one id space
                obs_events.emit(
                    "query_phases",
                    query=getattr(ctx, "events_qid", None) or ctx.ctx_id,
                    ok=ok, wall_ns=wall_ns, attempts=ctx.attempt_no,
                    priority=priority, phases=phases)
            if ok:
                # only completed queries feed the SLO percentiles: a
                # shed/failed arrival returns in microseconds and would
                # drag p50 down, under-reporting real latency
                telemetry.note_query_latency(priority, wall_ns)
            if store is not None:
                profile = self.session._last_query_profile
                deltas = obs_history.counters_delta(
                    before, obs_history.process_counters())
                mesh = self.session.mesh
                store.append(obs_history.build_capsule(
                    query_id=ctx.ctx_id,
                    mesh_devices=int(mesh.devices.size)
                    if mesh is not None else 1,
                    fingerprint=getattr(profile, "fingerprint", None),
                    ok=ok, priority=priority, attempts=ctx.attempt_no,
                    wall_ns=wall_ns, phases=phases,
                    stats=ctx.runtime_stats,
                    summary=self.session._last_query_metrics,
                    deltas=deltas))
        except Exception:  # noqa: BLE001 — observability never masks
            pass

    def _collect_once(self) -> List[tuple]:
        import time as _time

        from ..exec import lifecycle
        from ..exec.task_metrics import query_snapshot, query_summary
        from ..obs import events as obs_events
        from ..obs.profile import QueryProfile
        from ..obs.stats import RuntimeStats
        with obs_events.query_scope() as qid:
            # conversion inside the scope: plan_fallback / plan_not_on_tpu
            # events must carry this query's id
            plan = self._exec()
            # runtime statistics + live progress (ISSUE 11): a fresh
            # RuntimeStats per attempt (a failed attempt's partial
            # distributions must not pollute the retry's), and the root
            # op id so note_batch counts only real query output
            ctx = lifecycle.current_context()
            stats = RuntimeStats()
            if ctx is not None:
                ctx.runtime_stats = stats
                ctx.root_op_id = plan._op_id
                # query_phases (emitted after the scope closes) must
                # carry the same id as this attempt's query_start/
                # query_end so the event log joins per query
                ctx.events_qid = qid
            before = query_snapshot()
            obs_events.emit("query_start", root=type(plan).__name__)
            t0 = _time.perf_counter_ns()
            ok = False
            try:
                out = plan.collect()
                ok = True
                return out
            finally:
                # metrics are harvested even on failure: a half-run
                # query's spill/retry spend is exactly what an operator
                # debugging it wants to see
                try:
                    summary = query_summary(plan, before)
                    self.session._last_query_metrics = summary
                    self.session._last_query_profile = QueryProfile(
                        plan, summary, statistics=stats,
                        phases=ctx.phase_ledger
                        if ctx is not None else None)
                except Exception:  # noqa: BLE001 — must never mask
                    pass
                obs_events.emit(
                    "query_end", root=type(plan).__name__, ok=ok,
                    wall_ns=_time.perf_counter_ns() - t0)

    def to_arrow(self):
        import pyarrow as pa
        tables = [b.to_arrow() for b in self._exec().execute()]
        if not tables:
            from ..types import to_arrow as t2a
            return pa.table({f.name: pa.array([], t2a(f.data_type))
                             for f in self.schema.fields})
        return pa.concat_tables(tables)

    def to_pydict(self) -> Dict:
        t = self.to_arrow()
        return {name: t.column(name).to_pylist() for name in t.column_names}

    def to_jax(self) -> Dict:
        """ML handoff (reference ColumnarRdd / spark-rapids-ml bridge):
        materialize the query DEVICE-RESIDENT as a dict of
        {name: (data, validity)} jnp arrays, trimmed to the row count —
        zero host round trip, ready to feed a JAX model. Fixed-width
        columns only (strings need tokenization first)."""
        batches = list(self._exec().execute())
        out: Dict = {}
        from ..exec.coalesce import concat_batches
        from ..columnar.batch import empty_batch
        if not batches:
            merged = empty_batch(self.schema)
        elif len(batches) == 1:
            merged = batches[0]
        else:
            merged = concat_batches(batches, self.schema)
        n = merged.num_rows_host
        for f, c in zip(self.schema.fields, merged.columns):
            assert f.data_type.is_fixed_width, \
                f"to_jax needs fixed-width columns, {f.name} is " \
                f"{f.data_type.simple_name()}"
            out[f.name] = (c.data[:n], c.validity[:n])
        return out

    def count(self) -> int:
        from ..expr.aggexprs import Count
        rows = self._with(L.LogicalAggregate([], [(Count(), "count")],
                                             self._plan)).collect()
        return rows[0][0]

    def explain(self) -> str:
        return TpuOverrides(self.session.conf).explain(self._plan)

    def logical_plan(self) -> L.LogicalPlan:
        return self._plan

    def write_parquet(self, path, partition_by: Optional[Sequence[str]] = None):
        from ..io.parquet import write_parquet
        write_parquet(self, path, partition_by=partition_by)

    def write_csv(self, path, header: bool = True, delimiter: str = ","):
        from ..io.csv import write_csv
        write_csv(self, path, header=header, delimiter=delimiter)

    def write_json(self, path):
        from ..io.json import write_json
        write_json(self, path)

    def write_orc(self, path):
        from ..io.orc import write_orc
        write_orc(self, path)

    def write_avro(self, path, codec: str = "deflate"):
        from ..io.avro import write_avro
        write_avro(self, path, codec=codec)

    def write_delta(self, path, mode: str = "append",
                    partition_by: Optional[Sequence[str]] = None):
        from ..delta import write_delta
        write_delta(self, path, mode=mode, partition_by=partition_by)

    def write_iceberg(self, path, mode: str = "append"):
        from ..io.iceberg import write_iceberg
        write_iceberg(self, path, mode=mode)

    def write_hive_text(self, path, **options):
        from ..io.hivetext import write_hive_text
        write_hive_text(self, path, **options)

    def _with(self, plan: L.LogicalPlan) -> "DataFrame":
        return DataFrame(plan, self.session)


def _to_schema(schema) -> Schema:
    assert isinstance(schema, Schema), \
        "pandas UDF output schema must be a Schema"
    return schema


def _named_pandas_fns(specs):
    """Normalize (fn, name, result_type, inputs...) pandas-UDF specs: the
    inputs may be varargs or one list/tuple."""
    named = []
    for fn, name, rt, *ins in specs:
        exprs = [_to_expr(e) for e in
                 (ins[0] if len(ins) == 1
                  and isinstance(ins[0], (list, tuple)) else ins)]
        named.append((fn, name, rt, exprs))
    return named


class CoGroupedData:
    def __init__(self, left: "GroupedData", right: "GroupedData"):
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(left_group_df, right_group_df) -> DataFrame per key in
        either input (reference GpuFlatMapCoGroupsInPandasExec)."""
        return self.left.df._with(L.LogicalCoGroupedMapInPandas(
            self.left.keys, self.right.keys, fn, _to_schema(schema),
            self.left.df._plan, self.right.df._plan))

    applyInPandas = apply_in_pandas


class GroupedData:
    def __init__(self, keys: List[Expression], df: DataFrame):
        self.keys = keys
        self.df = df

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """Spark df.groupBy(...).applyInPandas(fn, schema): fn receives
        each group as a pandas DataFrame and returns a DataFrame matching
        `schema` (reference GpuFlatMapGroupsInPandasExec.scala:79)."""
        return self.df._with(L.LogicalGroupedMapInPandas(
            self.keys, fn, _to_schema(schema), self.df._plan))

    applyInPandas = apply_in_pandas

    def agg_in_pandas(self, *aggs) -> DataFrame:
        """Grouped pandas aggregates: each agg is (fn, name, result_type,
        input columns/exprs...); fn receives one pandas Series per input
        and returns a scalar (reference GpuAggregateInPandasExec)."""
        key_names = [getattr(k, "name", f"key_{i}")
                     for i, k in enumerate(self.keys)]
        return self.df._with(L.LogicalAggregateInPandas(
            self.keys, key_names, _named_pandas_fns(aggs), self.df._plan))

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """Spark df.groupBy(k).cogroup(other.groupBy(k))."""
        return CoGroupedData(self, other)

    def agg(self, *aggs) -> DataFrame:
        named: List[Tuple[AggregateFunction, str]] = []
        for i, a in enumerate(aggs):
            if isinstance(a, tuple):
                named.append(a)
            else:
                assert isinstance(a, AggregateFunction), a
                default = f"{a.name}({', '.join(map(repr, a.inputs))})" \
                    if a.inputs else f"{a.name}(*)"
                named.append((a, default))
        return self.df._with(L.LogicalAggregate(self.keys, named,
                                                self.df._plan))
