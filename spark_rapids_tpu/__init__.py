"""spark_rapids_tpu — a TPU-native columnar SQL execution engine.

A from-scratch rebuild of the capabilities of the RAPIDS Accelerator for
Apache Spark (reference: binmahone/spark-rapids), designed TPU-first:

  * compute path: JAX/XLA programs + Pallas kernels over device-resident
    Arrow-like columns (static capacity buckets, device row counts);
  * scale-out: jax.sharding Mesh + shard_map with ICI collectives replacing
    the reference's UCX/NVLink shuffle transport;
  * memory: HBM budget manager with host/disk spill tiers and a
    retry/split-retry discipline mirroring the reference's RMM-based
    RmmRapidsRetryIterator contract;
  * planning: declarative override rule tables (wrap -> tag -> convert)
    mirroring GpuOverrides/RapidsMeta, operating on this engine's logical
    plans.

Spark-semantics fidelity (LongType/DoubleType/Decimal/hash parity) requires
64-bit lanes, so x64 mode is enabled at import — TPUs emulate i64/f64; hot
kernels deliberately stay in 32-bit lanes where Spark semantics allow.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

from . import types  # noqa: E402
from .columnar.column import Column, StringColumn, bucket_capacity  # noqa: E402
from .columnar.batch import ColumnarBatch  # noqa: E402
# the error taxonomy is public API: callers catching engine failures
# distinguish the OOM lane (memory.retry.TpuOOMError) from transient
# task-lane failures and integrity quarantines (docs/robustness.md)
from .faults import IntegrityError, TpuTaskRetryError  # noqa: E402
# a deadline-expired or user-cancelled governed query unwinds with this
# (exec/lifecycle.py; TpuSession.cancel_query / query.timeoutMs)
from .exec.lifecycle import QueryCancelledError  # noqa: E402
# the workload governor refused to start the query (queue full /
# admission timeout / known-degraded device) — carries reason and a
# retry_after_ms hint (exec/workload.py; spark.rapids.tpu.workload.*)
from .exec.workload import QueryAdmissionError  # noqa: E402
from .version import __version__  # noqa: E402
