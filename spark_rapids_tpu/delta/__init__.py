"""Delta Lake integration — one modern protocol version, as SURVEY §7
phase 9 prescribes (the reference ships nine per-version modules under
/root/reference/delta-lake/; this package is the analog of delta-24x +
delta-lake/common: GpuOptimisticTransaction.scala, GpuDeltaCatalog,
GpuMergeIntoCommand.scala, GpuStatisticsCollection.scala).

Self-contained: the transaction log (JSON actions + parquet checkpoints),
snapshot reconstruction, stats-collecting writes, and the copy-on-write
DELETE/UPDATE/MERGE commands are implemented here directly against the
engine — no delta-spark dependency.
"""

from .log import DeltaLog, Snapshot
from .table import DeltaTable, read_delta, write_delta

__all__ = ["DeltaLog", "Snapshot", "DeltaTable", "read_delta",
           "write_delta"]
