"""Delta transaction log: JSON commit files + parquet checkpoints +
snapshot reconstruction (the reference rides delta-core's Snapshot and
wraps commits in GpuOptimisticTransaction, delta-24x
GpuOptimisticTransaction.scala; this engine owns the log layer itself).

Log protocol (delta protocol spec, reader version 1 / writer version 2):
    <table>/_delta_log/00000000000000000000.json     one JSON action/line
    <table>/_delta_log/<v>.checkpoint.parquet        optional, actions
    <table>/_delta_log/_last_checkpoint              {"version": v, ...}

Actions handled: metaData, add, remove, protocol, commitInfo, txn.
Commits are atomic via O_EXCL create of the next version file — the same
filesystem contract delta's HDFSLogStore relies on; a concurrent writer
losing the race gets DeltaConcurrentModificationException and replays
(optimistic concurrency).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..types import (ArrayType, BinaryType, BooleanType, ByteType, DataType,
                     DateType, DecimalType, DoubleType, FloatType,
                     IntegerType, LongType, Schema, ShortType, StringType,
                     StructField, StructType, TimestampNTZType,
                     TimestampType)

CHECKPOINT_INTERVAL = 10


class DeltaConcurrentModificationException(Exception):
    pass


# ---------------------------------------------------------------------------
# Spark schema JSON <-> engine types (delta stores the Spark JSON format)
# ---------------------------------------------------------------------------

_PRIM = {
    "long": LongType(), "integer": IntegerType(), "short": ShortType(),
    "byte": ByteType(), "double": DoubleType(), "float": FloatType(),
    "boolean": BooleanType(), "string": StringType(),
    "binary": BinaryType(), "date": DateType(),
    "timestamp": TimestampType(), "timestamp_ntz": TimestampNTZType(),
}


def type_from_json(t) -> DataType:
    if isinstance(t, str):
        if t in _PRIM:
            return _PRIM[t]
        if t.startswith("decimal("):
            p, s = t[8:-1].split(",")
            return DecimalType(int(p), int(s))
        raise ValueError(f"unsupported delta type {t!r}")
    if t.get("type") == "struct":
        return StructType(tuple(
            StructField(f["name"], type_from_json(f["type"]),
                        f.get("nullable", True))
            for f in t["fields"]))
    if t.get("type") == "array":
        return ArrayType(type_from_json(t["elementType"]))
    raise ValueError(f"unsupported delta type {t!r}")


def type_to_json(dt: DataType):
    for name, t in _PRIM.items():
        if type(t) is type(dt):
            return name
    if isinstance(dt, DecimalType):
        return f"decimal({dt.precision},{dt.scale})"
    if isinstance(dt, StructType):
        return {"type": "struct", "fields": [
            {"name": f.name, "type": type_to_json(f.data_type),
             "nullable": f.nullable, "metadata": {}}
            for f in dt.fields]}
    if isinstance(dt, ArrayType):
        return {"type": "array", "elementType": type_to_json(dt.element_type),
                "containsNull": True}
    raise ValueError(f"unsupported type {dt!r}")


def schema_to_json(schema: Schema) -> str:
    return json.dumps({"type": "struct", "fields": [
        {"name": f.name, "type": type_to_json(f.data_type),
         "nullable": f.nullable, "metadata": {}} for f in schema.fields]})


def schema_from_json(s: str) -> Schema:
    st = type_from_json(json.loads(s))
    return Schema(tuple(st.fields))


# ---------------------------------------------------------------------------
# snapshot
# ---------------------------------------------------------------------------

class AddFile:
    __slots__ = ("path", "partition_values", "size", "stats",
                 "modification_time")

    def __init__(self, path: str, partition_values: Dict[str, str],
                 size: int, stats: Optional[str] = None,
                 modification_time: int = 0):
        self.path = path
        self.partition_values = partition_values or {}
        self.size = size
        self.stats = stats
        self.modification_time = modification_time

    def to_action(self, data_change: bool = True) -> dict:
        return {"add": {
            "path": self.path, "partitionValues": self.partition_values,
            "size": self.size, "modificationTime": self.modification_time,
            "dataChange": data_change,
            **({"stats": self.stats} if self.stats else {})}}

    def parsed_stats(self) -> Optional[dict]:
        if not self.stats:
            return None
        try:
            return json.loads(self.stats)
        except ValueError:
            return None


class Snapshot:
    def __init__(self, version: int, schema: Schema,
                 partition_columns: List[str], files: List[AddFile],
                 metadata: dict):
        self.version = version
        self.schema = schema
        self.partition_columns = partition_columns
        self.files = files
        self.metadata = metadata


class DeltaLog:
    """One table's _delta_log directory."""

    def __init__(self, table_path: str):
        self.table_path = os.path.abspath(table_path)
        self.log_path = os.path.join(self.table_path, "_delta_log")
        self._lock = threading.Lock()

    # -- paths -------------------------------------------------------------
    def _version_file(self, v: int) -> str:
        return os.path.join(self.log_path, f"{v:020d}.json")

    def _checkpoint_file(self, v: int) -> str:
        return os.path.join(self.log_path, f"{v:020d}.checkpoint.parquet")

    def exists(self) -> bool:
        return os.path.isdir(self.log_path) and (
            os.path.exists(self._version_file(0))
            or self.last_checkpoint() is not None)

    def latest_version(self) -> int:
        if not os.path.isdir(self.log_path):
            return -1
        best = -1
        for n in os.listdir(self.log_path):
            if n.endswith(".json") and n[:20].isdigit():
                best = max(best, int(n[:20]))
        return best

    def last_checkpoint(self) -> Optional[int]:
        p = os.path.join(self.log_path, "_last_checkpoint")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return int(json.load(f)["version"])

    # -- replay ------------------------------------------------------------
    def _read_version_actions(self, v: int) -> Iterator[dict]:
        with open(self._version_file(v)) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)

    def _read_checkpoint(self, v: int) -> Iterator[dict]:
        import pyarrow.parquet as pq
        table = pq.read_table(self._checkpoint_file(v))
        if "action" in table.column_names:
            # pre-round-3 layout of this engine: one JSON action per row
            for s in table.column("action").to_pylist():
                yield json.loads(s)
            return
        for row in table.to_pylist():
            # delta-spark checkpoint: one struct column per action type;
            # arrow map<string,string> cells surface as [(k, v), ...]
            for key in ("metaData", "add", "remove", "protocol", "txn"):
                val = row.get(key)
                if val is not None:
                    yield {key: _strip_nones(_maps_to_dicts(val))}

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.latest_version()
        if latest < 0 and self.last_checkpoint() is None:
            raise FileNotFoundError(
                f"{self.table_path!r} is not a delta table")
        target = latest if version is None else version
        start = 0
        actions: List[dict] = []
        cp = self.last_checkpoint()
        if cp is not None and cp <= target \
                and os.path.exists(self._checkpoint_file(cp)):
            actions.extend(self._read_checkpoint(cp))
            start = cp + 1
        for v in range(start, target + 1):
            if not os.path.exists(self._version_file(v)):
                raise FileNotFoundError(
                    f"missing delta log version {v} for {self.table_path!r}")
            actions.extend(self._read_version_actions(v))

        schema: Optional[Schema] = None
        part_cols: List[str] = []
        metadata: dict = {}
        adds: Dict[str, AddFile] = {}
        for a in actions:
            if "metaData" in a:
                md = a["metaData"]
                metadata = md
                schema = schema_from_json(md["schemaString"])
                part_cols = list(md.get("partitionColumns", []))
            elif "add" in a:
                ad = a["add"]
                adds[ad["path"]] = AddFile(
                    ad["path"], dict(ad.get("partitionValues") or {}),
                    ad.get("size", 0), ad.get("stats"),
                    ad.get("modificationTime", 0))
            elif "remove" in a:
                adds.pop(a["remove"]["path"], None)
        if schema is None:
            raise ValueError(f"no metaData action in {self.table_path!r}")
        return Snapshot(target, schema, part_cols, list(adds.values()),
                        metadata)

    # -- commit ------------------------------------------------------------
    def commit(self, actions: List[dict], expected_version: int) -> int:
        """Atomically write version `expected_version`; raises
        DeltaConcurrentModificationException if another writer won."""
        os.makedirs(self.log_path, exist_ok=True)
        path = self._version_file(expected_version)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            raise DeltaConcurrentModificationException(
                f"version {expected_version} was committed concurrently")
        with os.fdopen(fd, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")
        if expected_version > 0 \
                and expected_version % CHECKPOINT_INTERVAL == 0:
            self._write_checkpoint(expected_version)
        return expected_version

    def _write_checkpoint(self, v: int) -> None:
        """Write the Delta-protocol struct-typed checkpoint: one parquet
        file with nullable `protocol`/`metaData`/`add` struct columns, one
        action per row (delta-spark's classic checkpoint layout), so an
        external delta reader can load the table past the checkpoint.
        Reference behavior: delta-core Checkpoints.writeCheckpoint used via
        /root/reference/delta-lake (GpuOptimisticTransaction commits)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        snap = self.snapshot(v)
        strmap = pa.map_(pa.string(), pa.string())
        protocol_t = pa.struct([
            ("minReaderVersion", pa.int32()),
            ("minWriterVersion", pa.int32())])
        metadata_t = pa.struct([
            ("id", pa.string()),
            ("name", pa.string()),
            ("description", pa.string()),
            ("format", pa.struct([("provider", pa.string()),
                                  ("options", strmap)])),
            ("schemaString", pa.string()),
            ("partitionColumns", pa.list_(pa.string())),
            ("configuration", strmap),
            ("createdTime", pa.int64())])
        add_t = pa.struct([
            ("path", pa.string()),
            ("partitionValues", strmap),
            ("size", pa.int64()),
            ("modificationTime", pa.int64()),
            ("dataChange", pa.bool_()),
            ("stats", pa.string())])
        md = dict(snap.metadata)
        fmt = md.get("format") or {}
        md_row = {
            "id": md.get("id"),
            "name": md.get("name"),
            "description": md.get("description"),
            "format": {"provider": fmt.get("provider", "parquet"),
                       "options": dict(fmt.get("options") or {})},
            "schemaString": md.get("schemaString"),
            "partitionColumns": list(md.get("partitionColumns") or []),
            "configuration": dict(md.get("configuration") or {}),
            "createdTime": md.get("createdTime")}
        proto = self.protocol_action()["protocol"]
        n_actions = 2 + len(snap.files)
        protocol_col = [proto] + [None] * (n_actions - 1)
        metadata_col = [None, md_row] + [None] * len(snap.files)
        add_col: List[Optional[dict]] = [None, None]
        for f in snap.files:
            add_col.append({
                "path": f.path,
                "partitionValues": dict(f.partition_values or {}),
                "size": f.size,
                "modificationTime": f.modification_time,
                "dataChange": False,
                "stats": f.stats})
        table = pa.table({
            "protocol": pa.array(protocol_col, protocol_t),
            "metaData": pa.array(metadata_col, metadata_t),
            "add": pa.array(add_col, add_t)})
        pq.write_table(table, self._checkpoint_file(v))
        with open(os.path.join(self.log_path, "_last_checkpoint"),
                  "w") as f:
            json.dump({"version": v, "size": n_actions}, f)

    def metadata_action(self, schema: Schema, partition_columns: List[str],
                        table_id: str) -> dict:
        return {"metaData": {
            "id": table_id,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": schema_to_json(schema),
            "partitionColumns": partition_columns,
            "configuration": {},
            "createdTime": int(time.time() * 1000)}}

    @staticmethod
    def protocol_action() -> dict:
        return {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}}

    @staticmethod
    def commit_info(operation: str, **params) -> dict:
        return {"commitInfo": {
            "timestamp": int(time.time() * 1000),
            "operation": operation,
            "operationParameters": {k: str(v) for k, v in params.items()},
            "engineInfo": "spark-rapids-tpu"}}


def _strip_nones(d: dict) -> dict:
    return {k: v for k, v in d.items() if v is not None}


def _maps_to_dicts(v):
    """Recursively turn arrow map cells ([(k, v), ...]) into dicts."""
    if isinstance(v, dict):
        return {k: _maps_to_dicts(x) for k, x in v.items()}
    if isinstance(v, list):
        if v and all(isinstance(e, tuple) and len(e) == 2 for e in v):
            return {k: _maps_to_dicts(x) for k, x in v}
        return [_maps_to_dicts(e) for e in v]
    return v
