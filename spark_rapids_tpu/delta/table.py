"""Delta table scan + write + DML commands (reference delta-24x:
GpuDeltaCatalog / GpuOptimisticTransaction.scala for writes with stats
collection, GpuDeleteCommand / GpuUpdateCommand / GpuMergeIntoCommand.scala
for copy-on-write DML — all re-expressed over this engine's DataFrame
planner instead of delta-spark).

Scan: snapshot files → per-file parquet reads with partition values
injected as columns; file skipping uses partition values and the add
actions' min/max/nullCount stats through the same `with_filters` hook the
planner uses for parquet pushdown, so `filter(...)` over a delta scan
prunes whole files (the reference's data-skipping via
GpuStatisticsCollection).

DML is copy-on-write: only files containing affected rows are rewritten;
commits are optimistic (DeltaConcurrentModificationException on a lost
race).
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..columnar.column import Column, StringColumn
from ..config import RapidsConf
from ..expr.core import Expression, UnresolvedAttribute, lit
from ..expr.predicates import EqualNullSafe, IsNotNull, Not
from ..types import (BooleanType, DataType, DateType, DoubleType, FloatType,
                     IntegerType, LongType, Schema, ShortType, StringType,
                     StructField, TimestampType)
from .log import AddFile, DeltaLog, Snapshot, schema_to_json

_MARKER = "__delta_src_match"


def _parse_partition_value(raw: Optional[str], dt: DataType):
    if raw is None:
        return None
    if isinstance(dt, (IntegerType, LongType, ShortType)):
        return int(raw)
    if isinstance(dt, (DoubleType, FloatType)):
        return float(raw)
    if isinstance(dt, BooleanType):
        return raw.lower() == "true"
    if isinstance(dt, DateType):
        import datetime as _dt
        return (_dt.date.fromisoformat(raw)
                - _dt.date(1970, 1, 1)).days
    return raw  # string


def _fmt_partition_value(v, dt: DataType) -> Optional[str]:
    if v is None:
        return None
    if isinstance(dt, DateType):
        import datetime as _dt
        return (_dt.date(1970, 1, 1) + _dt.timedelta(days=int(v))
                ).isoformat()
    if isinstance(dt, BooleanType):
        return "true" if v else "false"
    return str(v)


class DeltaSource:
    """Scan source over one snapshot (plugs into LogicalScan; the planner
    pushes filter conjuncts through `with_filters` for file skipping)."""

    def __init__(self, log: DeltaLog, snapshot: Snapshot,
                 conf: Optional[RapidsConf] = None,
                 filters: Optional[Sequence[Tuple[str, str, object]]] = None,
                 files: Optional[List[AddFile]] = None):
        self.log = log
        self.snap = snapshot
        self.schema = snapshot.schema
        self._conf = conf
        self.filters = list(filters or [])
        self._files = files  # explicit file subset (DML rewrites)
        self.scan_stats = {"files_read": 0, "files_pruned": 0}

    def with_filters(self, filters) -> "DeltaSource":
        out = DeltaSource(self.log, self.snap, self._conf,
                          list(self.filters) + list(filters), self._files)
        out.scan_stats = self.scan_stats
        return out

    def estimated_size_bytes(self) -> int:
        return sum(f.size for f in (self._files or self.snap.files))

    # -- file skipping -----------------------------------------------------
    def _file_pruned(self, f: AddFile) -> bool:
        part_cols = set(self.snap.partition_columns)
        stats = f.parsed_stats()
        for (name, op, value) in self.filters:
            if name in part_cols:
                dt = self.schema.fields[self.schema.index_of(name)].data_type
                pv = _parse_partition_value(
                    f.partition_values.get(name), dt)
                if op == "is_null":
                    if pv is not None:
                        return True
                elif op == "is_not_null":
                    if pv is None:
                        return True
                elif pv is None:
                    return True  # comparison with NULL partition never true
                elif op == "==" and pv != value:
                    return True
                elif op == "<" and not (pv < value):
                    return True
                elif op == "<=" and not (pv <= value):
                    return True
                elif op == ">" and not (pv > value):
                    return True
                elif op == ">=" and not (pv >= value):
                    return True
            elif stats:
                mn = (stats.get("minValues") or {}).get(name)
                mx = (stats.get("maxValues") or {}).get(name)
                nc = (stats.get("nullCount") or {}).get(name)
                nr = stats.get("numRecords")
                if op == "is_null" and nc == 0:
                    return True
                if op == "is_not_null" and nc is not None \
                        and nc == nr:
                    return True
                if mn is None or mx is None:
                    continue
                try:
                    if op == "==" and (value < mn or value > mx):
                        return True
                    if op == "<" and mn >= value:
                        return True
                    if op == "<=" and mn > value:
                        return True
                    if op == ">" and mx <= value:
                        return True
                    if op == ">=" and mx < value:
                        return True
                except TypeError:
                    continue
        return False

    # -- scan --------------------------------------------------------------
    def files_after_skipping(self) -> List[AddFile]:
        out = []
        self.scan_stats["files_read"] = 0
        self.scan_stats["files_pruned"] = 0
        for f in (self._files if self._files is not None
                  else self.snap.files):
            if self.filters and self._file_pruned(f):
                self.scan_stats["files_pruned"] += 1
                continue
            self.scan_stats["files_read"] += 1
            out.append(f)
        return out

    def batches(self) -> Iterator[ColumnarBatch]:
        for f in self.files_after_skipping():
            yield from self._read_file(f)

    def _read_file(self, f: AddFile) -> Iterator[ColumnarBatch]:
        from ..io.parquet import ParquetSource
        path = os.path.join(self.log.table_path, f.path)
        part_cols = self.snap.partition_columns
        data_cols = [c for c in self.schema.names if c not in part_cols]
        src = ParquetSource(path, self._conf, columns=data_cols,
                            filters=[flt for flt in self.filters
                                     if flt[0] in data_cols])
        for b in src.batches():
            cols: List[Column] = []
            for fld in self.schema.fields:
                if fld.name in part_cols:
                    dt = fld.data_type
                    v = _parse_partition_value(
                        f.partition_values.get(fld.name), dt)
                    n = b.num_rows_host
                    if isinstance(dt, StringType):
                        col = StringColumn.from_pylist(
                            [v] * n, capacity=b.capacity)
                    else:
                        col = Column.from_pylist([v] * n, dt,
                                                 capacity=b.capacity)
                    cols.append(col)
                else:
                    cols.append(b.column(fld.name))
            yield ColumnarBatch(cols, b.num_rows_host, self.schema)


# ---------------------------------------------------------------------------
# write path with stats collection
# ---------------------------------------------------------------------------

def _collect_stats(table) -> str:
    """Per-file stats JSON from a pyarrow table (reference
    GpuStatisticsCollection: numRecords/min/max/nullCount drive data
    skipping on later reads)."""
    import pyarrow.compute as pc
    mins: Dict[str, object] = {}
    maxs: Dict[str, object] = {}
    nulls: Dict[str, int] = {}
    for name in table.column_names:
        col = table.column(name)
        nulls[name] = col.null_count
        if col.length() - col.null_count == 0:
            continue
        try:
            mn = pc.min(col).as_py()
            mx = pc.max(col).as_py()
        except Exception:
            continue
        import datetime as _dt
        for tag, v in (("mn", mn), ("mx", mx)):
            if isinstance(v, _dt.datetime):
                v = v.isoformat()
            elif isinstance(v, _dt.date):
                v = v.isoformat()
            elif isinstance(v, bytes):
                continue
            (mins if tag == "mn" else maxs)[name] = v
    return json.dumps({"numRecords": table.num_rows, "minValues": mins,
                       "maxValues": maxs, "nullCount": nulls})


def _write_data_files(df, table_path: str, partition_by: List[str]
                      ) -> List[AddFile]:
    """Materialize a DataFrame into parquet data files + AddFile actions
    (one file per partition tuple, or one file total)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    table = df.to_arrow()
    adds: List[AddFile] = []

    def write_one(sub, rel_dir: str, pvals: Dict[str, str]):
        if sub.num_rows == 0:
            return
        name = f"part-{uuid.uuid4().hex}.snappy.parquet"
        rel = os.path.join(rel_dir, name) if rel_dir else name
        full = os.path.join(table_path, rel)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        pq.write_table(sub, full)
        adds.append(AddFile(rel.replace(os.sep, "/"), pvals,
                            os.path.getsize(full), _collect_stats(sub),
                            int(os.path.getmtime(full) * 1000)))

    if not partition_by:
        write_one(table, "", {})
        return adds

    schema = df.schema
    # group rows by partition tuple host-side
    pcols = [table.column(c).to_pylist() for c in partition_by]
    data_cols = [c for c in table.column_names if c not in partition_by]
    groups: Dict[tuple, List[int]] = {}
    for i, key in enumerate(zip(*pcols)):
        groups.setdefault(key, []).append(i)
    for key, idxs in groups.items():
        sub = table.take(idxs).select(data_cols)
        pvals = {}
        parts = []
        for c, v in zip(partition_by, key):
            dt = schema.fields[schema.index_of(c)].data_type
            # arrow gives logical values; normalize to delta's string form
            import datetime as _dt
            if isinstance(v, _dt.date):
                sv = v.isoformat()
            elif v is None:
                sv = None
            else:
                sv = _fmt_partition_value(v, dt) \
                    if not isinstance(v, str) else v
            pvals[c] = sv
            parts.append(f"{c}={'__HIVE_DEFAULT_PARTITION__' if sv is None else sv}")
        write_one(sub, os.path.join(*parts), pvals)
    return adds


def write_delta(df, path: str, mode: str = "append",
                partition_by: Optional[Sequence[str]] = None) -> None:
    """DataFrame → delta table (append / overwrite / error-if-exists
    semantics of Spark's DataFrameWriter)."""
    log = DeltaLog(path)
    partition_by = list(partition_by or [])
    exists = log.exists()
    if mode == "error" and exists:
        raise FileExistsError(f"delta table {path!r} already exists")
    os.makedirs(path, exist_ok=True)
    adds = _write_data_files(df, log.table_path, partition_by)
    actions: List[dict] = [DeltaLog.commit_info(
        "WRITE", mode=mode, partitionBy=json.dumps(partition_by))]
    if not exists:
        actions.append(DeltaLog.protocol_action())
        actions.append(log.metadata_action(df.schema, partition_by,
                                           str(uuid.uuid4())))
        version = 0
    else:
        snap = log.snapshot()
        if snap.schema.names != df.schema.names:
            raise ValueError(
                f"schema mismatch: table {snap.schema.names} "
                f"vs data {df.schema.names}")
        version = snap.version + 1
        if mode == "overwrite":
            for f in snap.files:
                actions.append({"remove": {
                    "path": f.path, "dataChange": True,
                    "deletionTimestamp": 0}})
    actions.extend(a.to_action() for a in adds)
    log.commit(actions, version)


def read_delta(session, path: str, version: Optional[int] = None):
    from ..plan import logical as L
    log = DeltaLog(path)
    snap = log.snapshot(version)
    return session._df(L.LogicalScan(DeltaSource(log, snap, session.conf)))


# ---------------------------------------------------------------------------
# DML commands (copy-on-write)
# ---------------------------------------------------------------------------

class DeltaTable:
    """DML entry point (reference GpuDeleteCommand / GpuUpdateCommand /
    GpuMergeIntoCommand)."""

    def __init__(self, session, path: str):
        self.session = session
        self.log = DeltaLog(path)

    @staticmethod
    def for_path(session, path: str) -> "DeltaTable":
        return DeltaTable(session, path)

    def to_df(self):
        return read_delta(self.session, self.log.table_path)

    def history(self) -> List[dict]:
        out = []
        for v in range(self.log.latest_version() + 1):
            for a in self.log._read_version_actions(v):
                if "commitInfo" in a:
                    out.append({"version": v, **a["commitInfo"]})
        return out

    # -- shared rewrite machinery -----------------------------------------
    def _file_df(self, snap: Snapshot, f: AddFile):
        from ..plan import logical as L
        src = DeltaSource(self.log, snap, self.session.conf, files=[f])
        return self.session._df(L.LogicalScan(src))

    def _rewrite(self, snap: Snapshot, f: AddFile, new_df
                 ) -> List[dict]:
        """remove old file + add rewritten rows (partition kept)."""
        actions = [{"remove": {"path": f.path, "dataChange": True,
                               "deletionTimestamp": 0}}]
        part_cols = snap.partition_columns
        rel_dir = os.path.dirname(f.path)
        import pyarrow.parquet as pq
        table = new_df.to_arrow()
        if table.num_rows:
            data_cols = [c for c in table.column_names
                         if c not in part_cols]
            sub = table.select(data_cols)
            name = f"part-{uuid.uuid4().hex}.snappy.parquet"
            rel = os.path.join(rel_dir, name) if rel_dir else name
            full = os.path.join(self.log.table_path, rel)
            os.makedirs(os.path.dirname(full), exist_ok=True)
            pq.write_table(sub, full)
            actions.append(AddFile(
                rel.replace(os.sep, "/"), f.partition_values,
                os.path.getsize(full), _collect_stats(sub),
                int(os.path.getmtime(full) * 1000)).to_action())
        return actions

    def _matching_files(self, snap: Snapshot, condition: Expression
                        ) -> List[AddFile]:
        """Candidate files via the same skipping stats the scan uses."""
        from ..plan.overrides import extract_pushable_filters
        src = DeltaSource(self.log, snap, self.session.conf)
        pushed = extract_pushable_filters(condition, snap.schema)
        if pushed:
            src = src.with_filters(pushed)
        return src.files_after_skipping()

    # -- DELETE ------------------------------------------------------------
    def delete(self, condition) -> int:
        """DELETE FROM t WHERE cond (reference GpuDeleteCommand): rows
        where cond is TRUE are removed; NULL/false rows stay."""
        from ..api.session import _to_expr
        cond = _to_expr(condition)
        snap = self.log.snapshot()
        keep = Not(EqualNullSafe(cond, lit(True)))
        actions: List[dict] = [DeltaLog.commit_info("DELETE")]
        deleted = 0
        for f in self._matching_files(snap, cond):
            file_df = self._file_df(snap, f)
            total = file_df.count()
            kept_df = self._file_df(snap, f).filter(keep)
            kept = kept_df.count()
            if kept == total:
                continue
            deleted += total - kept
            actions.extend(self._rewrite(snap, f, kept_df))
        if len(actions) > 1:
            self.log.commit(actions, snap.version + 1)
        return deleted

    # -- UPDATE ------------------------------------------------------------
    def update(self, set: Dict[str, object], condition=None) -> int:
        """UPDATE t SET col=expr [WHERE cond] (reference
        GpuUpdateCommand)."""
        from ..api.functions import col
        from ..api.session import _to_expr
        from ..expr.conditional import If
        cond = _to_expr(condition) if condition is not None else lit(True)
        sets = {k: _to_expr(v) for k, v in set.items()}
        snap = self.log.snapshot()
        is_match = EqualNullSafe(cond, lit(True))
        actions: List[dict] = [DeltaLog.commit_info("UPDATE")]
        updated = 0
        for f in self._matching_files(snap, cond):
            file_df = self._file_df(snap, f)
            n_match = file_df.filter(is_match).count()
            if n_match == 0:
                continue
            updated += n_match
            exprs = []
            for fld in snap.schema.fields:
                if fld.name in sets:
                    exprs.append(If(is_match,
                                    sets[fld.name].cast(fld.data_type),
                                    col(fld.name)).alias(fld.name))
                else:
                    exprs.append(col(fld.name))
            new_df = self._file_df(snap, f).select(*exprs)
            actions.extend(self._rewrite(snap, f, new_df))
        if len(actions) > 1:
            self.log.commit(actions, snap.version + 1)
        return updated

    # -- OPTIMIZE ----------------------------------------------------------
    def optimize(self, zorder_by: Optional[Sequence[str]] = None) -> int:
        """OPTIMIZE [ZORDER BY cols]: rewrite the table's files as one
        compacted file per partition tuple, z-order-clustered when keys
        are given (reference delta-lake OPTIMIZE + zorder/ZOrderRules:
        sort by GpuInterleaveBits of the keys so file-level min/max
        stats skip aggressively on those columns). Returns the number of
        files removed."""
        from ..api.functions import col
        from ..expr.zorder import InterleaveBits
        snap = self.log.snapshot()
        if not snap.files:
            return 0
        df = self.to_df()
        if zorder_by:
            code = InterleaveBits(*[col(c) for c in zorder_by])
            df = (df.with_column("__zorder", code)
                    .sort("__zorder")
                    .select(*[col(n) for n in snap.schema.names]))
        adds = _write_data_files(df, self.log.table_path,
                                 snap.partition_columns)
        actions: List[dict] = [DeltaLog.commit_info(
            "OPTIMIZE", zOrderBy=json.dumps(list(zorder_by or [])))]
        for f in snap.files:
            actions.append({"remove": {"path": f.path, "dataChange": False,
                                       "deletionTimestamp": 0}})
        # rearrangement-only: adds must be dataChange=false too, or CDC/
        # streaming readers reprocess every compacted row (Delta OPTIMIZE
        # contract)
        actions.extend(a.to_action(data_change=False) for a in adds)
        self.log.commit(actions, snap.version + 1)
        return len(snap.files)

    # -- MERGE -------------------------------------------------------------
    def merge(self, source_df, on: Sequence[str]) -> "_MergeBuilder":
        """MERGE INTO t USING source ON t.k = s.k (equi-merge; reference
        GpuMergeIntoCommand / GpuRapidsProcessDeltaMergeJoinExec)."""
        return _MergeBuilder(self, source_df, list(on))


class _MergeBuilder:
    def __init__(self, table: DeltaTable, source_df, on: List[str]):
        self.table = table
        self.source = source_df
        self.on = on
        self._update: Optional[Dict[str, object]] = None
        self._delete = False
        self._insert: Optional[Dict[str, object]] = None

    def when_matched_update(self, set: Dict[str, object]
                            ) -> "_MergeBuilder":
        self._update = set
        return self

    def when_matched_delete(self) -> "_MergeBuilder":
        self._delete = True
        return self

    def when_not_matched_insert(self, values: Optional[Dict[str, object]]
                                = None) -> "_MergeBuilder":
        self._insert = values if values is not None else {}
        return self

    def execute(self) -> Dict[str, int]:
        from ..api.functions import col
        from ..api.session import _to_expr
        from ..expr.conditional import If
        t = self.table
        snap = t.log.snapshot()
        sess = t.session
        schema = snap.schema
        src_names = self.source.columns
        # prefix source columns to avoid collisions, keep join keys usable
        renamed = self.source.select(*[
            col(c).alias(f"__s_{c}") for c in src_names])
        marked = renamed.with_column(_MARKER, lit(True))

        # 1 source row per key, or the merge is ambiguous (Spark raises)
        key_counts = self.source.group_by(*self.on).agg(
            (_count_fn(), "__c")).collect()
        if any(row[-1] > 1 for row in key_counts):
            raise ValueError(
                "MERGE: multiple source rows match the same key")

        src_keys = set()
        key_idx = [self.source.schema.index_of(k) for k in self.on]
        for row in self.source.collect():
            src_keys.add(tuple(row[i] for i in key_idx))
        # SQL equi-join semantics: NULL keys never match — a source row
        # with a NULL key can only ever be an unmatched insert
        src_match_keys = {k for k in src_keys if None not in k}

        stats = {"updated": 0, "deleted": 0, "inserted": 0}
        actions: List[dict] = [DeltaLog.commit_info("MERGE")]

        matched_keys = set()
        for f in snap.files:
            file_df = t._file_df(snap, f)
            rows = file_df.collect()
            tkey_idx = [schema.index_of(k) for k in self.on]
            fkeys = {tuple(r[i] for i in tkey_idx) for r in rows}
            hit = fkeys & src_match_keys
            if not hit:
                continue
            matched_keys |= hit
            joined = t._file_df(snap, f).join(
                marked, left_on=list(self.on),
                right_on=[f"__s_{k}" for k in self.on], how="left_outer")
            is_matched = IsNotNull(col(_MARKER))
            out = joined
            n_hit_rows = sum(1 for r in rows
                             if tuple(r[i] for i in tkey_idx) in hit)
            if self._delete:
                out = out.filter(Not(EqualNullSafe(is_matched, lit(True))))
                stats["deleted"] += n_hit_rows
            exprs = []
            for fld in schema.fields:
                if self._update and fld.name in self._update:
                    upd = _to_expr(self._update[fld.name])
                    exprs.append(If(is_matched,
                                    upd.cast(fld.data_type),
                                    col(fld.name)).alias(fld.name))
                else:
                    exprs.append(col(fld.name))
            out = out.select(*exprs)
            if self._update:
                stats["updated"] += n_hit_rows
            actions.extend(t._rewrite(snap, f, out))

        if self._insert is not None:
            unmatched = [k for k in src_keys if k not in matched_keys]
            if unmatched:
                src_rows = self.source.collect()
                keep_rows = [r for r in src_rows
                             if tuple(r[i] for i in key_idx) in
                             set(unmatched)]
                ins_values: Dict[str, List] = {n: [] for n in schema.names}
                src_pos = {n: i for i, n in enumerate(src_names)}
                for r in keep_rows:
                    for fld in schema.fields:
                        if self._insert and fld.name in self._insert:
                            raise ValueError(
                                "explicit insert expressions not supported;"
                                " use column-name mapping")
                        v = r[src_pos[fld.name]] \
                            if fld.name in src_pos else None
                        ins_values[fld.name].append(v)
                ins_df = sess.from_pydict(ins_values, schema)
                adds = _write_data_files(ins_df, t.log.table_path,
                                         snap.partition_columns)
                actions.extend(a.to_action() for a in adds)
                stats["inserted"] = len(keep_rows)

        if len(actions) > 1:
            t.log.commit(actions, snap.version + 1)
        return stats


def _count_fn():
    from ..expr.aggexprs import Count
    return Count()
