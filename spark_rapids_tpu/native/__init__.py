"""Native host-runtime library: build-on-first-use C++ via ctypes.

The reference's host runtime leans on external native libraries (nvcomp
LZ4 for shuffle compression, JCudfSerialization framing, RMM bookkeeping —
SURVEY §2.9). This package holds the TPU build's native pieces, compiled
from `src/` with g++ at first use and cached next to the sources. Python
fallbacks exist for every entry point so the engine still runs (slower,
or with codec COPY) where a toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "blockcodec.cpp")
_SO = os.path.join(_HERE, "src", "libtpublockcodec.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile_so() -> None:
    tmp = _SO + ".tmp"
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
        check=True, capture_output=True, timeout=120)
    os.replace(tmp, _SO)


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _compile_so()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/foreign binary (e.g. wrong arch): rebuild from source
            _compile_so()
            lib = ctypes.CDLL(_SO)
    except (OSError, subprocess.SubprocessError):
        _build_failed = True
        return None
    i64, u64, u8p = (ctypes.c_int64, ctypes.c_uint64,
                     ctypes.POINTER(ctypes.c_uint8))
    lib.tpu_lz4_compress_bound.restype = i64
    lib.tpu_lz4_compress_bound.argtypes = [i64]
    lib.tpu_lz4_compress.restype = i64
    lib.tpu_lz4_compress.argtypes = [u8p, i64, u8p, i64]
    lib.tpu_lz4_decompress.restype = i64
    lib.tpu_lz4_decompress.argtypes = [u8p, i64, u8p, i64]
    lib.tpu_xxh64.restype = u64
    lib.tpu_xxh64.argtypes = [u8p, i64, u64]
    return lib


def native_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when g++/dlopen is unavailable."""
    global _lib
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                _lib = _build()
    return _lib


def lz4_available() -> bool:
    return native_lib() is not None


def _as_u8p(buf) -> "ctypes.POINTER(ctypes.c_uint8)":
    return ctypes.cast(
        (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
        if isinstance(buf, (bytes, bytearray)) else buf,
        ctypes.POINTER(ctypes.c_uint8))


def lz4_compress(data: bytes) -> bytes:
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native LZ4 codec unavailable (no g++)")
    bound = lib.tpu_lz4_compress_bound(len(data))
    dst = ctypes.create_string_buffer(bound)
    n = lib.tpu_lz4_compress(
        _as_u8p(data), len(data),
        ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)), bound)
    if n < 0:
        raise RuntimeError("LZ4 compression failed")
    return dst.raw[:n]


def lz4_decompress(data: bytes, raw_len: int) -> bytes:
    lib = native_lib()
    if lib is None:
        raise RuntimeError("native LZ4 codec unavailable (no g++)")
    dst = ctypes.create_string_buffer(max(raw_len, 1))
    n = lib.tpu_lz4_decompress(
        _as_u8p(data), len(data),
        ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)), raw_len)
    if n != raw_len:
        raise ValueError("corrupt LZ4 block")
    return dst.raw[:raw_len]


def _xxh64_py(data: bytes, seed: int) -> int:
    """Pure-python xxhash64 (canonical constants) fallback."""
    M = (1 << 64) - 1
    P1, P2, P3, P4, P5 = (0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F,
                          0x165667B19E3779F9, 0x85EBCA77C2B2AE63,
                          0x27D4EB2F165667C5)

    def rotl(v, r):
        return ((v << r) | (v >> (64 - r))) & M

    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i + 32 <= n:
            for j, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 8 * j:i + 8 * j + 8], "little")
                v = rotl((v + lane * P2) & M, 31) * P1 & M
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            h ^= rotl(v * P2 & M, 31) * P1 & M
            h = (h * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 8 <= n:
        lane = int.from_bytes(data[i:i + 8], "little")
        h ^= rotl(lane * P2 & M, 31) * P1 & M
        h = (rotl(h, 27) * P1 + P4) & M
        i += 8
    if i + 4 <= n:
        h ^= int.from_bytes(data[i:i + 4], "little") * P1 & M
        h = (rotl(h, 23) * P2 + P3) & M
        i += 4
    while i < n:
        h ^= data[i] * P5 & M
        h = rotl(h, 11) * P1 & M
        i += 1
    h ^= h >> 33
    h = h * P2 & M
    h ^= h >> 29
    h = h * P3 & M
    h ^= h >> 32
    return h


def xxh64(data: bytes, seed: int = 0) -> int:
    lib = native_lib()
    if lib is None:
        return _xxh64_py(data, seed)
    return int(lib.tpu_xxh64(_as_u8p(data), len(data), seed))
