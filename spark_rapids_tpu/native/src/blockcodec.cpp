// Native block compression codec for the host shuffle data plane.
//
// The reference compresses device shuffle blocks with nvcomp LZ4
// (NvcompLZ4CompressionCodec.scala, TableCompressionCodec.scala); this is
// the TPU build's host-side equivalent: an LZ4 *block format* codec
// (https://github.com/lz4/lz4/blob/dev/doc/lz4_Block_format.md) implemented
// from the format spec, compiled with g++ and driven from Python over
// ctypes. Host shuffle blocks are compressed on the writer thread pool and
// decompressed on the reader pool (RapidsShuffleInternalManagerBase.scala
// :238/:569 threading model).
//
// Exported C ABI:
//   int64_t tpu_lz4_compress_bound(int64_t n)
//   int64_t tpu_lz4_compress(const uint8_t* src, int64_t n,
//                            uint8_t* dst, int64_t dst_cap)
//       -> compressed size, or -1 if dst_cap too small
//   int64_t tpu_lz4_decompress(const uint8_t* src, int64_t n,
//                              uint8_t* dst, int64_t raw_len)
//       -> raw_len on success, -1 on malformed input
//   uint64_t tpu_xxh64(const uint8_t* src, int64_t n, uint64_t seed)
//       -> frame checksum (same xxhash64 family the device kernels use)

#include <cstdint>
#include <cstring>

namespace {

constexpr int kMinMatch = 4;
constexpr int kHashLog = 16;
constexpr int kMaxOffset = 65535;
// spec: the last match must start at least 12 bytes before block end and
// the last 5 bytes are always literals
constexpr int kLastLiterals = 5;
constexpr int kMfLimit = 12;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashLog);
}

}  // namespace

extern "C" {

int64_t tpu_lz4_compress_bound(int64_t n) {
  // worst case: incompressible data expands by 1 byte per 255 + header slop
  return n + n / 255 + 16;
}

int64_t tpu_lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                         int64_t dst_cap) {
  if (n < 0) return -1;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  const uint8_t* anchor = src;

  auto emit = [&](const uint8_t* lit_start, int64_t lit_len, int64_t offset,
                  int64_t match_len) -> bool {
    // token + literal length
    int64_t need = 1 + lit_len / 255 + 1 + lit_len + (offset ? 2 : 0) +
                   (match_len >= 15 ? match_len / 255 + 1 : 0) + 8;
    if (op + need > oend) return false;
    uint8_t* token = op++;
    int64_t ll = lit_len;
    if (ll >= 15) {
      *token = 15 << 4;
      ll -= 15;
      while (ll >= 255) { *op++ = 255; ll -= 255; }
      *op++ = static_cast<uint8_t>(ll);
    } else {
      *token = static_cast<uint8_t>(ll << 4);
    }
    std::memcpy(op, lit_start, lit_len);
    op += lit_len;
    if (offset == 0) return true;  // final literals-only sequence
    op[0] = static_cast<uint8_t>(offset & 0xff);
    op[1] = static_cast<uint8_t>(offset >> 8);
    op += 2;
    int64_t ml = match_len - kMinMatch;
    if (ml >= 15) {
      *token |= 15;
      ml -= 15;
      while (ml >= 255) { *op++ = 255; ml -= 255; }
      *op++ = static_cast<uint8_t>(ml);
    } else {
      *token |= static_cast<uint8_t>(ml);
    }
    return true;
  };

  if (n >= kMfLimit) {
    int32_t table[1 << kHashLog];
    std::memset(table, -1, sizeof(table));
    const uint8_t* const mflimit = iend - kMfLimit;
    while (ip <= mflimit) {
      uint32_t h = hash4(read32(ip));
      int32_t cand = table[h];
      table[h] = static_cast<int32_t>(ip - src);
      if (cand >= 0 && (ip - src) - cand <= kMaxOffset &&
          read32(src + cand) == read32(ip)) {
        // extend the match forward
        const uint8_t* m = src + cand;
        const uint8_t* p = ip + kMinMatch;
        const uint8_t* q = m + kMinMatch;
        const uint8_t* const match_limit = iend - kLastLiterals;
        while (p < match_limit && *p == *q) { ++p; ++q; }
        int64_t match_len = p - ip;
        if (!emit(anchor, ip - anchor, ip - m, match_len)) return -1;
        ip += match_len;
        anchor = ip;
        if (ip <= mflimit) {
          table[hash4(read32(ip - 2))] = static_cast<int32_t>(ip - 2 - src);
        }
      } else {
        ++ip;
      }
    }
  }
  if (!emit(anchor, iend - anchor, 0, 0)) return -1;
  return op - dst;
}

int64_t tpu_lz4_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                           int64_t raw_len) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + raw_len;
  while (ip < iend) {
    uint8_t token = *ip++;
    int64_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > iend || op + lit_len > oend) return -1;
    std::memcpy(op, ip, lit_len);
    ip += lit_len;
    op += lit_len;
    if (ip >= iend) break;  // literals-only terminal sequence
    if (ip + 2 > iend) return -1;
    int64_t offset = ip[0] | (ip[1] << 8);
    ip += 2;
    if (offset == 0 || offset > op - dst) return -1;
    int64_t match_len = (token & 15);
    if (match_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        match_len += b;
      } while (b == 255);
    }
    match_len += kMinMatch;
    if (op + match_len > oend) return -1;
    const uint8_t* m = op - offset;
    // overlapping copy must run byte-forward (RLE-style matches)
    for (int64_t i = 0; i < match_len; ++i) op[i] = m[i];
    op += match_len;
  }
  return (op == oend && ip == iend) ? raw_len : -1;
}

// xxhash64 (canonical constants) for frame checksums — the same hash
// family the device kernels implement in ops/hashing.py.
uint64_t tpu_xxh64(const uint8_t* src, int64_t n, uint64_t seed) {
  constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
  constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
  constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
  constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
  constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;
  auto rotl = [](uint64_t v, int r) { return (v << r) | (v >> (64 - r)); };
  auto read64 = [](const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;
  };
  const uint8_t* p = src;
  const uint8_t* const end = src + n;
  uint64_t h;
  if (n >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
             v4 = seed - P1;
    do {
      v1 = rotl(v1 + read64(p) * P2, 31) * P1; p += 8;
      v2 = rotl(v2 + read64(p) * P2, 31) * P1; p += 8;
      v3 = rotl(v3 + read64(p) * P2, 31) * P1; p += 8;
      v4 = rotl(v4 + read64(p) * P2, 31) * P1; p += 8;
    } while (p + 32 <= end);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    auto merge = [&](uint64_t v) {
      h ^= rotl(v * P2, 31) * P1;
      h = h * P1 + P4;
    };
    merge(v1); merge(v2); merge(v3); merge(v4);
  } else {
    h = seed + P5;
  }
  h += static_cast<uint64_t>(n);
  while (p + 8 <= end) {
    h ^= rotl(read64(p) * P2, 31) * P1;
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    h ^= static_cast<uint64_t>(v) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= static_cast<uint64_t>(*p++) * P5;
    h = rotl(h, 11) * P1;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // extern "C"
