"""Host shuffle manager — MULTITHREADED mode (the reference's default:
RapidsShuffleInternalManagerBase.scala:238 threaded writers, :569 threaded
readers, over Spark's file-based sort shuffle; SURVEY §2.5 + §3.5).

Disk layout mirrors Spark's sort-shuffle contract: one data file + one
index per map task. Partition blocks are serialized + LZ4-compressed in
parallel on the writer pool (serialization dominates, so this is where the
threads pay off), then written sequentially in partition order; the index
records the partition byte ranges. Readers fetch a partition's segment
from every map output and decode blocks on the reader pool.
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..config import (SHUFFLE_READER_THREADS, SHUFFLE_WRITER_THREADS,
                      SPILL_DIR, RapidsConf, active_conf)
from ..types import Schema
from .. import faults
from ..io.retrying import with_io_retry
from .serializer import (CorruptFrameError, deserialize_batch,
                         host_gather_batch, serialize_batch)


class HostShuffleHandle:
    """Registration record (Spark's ShuffleHandle analog)."""

    def __init__(self, shuffle_id: int, n_partitions: int, schema: Schema):
        self.shuffle_id = shuffle_id
        self.n_partitions = n_partitions
        self.schema = schema
        self.map_outputs: List[str] = []  # data file per completed map task


class HostShuffleWriter:
    """Writes one map task's partitioned blocks (reference
    RapidsShuffleThreadedWriterBase)."""

    def __init__(self, handle: HostShuffleHandle, map_id: int,
                 manager: "HostShuffleManager",
                 conf: Optional[RapidsConf] = None):
        self.handle = handle
        self.map_id = map_id
        self.manager = manager
        conf = conf or active_conf()
        self._pool = manager.writer_pool(conf)
        self.bytes_written = 0

    def write(self, partitioned: Sequence[List[ColumnarBatch]]) -> None:
        """partitioned[p] = list of batches for partition p. Serialization
        (the expensive part: host gather + LZ4) fans out on the writer
        pool; the file write is sequential in partition order so the index
        stays a flat range table.

        Commit protocol (ISSUE 4): both files are written under
        ATTEMPT-TAGGED temp names and renamed into place atomically,
        data first, index last; the map output is only registered with
        the handle after both renames land. A task attempt that dies
        mid-write leaves only `.attempt-K.tmp` droppings (cleaned below)
        — a reader can never observe a partial shard, and two attempts
        of one map task never collide on a temp name (the reference's
        shuffle write-then-commit discipline, single-process edition)."""
        n = self.handle.n_partitions
        assert len(partitioned) == n
        jobs = [(p, i, self._pool.submit(serialize_batch, b))
                for p in range(n) for i, b in enumerate(partitioned[p])]
        frames: Dict[tuple, bytes] = {}
        for p, i, fut in jobs:
            frames[(p, i)] = fut.result()
        data_path = self.manager.map_data_path(self.handle.shuffle_id,
                                               self.map_id)
        from ..exec.task_retry import task_attempt
        tag = f".attempt-{task_attempt()}.tmp"
        tmp_data, tmp_index = data_path + tag, data_path + ".index" + tag
        offsets = [0] * (n + 1)
        try:
            with open(tmp_data, "wb") as f:
                pos = 0
                for p in range(n):
                    for i in range(len(partitioned[p])):
                        frame = frames[(p, i)]
                        f.write(struct.pack("<Q", len(frame)))
                        f.write(frame)
                        pos += 8 + len(frame)
                    offsets[p + 1] = pos
            with open(tmp_index, "wb") as f:
                f.write(struct.pack(f"<{n + 1}Q", *offsets))
            os.replace(tmp_data, data_path)
            os.replace(tmp_index, data_path + ".index")
        except BaseException:
            for t in (tmp_data, tmp_index):
                try:
                    os.unlink(t)
                except OSError:
                    pass
            raise
        self.bytes_written = offsets[n]
        self.handle.map_outputs.append(data_path)


class HostShuffleReader:
    """Reads one partition across all map outputs (reference
    RapidsShuffleThreadedReaderBase / the reduce-side fetch)."""

    def __init__(self, handle: HostShuffleHandle,
                 manager: "HostShuffleManager",
                 conf: Optional[RapidsConf] = None):
        self.handle = handle
        self.manager = manager
        #: captured for the pool threads (active_conf is thread-local):
        #: the IO-retry policy must follow the query's conf, not the
        #: worker's default
        self._conf = conf or active_conf()
        self._pool = manager.reader_pool(self._conf)
        #: per-map index table cache: one parse per map output, not one
        #: per (map, partition) pair
        self._index_cache: Dict[str, Tuple[int, ...]] = {}

    def _index(self, data_path: str) -> Tuple[int, ...]:
        cached = self._index_cache.get(data_path)
        if cached is None:
            n = self.handle.n_partitions
            with open(data_path + ".index", "rb") as f:
                cached = struct.unpack(f"<{n + 1}Q", f.read(8 * (n + 1)))
            self._index_cache[data_path] = cached
        return cached

    def _fetch_segment(self, data_path: str, partition: int) -> List[bytes]:
        """One partition's frames from one map output, with bounded IO
        retry (ISSUE 4 satellite): a transient read failure — or an
        injected `shuffle.fetch` fault — re-fetches with backoff
        instead of killing the query."""
        def fetch() -> List[bytes]:
            # the index read lives INSIDE the retry lane too: a flaky
            # mount fails the .index open just as readily as the data
            # segment, and the cache makes the re-read free afterwards
            offsets = self._index(data_path)
            lo, hi = offsets[partition], offsets[partition + 1]
            frames: List[bytes] = []
            if hi > lo:
                with open(data_path, "rb") as f:
                    f.seek(lo)
                    seg = f.read(hi - lo)
                p = 0
                while p < len(seg):
                    (ln,) = struct.unpack_from("<Q", seg, p)
                    frames.append(seg[p + 8: p + 8 + ln])
                    p += 8 + ln
            return frames

        return with_io_retry(
            fetch, "shuffle.fetch", conf=self._conf,
            fault_point="shuffle.fetch",
            # per-(map file, partition) jitter: concurrent pool threads
            # on one flaky mount must not re-herd in lockstep
            salt=f"{os.path.basename(data_path)}:{partition}")

    def _decode(self, frame: bytes, key: str = "") -> ColumnarBatch:
        """Integrity-checked decode: the frame's xxh64 (stamped at
        write over header + size table + payload) is verified inside
        deserialize_batch; a corrupt block is quarantined — an
        `integrity_fail` event, never propagated downstream — and the
        failure surfaces as a task-retry so the query recomputes."""
        frame = faults.apply("shuffle.decode", frame, key=key or None)
        try:
            return deserialize_batch(frame, self.handle.schema)
        except CorruptFrameError as e:
            from ..obs import events as obs_events
            obs_events.emit("integrity_fail", what="shuffle_block",
                            shuffle_id=self.handle.shuffle_id,
                            bytes=len(frame), error=str(e)[:200])
            raise faults.IntegrityError(
                f"corrupt shuffle block (shuffle {self.handle.shuffle_id}): "
                f"{e}") from e

    def read_partition(self, partition: int) -> Iterator[ColumnarBatch]:
        segs = list(self._pool.map(
            lambda path: self._fetch_segment(path, partition),
            self.handle.map_outputs))
        frames = [fr for seg in segs for fr in seg]
        # per-frame injection key (partition + frame ordinal): the chaos
        # verdict follows the frame, not decode-pool scheduling
        yield from self._pool.map(
            lambda args: self._decode(args[1], key=f"p{partition}:{args[0]}"),
            enumerate(frames))


class HostShuffleManager:
    """Process-wide registry + block file manager (Spark's ShuffleManager
    SPI + RapidsDiskBlockManager)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next_id = 0
        self._handles: Dict[int, HostShuffleHandle] = {}
        self._root: Optional[str] = None
        self._writer_pool: Optional[ThreadPoolExecutor] = None
        self._reader_pool: Optional[ThreadPoolExecutor] = None

    # -- dirs & pools ------------------------------------------------------
    def root_dir(self, conf: Optional[RapidsConf] = None) -> str:
        with self._lock:
            if self._root is None:
                conf = conf or active_conf()
                base = conf.get(SPILL_DIR) or tempfile.gettempdir()
                self._root = tempfile.mkdtemp(prefix="tpu-shuffle-",
                                              dir=base)
            return self._root

    def map_data_path(self, shuffle_id: int, map_id: int) -> str:
        return os.path.join(self.root_dir(),
                            f"shuffle_{shuffle_id}_{map_id}.data")

    def writer_pool(self, conf: RapidsConf) -> ThreadPoolExecutor:
        with self._lock:
            if self._writer_pool is None:
                self._writer_pool = ThreadPoolExecutor(
                    max_workers=max(1, conf.get(SHUFFLE_WRITER_THREADS)),
                    thread_name_prefix="shuffle-writer")
            return self._writer_pool

    def reader_pool(self, conf: RapidsConf) -> ThreadPoolExecutor:
        with self._lock:
            if self._reader_pool is None:
                self._reader_pool = ThreadPoolExecutor(
                    max_workers=max(1, conf.get(SHUFFLE_READER_THREADS)),
                    thread_name_prefix="shuffle-reader")
            return self._reader_pool

    # -- lifecycle ---------------------------------------------------------
    def register(self, n_partitions: int, schema: Schema
                 ) -> HostShuffleHandle:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            h = HostShuffleHandle(sid, n_partitions, schema)
            self._handles[sid] = h
            return h

    def unregister(self, handle: HostShuffleHandle) -> None:
        with self._lock:
            self._handles.pop(handle.shuffle_id, None)
        for path in handle.map_outputs:
            for p in (path, path + ".index"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        handle.map_outputs.clear()


_MANAGER: Optional[HostShuffleManager] = None
_MANAGER_LOCK = threading.Lock()


def shuffle_manager() -> HostShuffleManager:
    global _MANAGER
    if _MANAGER is None:
        with _MANAGER_LOCK:
            if _MANAGER is None:
                _MANAGER = HostShuffleManager()
    return _MANAGER


def partition_batch_host(batch: ColumnarBatch, pid: np.ndarray,
                         n_partitions: int) -> List[ColumnarBatch]:
    """Split a batch into per-partition compact host batches given the
    device-computed partition id per row (Spark-exact murmur3 pmod from
    parallel/exchange.partition_ids). Stable within a partition."""
    order = np.argsort(pid, kind="stable")
    sorted_pid = pid[order]
    bounds = np.searchsorted(sorted_pid, np.arange(n_partitions + 1))
    return [host_gather_batch(batch, order[bounds[p]: bounds[p + 1]])
            for p in range(n_partitions)]
