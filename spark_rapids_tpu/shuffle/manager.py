"""Host shuffle manager — MULTITHREADED mode (the reference's default:
RapidsShuffleInternalManagerBase.scala:238 threaded writers, :569 threaded
readers, over Spark's file-based sort shuffle; SURVEY §2.5 + §3.5).

Disk layout mirrors Spark's sort-shuffle contract: one data file + one
index per map task. Partition blocks are serialized + LZ4-compressed in
parallel on the writer pool (serialization dominates, so this is where the
threads pay off), then written sequentially in partition order; the index
records the partition byte ranges. Readers fetch a partition's segment
from every map output and decode blocks on the reader pool.
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..config import (SHUFFLE_READER_THREADS, SHUFFLE_WRITER_THREADS,
                      SPILL_DIR, RapidsConf, active_conf)
from ..types import Schema
from .. import faults
from ..io.retrying import with_io_retry
from .serializer import (CorruptFrameError, deserialize_batch,
                         host_gather_batch, host_gather_calls,
                         host_slice_batch, serialize_batch,
                         serialize_slice)


#: process-cumulative shuffle-write counters (bench.py embeds per-record
#: deltas, the chaos-delta pattern): batches split per lane, frames and
#: bytes written, and the write-time split pack / serialize / file-IO
_COUNTER_LOCK = threading.Lock()
_COUNTERS = {"batches": 0, "device_batches": 0, "host_batches": 0,
             "frames": 0, "bytes": 0, "pack_ns": 0, "serialize_ns": 0,
             "io_ns": 0}


def note_shuffle_write(**deltas) -> None:
    with _COUNTER_LOCK:
        for k, v in deltas.items():
            _COUNTERS[k] += v


def counters() -> Dict[str, int]:
    """Snapshot of the shuffle-write counters, plus the serializer's
    host-gather call count (0 growth on the device-partition lanes)."""
    with _COUNTER_LOCK:
        out = dict(_COUNTERS)
    out["host_gathers"] = host_gather_calls()
    return out


#: process-cumulative ICI-lane counters (ISSUE 16; bench.py embeds
#: per-record deltas like the write counters above): collective rounds
#: and batches exchanged device-to-device, bytes moved over the mesh
#: axis, collective wall time, and rounds that degraded to the host
#: serialize lane
_ICI_COUNTERS = {"rounds": 0, "batches": 0, "bytes": 0,
                 "collective_ns": 0, "fallbacks": 0}


def note_ici_exchange(**deltas) -> None:
    with _COUNTER_LOCK:
        for k, v in deltas.items():
            _ICI_COUNTERS[k] += v


def ici_counters() -> Dict[str, int]:
    """Snapshot of the ICI exchange-lane counters. `frames`/`bytes` in
    counters() stay flat while this lane carries the data — the
    structural zero-host-serialize assertion tests pin."""
    with _COUNTER_LOCK:
        return dict(_ICI_COUNTERS)


class HostShuffleHandle:
    """Registration record (Spark's ShuffleHandle analog)."""

    def __init__(self, shuffle_id: int, n_partitions: int, schema: Schema):
        self.shuffle_id = shuffle_id
        self.n_partitions = n_partitions
        self.schema = schema
        self.map_outputs: List[str] = []  # data file per completed map task
        #: partition-granular recovery lineage (ISSUE 6): data path ->
        #: zero-arg recompute that re-executes ONLY the producing
        #: sub-plan (the exchange child) and atomically rewrites that
        #: one map output. Captured by HostShuffleExchangeExec at write
        #: time when spark.rapids.tpu.task.partitionRecovery.enabled.
        self.lineage: Dict[str, object] = {}
        #: map outputs already recomputed once — a SECOND corruption of
        #: the same output means the lineage itself is producing bad
        #: bytes (or the disk is gone); fall back to the whole-plan
        #: lane. Guarded by recover_lock: partitions read concurrently
        #: through the pipelined streams may hit the same damaged map
        #: output at once (review r3).
        self.recovered: set = set()
        self.recover_lock = threading.Lock()
        #: map outputs invalidated by a dead-peer transition (ISSUE
        #: 20): the next read of one re-executes its lineage BEFORE any
        #: fetch trusts the dead peer's bytes — Spark's fetch-failure
        #: map-output invalidation, single-process edition. Guarded by
        #: recover_lock, like `recovered`; empty-set truthiness is the
        #: entire steady-state cost on the read path.
        self.invalidated: set = set()


class HostShuffleWriter:
    """Writes one map task's partitioned blocks (reference
    RapidsShuffleThreadedWriterBase)."""

    def __init__(self, handle: HostShuffleHandle, map_id: int,
                 manager: "HostShuffleManager",
                 conf: Optional[RapidsConf] = None):
        self.handle = handle
        self.map_id = map_id
        self.manager = manager
        conf = conf or active_conf()
        self._pool = manager.writer_pool(conf)
        self.bytes_written = 0
        self.frames_written = 0
        self.serialize_ns = 0
        self.io_ns = 0
        #: exact per-partition written bytes (the index offset diffs,
        #: ISSUE 11): sum(partition_bytes) == bytes_written to the byte
        #: — the exchange records these into the runtime statistics
        self.partition_bytes: List[int] = []

    def write(self, partitioned: Sequence[List[ColumnarBatch]],
              register: bool = True, lane: str = "host") -> None:
        """partitioned[p] = list of batches for partition p. Serialization
        (the expensive part: host gather + LZ4) fans out on the writer
        pool; the file write is sequential in partition order so the index
        stays a flat range table. `lane` only labels the write counters
        (the device lane routes its empty-batch maps through here)."""
        n = self.handle.n_partitions
        assert len(partitioned) == n
        import time as _time
        t0 = _time.perf_counter_ns()
        # contract: ok thread-adopt — serialize_batch is a pure function
        # of its batch argument: no conf/event/attempt reads on the pool
        # thread (fault keys ride the frame ordinals at decode, not here)
        jobs = [(p, self._pool.submit(serialize_batch, b))
                for p in range(n) for b in partitioned[p]]
        frames_by_part: List[List[bytes]] = [[] for _ in range(n)]
        for p, fut in jobs:
            frames_by_part[p].append(fut.result())
        self.serialize_ns = _time.perf_counter_ns() - t0
        self._commit(frames_by_part, register, lane=lane)

    def write_slices(self, packed: ColumnarBatch, bounds,
                     register: bool = True) -> None:
        """Write one map task from a partition-ordered host batch
        (ISSUE 9 device lane): `bounds[p]..bounds[p+1]` is partition
        p's row range, and each non-empty partition serializes straight
        from that slice on the writer pool (serialize_slice — offsets
        rebased in place, no gathers). Frame count and order match
        write()'s one-frame-per-non-empty-partition exactly, so the
        seeded chaos keys (`shuffle.decode` global ordinals) and the
        reader's frame indexing are unchanged by the lane."""
        n = self.handle.n_partitions
        assert len(bounds) == n + 1
        import time as _time
        t0 = _time.perf_counter_ns()
        # contract: ok thread-adopt — serialize_slice is a pure function
        # of (packed batch, row range): no thread-local reads on the pool
        jobs = [(p, self._pool.submit(serialize_slice, packed,
                                      int(bounds[p]), int(bounds[p + 1])))
                for p in range(n) if bounds[p + 1] > bounds[p]]
        frames_by_part: List[List[bytes]] = [[] for _ in range(n)]
        for p, fut in jobs:
            frames_by_part[p].append(fut.result())
        self.serialize_ns = _time.perf_counter_ns() - t0
        self._commit(frames_by_part, register, lane="device")

    def _commit(self, frames_by_part: Sequence[List[bytes]],
                register: bool, lane: str) -> None:
        """Write the serialized frames in partition order and publish
        the map output.

        Commit protocol (ISSUE 4): both files are written under
        ATTEMPT-TAGGED temp names and renamed into place atomically,
        data first, index last; the map output is only registered with
        the handle after both renames land. A task attempt that dies
        mid-write leaves only `.attempt-K.tmp` droppings (cleaned below)
        — a reader can never observe a partial shard, and two attempts
        of one map task never collide on a temp name (the reference's
        shuffle write-then-commit discipline, single-process edition)."""
        import time as _time
        n = self.handle.n_partitions
        data_path = self.manager.map_data_path(self.handle.shuffle_id,
                                               self.map_id)
        from ..exec.task_retry import task_attempt
        tag = f".attempt-{task_attempt()}.tmp"
        tmp_data, tmp_index = data_path + tag, data_path + ".index" + tag
        offsets = [0] * (n + 1)
        t0 = _time.perf_counter_ns()
        try:
            with open(tmp_data, "wb") as f:
                pos = 0
                for p in range(n):
                    for frame in frames_by_part[p]:
                        f.write(struct.pack("<Q", len(frame)))
                        f.write(frame)
                        pos += 8 + len(frame)
                    offsets[p + 1] = pos
            with open(tmp_index, "wb") as f:
                f.write(struct.pack(f"<{n + 1}Q", *offsets))
            os.replace(tmp_data, data_path)
            os.replace(tmp_index, data_path + ".index")
        except BaseException:
            for t in (tmp_data, tmp_index):
                try:
                    os.unlink(t)
                except OSError:
                    pass
            raise
        self.io_ns = _time.perf_counter_ns() - t0
        self.bytes_written = offsets[n]
        self.partition_bytes = [offsets[p + 1] - offsets[p]
                                for p in range(n)]
        self.frames_written = sum(len(fs) for fs in frames_by_part)
        note_shuffle_write(
            batches=1, frames=self.frames_written,
            bytes=self.bytes_written, serialize_ns=self.serialize_ns,
            io_ns=self.io_ns,
            **({"device_batches": 1} if lane == "device"
               else {"host_batches": 1}))
        if register:
            self.handle.map_outputs.append(data_path)
        # register=False is the partition-recovery rewrite path: the map
        # output is already registered — the atomic renames above simply
        # replaced the damaged files in place


class HostShuffleReader:
    """Reads one partition across all map outputs (reference
    RapidsShuffleThreadedReaderBase / the reduce-side fetch)."""

    def __init__(self, handle: HostShuffleHandle,
                 manager: "HostShuffleManager",
                 conf: Optional[RapidsConf] = None):
        self.handle = handle
        self.manager = manager
        #: captured for the pool threads (active_conf is thread-local):
        #: the IO-retry policy must follow the query's conf, not the
        #: worker's default
        self._conf = conf or active_conf()
        self._pool = manager.reader_pool(self._conf)
        #: per-map index table cache: one parse per map output, not one
        #: per (map, partition) pair
        self._index_cache: Dict[str, Tuple[int, ...]] = {}
        #: speculative sub-read policy (ISSUE 20): None when
        #: shuffle.speculation.enabled is off — the plain read path
        #: below is untouched, one conf read per reader
        from ..exec import speculation_shield
        self._spec = speculation_shield.reader_speculation(self._conf)

    def _index(self, data_path: str) -> Tuple[int, ...]:
        cached = self._index_cache.get(data_path)
        if cached is None:
            n = self.handle.n_partitions
            with open(data_path + ".index", "rb") as f:
                cached = struct.unpack(f"<{n + 1}Q", f.read(8 * (n + 1)))
            self._index_cache[data_path] = cached
        return cached

    def _fetch_segment(self, data_path: str, partition: int,
                       salt_prefix: str = "") -> List[bytes]:
        """One partition's frames from one map output, with bounded IO
        retry (ISSUE 4 satellite): a transient read failure — or an
        injected `shuffle.fetch` fault — re-fetches with backoff
        instead of killing the query. `salt_prefix` distinguishes a
        speculative duplicate attempt (`spec:`) so it draws its own
        fault verdicts instead of replaying the primary's (ISSUE 20:
        the injected straggler must not also delay its duplicate)."""
        def fetch() -> List[bytes]:
            # the index read lives INSIDE the retry lane too: a flaky
            # mount fails the .index open just as readily as the data
            # segment, and the cache makes the re-read free afterwards
            offsets = self._index(data_path)
            lo, hi = offsets[partition], offsets[partition + 1]
            frames: List[bytes] = []
            if hi > lo:
                with open(data_path, "rb") as f:
                    f.seek(lo)
                    seg = f.read(hi - lo)
                p = 0
                while p < len(seg):
                    (ln,) = struct.unpack_from("<Q", seg, p)
                    frames.append(seg[p + 8: p + 8 + ln])
                    p += 8 + ln
            return frames

        return with_io_retry(
            fetch, "shuffle.fetch", conf=self._conf,
            fault_point="shuffle.fetch",
            # per-(map file, partition) jitter: concurrent pool threads
            # on one flaky mount must not re-herd in lockstep
            salt=f"{salt_prefix}{os.path.basename(data_path)}:{partition}")

    def _decode(self, frame: bytes, key: str = "") -> ColumnarBatch:
        """Integrity-checked decode: the frame's xxh64 (stamped at
        write over header + size table + payload) is verified inside
        deserialize_batch; a corrupt block is quarantined — an
        `integrity_fail` event, never propagated downstream — and the
        failure surfaces as a task-retry so the query recomputes."""
        frame = faults.apply("shuffle.decode", frame, key=key or None)
        try:
            # host-backed decode: device promotion happens at the
            # exchange's read seam (ONE packed upload per batch, on the
            # pipeline producer thread — ISSUE 10), not on this pool
            # thread
            return deserialize_batch(frame, self.handle.schema,
                                     device=False)
        except CorruptFrameError as e:
            from ..obs import events as obs_events
            obs_events.emit("integrity_fail", what="shuffle_block",
                            shuffle_id=self.handle.shuffle_id,
                            bytes=len(frame), error=str(e)[:200])
            raise faults.IntegrityError(
                f"corrupt shuffle block (shuffle {self.handle.shuffle_id}): "
                f"{e}") from e

    def read_partition(self, partition: int) -> Iterator[ColumnarBatch]:
        paths = list(self.handle.map_outputs)
        # dead-peer invalidation consumption (ISSUE 20): a marked map
        # output recomputes from lineage before any fetch trusts it —
        # one empty-set truthiness check in the steady state
        if self.handle.invalidated:
            for path in paths:
                self._refresh_invalidated(path, partition)
        # the reader pool serves every query: io_retry/integrity_fail
        # events from fetch/decode tasks carry the SUBMITTING thread's
        # query id via per-job adoption (ISSUE 12 thread-adopt fix)
        from ..obs import events as obs_events
        qid = obs_events.current_query_id()
        spec = self._spec
        if spec is None:
            segs = list(self._pool.map(
                lambda path: obs_events.with_query_id(
                    qid, self._fetch_segment, path, partition), paths))
        else:
            # speculative sub-reads (ISSUE 20): explicit per-map
            # futures so a straggling fetch past the measured bound
            # races ONE duplicate under a `spec:` work-item key —
            # first result wins, the loser is cancelled/discarded
            futs = [self._pool.submit(
                obs_events.with_query_id, qid, spec.timed, "fetch",
                self._fetch_segment, path, partition)
                for path in paths]
            segs = [spec.resolve(
                "fetch", fut,
                launch=lambda p=path: self._pool.submit(
                    obs_events.with_query_id, qid, spec.timed, "fetch",
                    self._fetch_segment, p, partition, "spec:"),
                key=f"{os.path.basename(path)}:{partition}")
                for path, fut in zip(paths, futs)]
        # per-frame injection key (partition + GLOBAL frame ordinal in
        # map-output order — identical to the pre-ISSUE-6 flattened
        # scheme, so seeded chaos draws replay unchanged): the chaos
        # verdict follows the frame, not decode-pool scheduling
        jobs = []
        ordinal = 0
        for path, frames in zip(paths, segs):
            for i, fr in enumerate(frames):
                dkey = f"p{partition}:{ordinal}"
                if spec is None:
                    fut = self._pool.submit(
                        obs_events.with_query_id, qid,
                        self._decode, fr, dkey)
                    fr = None  # the plain path holds no frame copies
                else:
                    fut = self._pool.submit(
                        obs_events.with_query_id, qid, spec.timed,
                        "decode", self._decode, fr, dkey)
                jobs.append((path, i, fr, dkey, fut))
                ordinal += 1
        for path, frame_idx, fr, dkey, fut in jobs:
            try:
                if spec is None:
                    yield fut.result()
                else:
                    # the spec decode draws its own fault verdicts
                    # (`spec:`-prefixed key), like the spec fetch salt
                    yield spec.resolve(
                        "decode", fut,
                        launch=lambda f=fr, k=dkey: self._pool.submit(
                            obs_events.with_query_id, qid, spec.timed,
                            "decode", self._decode, f, f"spec:{k}"),
                        key=dkey)
            except faults.IntegrityError as e:
                # partition-granular recovery (ISSUE 6): the lineage the
                # exchange captured at write time can rewrite just this
                # map output — consult it before surrendering the whole
                # attempt to the task-retry lane
                yield self._recover_block(path, partition, frame_idx, e)

    # -- adaptive skew-split sub-reads (ISSUE 19) ---------------------------
    def plan_map_groups(self, partition: int, target_bytes: int,
                        ) -> List[Tuple[List[str], int]]:
        """Greedy map-output-granular grouping of one partition's
        segments so each group stays under `target_bytes` (a single
        oversized map output still gets its own group — maps are the
        split granularity, ISSUE 6 lineage follows them). Map order is
        preserved, so the concatenation of the groups' frames IS the
        unsplit read: integer results stay byte-exact. Uses the cached
        index tables — no data IO."""
        groups: List[Tuple[List[str], int]] = []
        cur: List[str] = []
        cur_b = 0
        for path in list(self.handle.map_outputs):
            offsets = self._index(path)
            b = offsets[partition + 1] - offsets[partition]
            if cur and cur_b + b > target_bytes:
                groups.append((cur, cur_b))
                cur, cur_b = [], 0
            cur.append(path)
            cur_b += b
        if cur:
            groups.append((cur, cur_b))
        return groups

    def read_partition_maps(self, partition: int, paths: Sequence[str],
                            sub: int, ordinal: List[int],
                            ) -> Iterator[ColumnarBatch]:
        """One skew-split sub-read: `partition` restricted to the map
        outputs in `paths`. Mirrors read_partition's fetch/decode
        pipelining but bounds the decode window to one sub-read — the
        memory effect the split exists for. `ordinal` is a shared
        mutable counter threaded across a partition's sub-reads so the
        per-frame decode keys stay GLOBALLY numbered in map-output
        order: seeded `shuffle.decode` chaos draws replay identically
        with adaptive on or off. The sub-read seam carries its own
        keyed fault point (`shuffle.skew_split`, work-item key
        shuffle_id:partition:sub); an injected corrupt frame recovers
        through the same per-map lineage lane as an unsplit read."""
        from ..obs import events as obs_events
        qid = obs_events.current_query_id()
        key = f"{self.handle.shuffle_id}:{partition}:{sub}"
        paths = list(paths)
        if self.handle.invalidated:
            for path in paths:
                self._refresh_invalidated(path, partition)
        segs = list(self._pool.map(
            lambda path: obs_events.with_query_id(
                qid, self._fetch_segment, path, partition), paths))
        jobs = []
        for path, frames in zip(paths, segs):
            for i, fr in enumerate(frames):
                fr = faults.apply("shuffle.skew_split", fr, key=key)
                jobs.append((path, i, self._pool.submit(
                    obs_events.with_query_id, qid,
                    self._decode, fr, f"p{partition}:{ordinal[0]}")))
                ordinal[0] += 1
        for path, frame_idx, fut in jobs:
            try:
                yield fut.result()
            except faults.IntegrityError as e:
                yield self._recover_block(path, partition, frame_idx, e)

    def _refresh_invalidated(self, path: str, partition: int) -> None:
        """Consume one dead-peer invalidation marker (ISSUE 20): re-run
        the map output's captured lineage BEFORE any fetch trusts the
        dead peer's bytes — the PR 5 partition-granular lane, not a
        whole-plan retry. Exactly one recompute per invalidated output:
        the marker is discarded under recover_lock, so concurrent
        partition streams refresh once and everyone else reads the
        rewrite. Without lineage the marker clears and the committed
        on-disk file is read as-is (single-process: the bytes are still
        the atomic-commit output)."""
        handle = self.handle
        if path not in handle.invalidated:
            return
        import time as _time
        with handle.recover_lock:
            if path not in handle.invalidated:
                return  # another stream refreshed it
            handle.invalidated.discard(path)
            recompute = handle.lineage.get(path)
            if recompute is None:
                return
            t0 = _time.perf_counter_ns()
            recompute()
            # the file changed under us: drop the cached index table,
            # and make the refreshed output recompute-eligible again
            # (the invalidation lane and the corruption lane each get
            # one shot at a given output)
            self._index_cache.pop(path, None)
            handle.recovered.discard(path)
            from ..exec import lifecycle
            from ..obs import events as obs_events
            lifecycle.note_partition_recompute()
            obs_events.emit(
                "partition_recompute", shuffle_id=handle.shuffle_id,
                partition=partition, map_path=os.path.basename(path),
                trigger="dead_peer",
                wall_ns=_time.perf_counter_ns() - t0)

    def _recover_block(self, path: str, partition: int, frame_idx: int,
                       err: "faults.IntegrityError") -> ColumnarBatch:
        """Recover ONE quarantined shuffle block by re-executing only
        its producing sub-plan (the handle's captured lineage), then
        re-fetching + re-decoding the rewritten map output. Falls back
        to the whole-plan lane (re-raising with provenance attached)
        when lineage is missing, the conf gates it off, this map output
        already recovered once, or the recomputed block is corrupt
        again."""
        import time as _time

        from ..config import PARTITION_RECOVERY_ENABLED
        recompute = self.handle.lineage.get(path)
        if recompute is None \
                or not self._conf.get(PARTITION_RECOVERY_ENABLED):
            raise self._with_provenance(err, path, partition)
        # check-then-recompute under the handle lock (review r3):
        # concurrent partition streams hitting one damaged map output
        # must produce exactly ONE recompute — the loser waits the
        # rewrite out here and then simply re-fetches below (its frame
        # came from a stale pre-rewrite read). Recovery stays bounded:
        # the post-recovery re-decode raises straight out of
        # read_partition with provenance (it is not wrapped by the
        # recovery handler), so a map output whose REWRITE is bad
        # escalates to the whole-plan lane instead of recomputing
        # forever.
        with self.handle.recover_lock:
            if path not in self.handle.recovered:
                self.handle.recovered.add(path)
                t0 = _time.perf_counter_ns()
                try:
                    recompute()
                except Exception:  # noqa: BLE001 — the recompute
                    # itself died (its sub-plan re-raises real
                    # failures): the original integrity error is what
                    # the task-retry lane should see
                    raise self._with_provenance(err, path, partition)
                # the file changed under us: drop the cached index table
                self._index_cache.pop(path, None)
                from ..exec import lifecycle
                from ..obs import events as obs_events
                lifecycle.note_partition_recompute()
                obs_events.emit(
                    "partition_recompute",
                    shuffle_id=self.handle.shuffle_id,
                    partition=partition,
                    map_path=os.path.basename(path),
                    wall_ns=_time.perf_counter_ns() - t0)
        try:
            frames = self._fetch_segment(path, partition)
            if frame_idx >= len(frames):
                raise self._with_provenance(err, path, partition)
            # fresh injection key: the recovered decode draws its own
            # deterministic verdicts instead of replaying the one that
            # just quarantined this block
            return self._decode(frames[frame_idx],
                                key=f"recover:p{partition}:{frame_idx}")
        except faults.IntegrityError as e2:
            raise self._with_provenance(e2, path, partition)

    def _with_provenance(self, err: "faults.IntegrityError", path: str,
                         partition: int) -> "faults.IntegrityError":
        err.provenance = {"kind": "shuffle_block",
                          "shuffle_id": self.handle.shuffle_id,
                          "partition": partition,
                          "map_path": os.path.basename(path)}
        return err


class HostShuffleManager:
    """Process-wide registry + block file manager (Spark's ShuffleManager
    SPI + RapidsDiskBlockManager)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next_id = 0
        self._handles: Dict[int, HostShuffleHandle] = {}
        self._root: Optional[str] = None
        self._writer_pool: Optional[ThreadPoolExecutor] = None
        self._reader_pool: Optional[ThreadPoolExecutor] = None
        #: dead-peer bookkeeping (ISSUE 20): executor_id ->
        #: [(shuffle_id, data_path)] for map outputs a peer holds —
        #: Spark's MapOutputTracker per-executor attribution, consumed
        #: exactly once by invalidate_peer_outputs on peer_dead
        self._peer_outputs: Dict[str, List[Tuple[int, str]]] = {}

    # -- dirs & pools ------------------------------------------------------
    def root_dir(self, conf: Optional[RapidsConf] = None) -> str:
        with self._lock:
            if self._root is None:
                conf = conf or active_conf()
                base = conf.get(SPILL_DIR) or tempfile.gettempdir()
                self._root = tempfile.mkdtemp(prefix="tpu-shuffle-",
                                              dir=base)
            return self._root

    def map_data_path(self, shuffle_id: int, map_id: int) -> str:
        return os.path.join(self.root_dir(),
                            f"shuffle_{shuffle_id}_{map_id}.data")

    def writer_pool(self, conf: RapidsConf) -> ThreadPoolExecutor:
        with self._lock:
            if self._writer_pool is None:
                self._writer_pool = ThreadPoolExecutor(
                    max_workers=max(1, conf.get(SHUFFLE_WRITER_THREADS)),
                    thread_name_prefix="shuffle-writer")
            return self._writer_pool

    def reader_pool(self, conf: RapidsConf) -> ThreadPoolExecutor:
        with self._lock:
            if self._reader_pool is None:
                self._reader_pool = ThreadPoolExecutor(
                    max_workers=max(1, conf.get(SHUFFLE_READER_THREADS)),
                    thread_name_prefix="shuffle-reader")
            return self._reader_pool

    # -- lifecycle ---------------------------------------------------------
    def register(self, n_partitions: int, schema: Schema
                 ) -> HostShuffleHandle:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            h = HostShuffleHandle(sid, n_partitions, schema)
            self._handles[sid] = h
            return h

    # -- dead-peer map-output invalidation (ISSUE 20) ----------------------
    def bind_peer_output(self, executor_id: str,
                         handle: HostShuffleHandle, path: str) -> None:
        """Attribute one registered map output to the peer that holds
        it. The default single-process session never binds (no
        heartbeat manager runs), so the registry stays empty and the
        read path pays nothing."""
        with self._lock:
            self._peer_outputs.setdefault(executor_id, []).append(
                (handle.shuffle_id, path))

    def invalidate_peer_outputs(self, executor_id: str) -> int:
        """peer_dead transition -> mark every map output bound to that
        peer invalidated, EXACTLY once (the bindings pop with the
        call): the next read of each routes through the partition-
        granular recompute lane (HostShuffleReader._refresh_invalidated)
        instead of trusting a dead executor's shards. Returns how many
        outputs were invalidated; emits one `map_output_invalidated`
        per output, outside the registry lock."""
        with self._lock:
            bound = self._peer_outputs.pop(executor_id, [])
            handles = {sid: self._handles.get(sid) for sid, _ in bound}
        n = 0
        from ..obs import events as obs_events
        for sid, path in bound:
            h = handles.get(sid)
            if h is None:
                continue  # shuffle already unregistered
            with h.recover_lock:
                if path in h.invalidated:
                    continue
                h.invalidated.add(path)
            n += 1
            obs_events.emit(
                "map_output_invalidated", executor_id=executor_id,
                shuffle_id=sid, map_path=os.path.basename(path),
                has_lineage=path in h.lineage)
        return n

    def unregister(self, handle: HostShuffleHandle) -> None:
        with self._lock:
            self._handles.pop(handle.shuffle_id, None)
            # drop any dead-peer bindings pointing at this shuffle (the
            # invalidation lane must not resurrect an unregistered id)
            if self._peer_outputs:
                sid = handle.shuffle_id
                for eid in list(self._peer_outputs):
                    kept = [b for b in self._peer_outputs[eid]
                            if b[0] != sid]
                    if kept:
                        self._peer_outputs[eid] = kept
                    else:
                        del self._peer_outputs[eid]
        for path in handle.map_outputs:
            for p in (path, path + ".index"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        handle.map_outputs.clear()


_MANAGER: Optional[HostShuffleManager] = None
_MANAGER_LOCK = threading.Lock()


def shuffle_manager() -> HostShuffleManager:
    global _MANAGER
    if _MANAGER is None:
        with _MANAGER_LOCK:
            if _MANAGER is None:
                _MANAGER = HostShuffleManager()
    return _MANAGER


def partition_batch_host(batch: ColumnarBatch, pid: np.ndarray,
                         n_partitions: int) -> List[ColumnarBatch]:
    """Split a batch into per-partition compact host batches given the
    device-computed partition id per row (Spark-exact murmur3 pmod from
    parallel/exchange.partition_ids). Stable within a partition.

    ONE stable argsort-by-pid + ONE whole-batch gather, then each
    partition emits as a gather-free row-range slice (ISSUE 9
    satellite) — O(n log n + cols) per batch instead of the old
    O(partitions x cols) per-partition gathers. Output batches are
    byte-identical to the per-partition-gather formulation (the slice
    helper reproduces host_gather_column's buckets and padding)."""
    order = np.argsort(pid, kind="stable")
    sorted_pid = pid[order]
    bounds = np.searchsorted(sorted_pid, np.arange(n_partitions + 1))
    packed = host_gather_batch(batch, order[: bounds[n_partitions]])
    return [host_slice_batch(packed, int(bounds[p]), int(bounds[p + 1]))
            for p in range(n_partitions)]
