"""Host shuffle data plane — the reference's MULTITHREADED shuffle mode
(RapidsShuffleInternalManagerBase.scala:238 writer / :569 reader; SURVEY
§2.5): partition blocks serialized with a native LZ4 codec on a writer
thread pool into per-map data+index files, fetched and decoded on a reader
pool. This is the always-works mode; the ICI all-to-all exchange
(parallel/exchange.py) is the accelerated data plane, like the reference's
UCX mode.
"""

from .manager import (HostShuffleManager, HostShuffleReader,
                      HostShuffleWriter, shuffle_manager)
from .serializer import (CODEC_COPY, CODEC_LZ4, deserialize_batch,
                         serialize_batch)

__all__ = [
    "HostShuffleManager", "HostShuffleReader", "HostShuffleWriter",
    "shuffle_manager", "serialize_batch", "deserialize_batch",
    "CODEC_COPY", "CODEC_LZ4",
]
