"""Columnar batch wire format for the host shuffle (and the disk spill /
dump tooling): the reference's GpuColumnarBatchSerializer.scala:127 +
JCudfSerialization host-buffer framing, with nvcomp LZ4 replaced by the
native block codec (native/src/blockcodec.cpp).

Frame layout (little-endian):

    magic "TPUSHUF1" | u8 version | u8 codec | u16 flags
    u64 num_rows | u64 schema_hash | u64 raw_len | u64 comp_len
    u64 checksum (xxh64 of the stored payload)
    u32 nbuf | nbuf * u64 buffer byte lengths
    payload (concatenated buffers, possibly compressed)

The buffer *structure* is fully determined by the schema (the reader
always knows it from the plan), so the header carries only byte lengths
plus a schema fingerprint to catch mismatches. Buffers per column, in
order, trimmed to the logical row count (padding never hits the wire):

    fixed-width: validity bitmask (packbits), data[:num_rows]
    string:      validity bitmask, offsets[:num_rows+1] rebased to 0,
                 bytes[:total]
    array:       validity bitmask, offsets[:num_rows+1] rebased to 0,
                 then the child's buffers for offsets[num_rows] elements
    struct:      validity bitmask, then each child's buffers
"""

from __future__ import annotations

import struct
import threading
from typing import List, Tuple

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..columnar.column import (ArrayColumn, Column, MapColumn,
                               StringColumn, StructColumn,
                               bucket_capacity)
from ..native import lz4_available, lz4_compress, lz4_decompress, xxh64
from ..types import Schema

MAGIC = b"TPUSHUF1"
VERSION = 1
CODEC_COPY = 0  # reference CopyCompressionCodec
CODEC_LZ4 = 1   # reference NvcompLZ4CompressionCodec (host analog)


class CorruptFrameError(ValueError):
    """The frame's structure or checksum failed verification: the block
    is damaged (torn write, bit rot, injected corruption). The reader
    quarantines it and recovers by recompute (ISSUE 4 integrity)."""

_HEADER = struct.Struct("<8sBBHQQQQQI")


def schema_fingerprint(schema: Schema) -> int:
    return xxh64(repr([(f.name, f.data_type.simple_name())
                       for f in schema.fields]).encode())


# ---------------------------------------------------------------------------
# host-side column encode (device → trimmed numpy buffers)
# ---------------------------------------------------------------------------

def _np(x) -> np.ndarray:
    return np.asarray(x)


def _rebase_offsets(off: np.ndarray, n: int, start: int = 0) -> np.ndarray:
    out = off[start: start + n + 1].astype(np.int32, copy=True)
    return out - out[0]


def _encode_column(col: Column, n: int, out: List[np.ndarray],
                   start: int = 0) -> None:
    """Encode rows [start, start+n) of `col` into trimmed buffers. The
    `start` base makes non-compacted children (array-of-X whose referenced
    span begins past element 0) encode correctly instead of asserting."""
    out.append(np.packbits(
        _np(col.validity)[start: start + n].astype(np.bool_),
        bitorder="little"))
    if isinstance(col, StringColumn):
        off = _np(col.offsets)
        out.append(_rebase_offsets(off, n, start))
        lo = int(off[start])
        hi = int(off[start + n]) if n else lo
        out.append(_np(col.data)[lo:hi].astype(np.uint8, copy=False))
    elif isinstance(col, ArrayColumn):
        off = _np(col.offsets)
        out.append(_rebase_offsets(off, n, start))
        # the child is encoded for exactly the referenced element span
        lo = int(off[start])
        hi = int(off[start + n]) if n else lo
        _encode_column(col.child, hi - lo, out, start=lo)
    elif isinstance(col, StructColumn):
        for ch in col.children:
            _encode_column(ch, n, out, start=start)
    elif isinstance(col, MapColumn):
        off = _np(col.offsets)
        out.append(_rebase_offsets(off, n, start))
        lo = int(off[start])
        hi = int(off[start + n]) if n else lo
        _encode_column(col.keys, hi - lo, out, start=lo)
        _encode_column(col.values, hi - lo, out, start=lo)
    else:
        out.append(np.ascontiguousarray(_np(col.data)[start: start + n]))


def _decode_column(dtype, n: int, bufs: List[bytes], pos: int,
                   capacity: int) -> Tuple[Column, int]:
    """Decode one column's buffers into a column whose leaves follow
    the active build mode (`columnar.column._dev`): numpy under
    `host_build()` — the ISSUE 10 decode path, so the whole batch can
    promote to device as ONE packed upload — device-per-buffer
    otherwise."""
    from ..columnar.column import _dev
    from ..types import ArrayType, StringType, StructType

    vbits = np.frombuffer(bufs[pos], dtype=np.uint8)
    pos += 1
    validity = np.unpackbits(vbits, count=n, bitorder="little").astype(
        np.bool_) if n else np.zeros(0, np.bool_)
    vpad = np.zeros(capacity, np.bool_)
    vpad[:n] = validity

    if isinstance(dtype, StructType):
        kids = []
        for f in dtype.fields:
            k, pos = _decode_column(f.data_type, n, bufs, pos, capacity)
            kids.append(k)
        return StructColumn(tuple(kids), _dev(vpad), dtype), pos

    from ..types import DecimalType, LongType
    if isinstance(dtype, DecimalType) and dtype.precision > 18:
        from ..columnar.column import Decimal128Column
        hi, pos = _decode_column(LongType(), n, bufs, pos, capacity)
        lo, pos = _decode_column(LongType(), n, bufs, pos, capacity)
        return Decimal128Column((hi, lo), _dev(vpad), dtype), pos

    if isinstance(dtype, ArrayType):
        off = np.frombuffer(bufs[pos], dtype=np.int32)
        pos += 1
        opad = np.zeros(capacity + 1, np.int32)
        opad[: n + 1] = off
        opad[n + 1:] = off[n] if n else 0
        child_n = int(off[n]) if n else 0
        child_cap = bucket_capacity(max(child_n, 1))
        child, pos = _decode_column(dtype.element_type, child_n, bufs, pos,
                                    child_cap)
        return ArrayColumn(child, _dev(opad), _dev(vpad), dtype), pos

    from ..types import MapType
    if isinstance(dtype, MapType):
        from ..columnar.column import MapColumn
        off = np.frombuffer(bufs[pos], dtype=np.int32)
        pos += 1
        opad = np.zeros(capacity + 1, np.int32)
        opad[: n + 1] = off
        opad[n + 1:] = off[n] if n else 0
        entry_n = int(off[n]) if n else 0
        ecap = bucket_capacity(max(entry_n, 1))
        keys, pos = _decode_column(dtype.key_type, entry_n, bufs, pos,
                                   ecap)
        vals, pos = _decode_column(dtype.value_type, entry_n, bufs, pos,
                                   ecap)
        return MapColumn(keys, vals, _dev(opad), _dev(vpad), dtype), pos

    if dtype.jnp_dtype is None or isinstance(dtype, StringType):
        off = np.frombuffer(bufs[pos], dtype=np.int32)
        pos += 1
        data = np.frombuffer(bufs[pos], dtype=np.uint8)
        pos += 1
        opad = np.zeros(capacity + 1, np.int32)
        opad[: n + 1] = off
        opad[n + 1:] = off[n] if n else 0
        byte_cap = bucket_capacity(max(len(data), 1))
        dpad = np.zeros(byte_cap, np.uint8)
        dpad[: len(data)] = data
        return StringColumn(_dev(dpad), _dev(opad), _dev(vpad),
                            dtype), pos

    data = np.frombuffer(bufs[pos], dtype=dtype.jnp_dtype)
    pos += 1
    dpad = np.zeros(capacity, dtype.jnp_dtype)
    dpad[:n] = data
    return Column(_dev(dpad), _dev(vpad), dtype), pos


# ---------------------------------------------------------------------------
# frame encode/decode
# ---------------------------------------------------------------------------

def serialize_batch(batch: ColumnarBatch, codec: int = None) -> bytes:
    """Batch → one self-checking frame. Device padding is trimmed; string
    and array payloads keep only referenced bytes/elements."""
    n = batch.num_rows_host
    bufs: List[np.ndarray] = []
    for col in batch.columns:
        _encode_column(col, n, bufs)
    return _frame_from_bufs(bufs, n, batch.schema, codec)


def _frame_from_bufs(bufs: List[np.ndarray], n: int, schema: Schema,
                     codec: int = None) -> bytes:
    """Shared frame assembly: trimmed buffers -> one self-checking
    frame (the byte layout both serialize_batch and serialize_slice
    produce — the slice path is byte-identical by construction)."""
    if codec is None:
        codec = CODEC_LZ4 if lz4_available() else CODEC_COPY
    raw_parts = [np.ascontiguousarray(b).tobytes() for b in bufs]
    raw = b"".join(raw_parts)
    if codec == CODEC_LZ4:
        payload = lz4_compress(raw)
        if len(payload) >= len(raw):  # incompressible: store raw
            codec, payload = CODEC_COPY, raw
    else:
        payload = raw
    sizes = struct.pack(f"<{len(raw_parts)}Q", *map(len, raw_parts))
    # the checksum covers the WHOLE frame — header (with the checksum
    # field zeroed), size table and payload. Header fields are live
    # decode inputs (codec selects decompression, raw_len sizes it, the
    # size table is sliced by n/nbuf): a flipped bit in any of them must
    # be a detected corruption, not garbage buffers or a misclassified
    # schema mismatch
    shash = schema_fingerprint(schema)
    hdr0 = _HEADER.pack(MAGIC, VERSION, codec, 0, n, shash,
                        len(raw), len(payload), 0, len(raw_parts))
    chk = xxh64(hdr0 + sizes + payload)
    header = _HEADER.pack(MAGIC, VERSION, codec, 0, n, shash,
                          len(raw), len(payload), chk, len(raw_parts))
    return header + sizes + payload


def serialize_slice(batch: ColumnarBatch, lo: int, hi: int,
                    codec: int = None) -> bytes:
    """Encode rows [lo, hi) of a host-resident batch as one frame —
    byte-identical to `serialize_batch(host_gather_batch(batch,
    arange(lo, hi)))` but with ZERO gathers: offsets rebase in place,
    validity lanes and payload bytes slice (ISSUE 9). The device
    shuffle partitioner lands the batch partition-ordered, so every
    partition is exactly such a row range."""
    n = hi - lo
    assert 0 <= lo <= hi, (lo, hi)
    bufs: List[np.ndarray] = []
    for col in batch.columns:
        _encode_column(col, n, bufs, start=lo)
    return _frame_from_bufs(bufs, n, batch.schema, codec)


def deserialize_batch(frame: bytes, schema: Schema,
                      device: bool = True,
                      fault_key: str = None) -> ColumnarBatch:
    """Frame -> batch. Columns decode host-resident; with `device`
    (default) the batch promotes through the packed upload engine (ONE
    transfer when packedUpload is on — the shuffle-read ingest seam,
    ISSUE 10), drawing its `device.dispatch` chaos verdicts under
    `fault_key` (callers on pool/producer threads should pass their
    work-item identity so seeded placement is schedule-independent).
    `device=False` returns the host-backed batch so the caller can
    promote at its own seam (the exchange promotes on its pipeline
    producer thread, with metric attribution and per-batch chaos
    keys)."""
    if len(frame) < _HEADER.size:
        raise CorruptFrameError("truncated shuffle frame header")
    (magic, version, codec, flags, n, shash, raw_len, comp_len, chk,
     nbuf) = _HEADER.unpack_from(frame, 0)
    if magic != MAGIC or version != VERSION:
        raise CorruptFrameError("not a TPU shuffle frame")
    off = _HEADER.size
    if len(frame) < off + 8 * nbuf:
        raise CorruptFrameError("truncated shuffle frame size table")
    sizes = struct.unpack_from(f"<{nbuf}Q", frame, off)
    sizes_bytes = frame[off: off + 8 * nbuf]
    off += 8 * nbuf
    payload = frame[off: off + comp_len]
    hdr0 = _HEADER.pack(magic, version, codec, flags, n, shash,
                        raw_len, comp_len, 0, nbuf)
    if len(payload) != comp_len or xxh64(hdr0 + sizes_bytes + payload) != chk:
        raise CorruptFrameError(
            "shuffle frame checksum mismatch (corrupt block)")
    # checksum verified: a fingerprint mismatch now is a REAL schema
    # disagreement (an engine bug), not bit rot — fail loudly, don't
    # quarantine-and-recompute our way past it
    if shash != schema_fingerprint(schema):
        raise ValueError("shuffle frame schema mismatch")
    raw = lz4_decompress(payload, raw_len) if codec == CODEC_LZ4 else payload
    bufs: List[bytes] = []
    p = 0
    for s in sizes:
        bufs.append(raw[p: p + s])
        p += s
    capacity = bucket_capacity(max(n, 1))
    from ..columnar.column import host_build
    cols: List[Column] = []
    pos = 0
    with host_build():
        for f in schema.fields:
            c, pos = _decode_column(f.data_type, n, bufs, pos, capacity)
            cols.append(c)
    if not device:
        return ColumnarBatch(cols, n, schema)
    from ..columnar.upload import to_device_batch
    return to_device_batch(cols, n, schema, fault_key=fault_key,
                           seam="shuffle")


# ---------------------------------------------------------------------------
# host row gather (writer-side partition split)
# ---------------------------------------------------------------------------

#: process-cumulative count of host-side row gathers (top-level
#: host_gather_column calls; child recursions don't double-count).
#: The device partition lane (ISSUE 9) pins this at ZERO per written
#: batch on the hash/roundrobin/single lanes — the structural test and
#: bench.py's {"shuffle": ...} block both read it.
_host_gathers = 0
_host_gathers_lock = threading.Lock()


def host_gather_calls() -> int:
    with _host_gathers_lock:
        return _host_gathers


def host_gather_column(col: Column, idx: np.ndarray,
                       _toplevel: bool = True) -> Column:
    """Row-gather a device column into a compact host-backed column (used
    by the shuffle writer to split a batch into partition blocks). The
    result's arrays are numpy; serialize_batch consumes them directly."""
    from ..types import ArrayType  # noqa: F401

    if _toplevel:
        global _host_gathers
        with _host_gathers_lock:
            _host_gathers += 1
    validity = _np(col.validity)[idx] if len(idx) else np.zeros(0, np.bool_)
    cap = bucket_capacity(max(len(idx), 1))
    vpad = np.zeros(cap, np.bool_)
    vpad[: len(idx)] = validity

    if isinstance(col, StringColumn):
        off = _np(col.offsets)
        data = _np(col.data)
        starts = off[idx]
        lens = (off[idx + 1] - starts).astype(np.int64)
        total = int(lens.sum())
        new_off = np.zeros(cap + 1, np.int32)
        np.cumsum(lens, out=new_off[1: len(idx) + 1])
        new_off[len(idx) + 1:] = new_off[len(idx)]
        out = np.zeros(bucket_capacity(max(total, 1)), np.uint8)
        if total:
            cum = np.concatenate([[0], np.cumsum(lens)[:-1]])
            byte_idx = (np.repeat(starts, lens)
                        + np.arange(total) - np.repeat(cum, lens))
            out[:total] = data[byte_idx]
        return StringColumn(out, new_off, vpad, col.dtype)

    if isinstance(col, ArrayColumn):
        off = _np(col.offsets)
        starts = off[idx]
        lens = (off[idx + 1] - starts).astype(np.int64)
        total = int(lens.sum())
        new_off = np.zeros(cap + 1, np.int32)
        np.cumsum(lens, out=new_off[1: len(idx) + 1])
        new_off[len(idx) + 1:] = new_off[len(idx)]
        if total:
            cum = np.concatenate([[0], np.cumsum(lens)[:-1]])
            elem_idx = (np.repeat(starts, lens)
                        + np.arange(total) - np.repeat(cum, lens))
        else:
            elem_idx = np.zeros(0, np.int64)
        child = host_gather_column(col.child, elem_idx, _toplevel=False)
        return ArrayColumn(child, new_off, vpad,
                           col.dtype)

    if isinstance(col, MapColumn):
        off = _np(col.offsets)
        starts = off[idx]
        lens = (off[idx + 1] - starts).astype(np.int64)
        total = int(lens.sum())
        new_off = np.zeros(cap + 1, np.int32)
        np.cumsum(lens, out=new_off[1: len(idx) + 1])
        new_off[len(idx) + 1:] = new_off[len(idx)]
        if total:
            cum = np.concatenate([[0], np.cumsum(lens)[:-1]])
            entry_idx = (np.repeat(starts, lens)
                         + np.arange(total) - np.repeat(cum, lens))
        else:
            entry_idx = np.zeros(0, np.int64)
        keys = host_gather_column(col.keys, entry_idx, _toplevel=False)
        vals = host_gather_column(col.values, entry_idx, _toplevel=False)
        return MapColumn(keys, vals, new_off, vpad, col.dtype)

    if isinstance(col, StructColumn):
        kids = tuple(host_gather_column(c, idx, _toplevel=False)
                     for c in col.children)
        return type(col)(kids, vpad, col.dtype)  # incl. Decimal128

    data = _np(col.data)[idx] if len(idx) else \
        np.zeros(0, _np(col.data).dtype)
    dpad = np.zeros(cap, data.dtype)
    dpad[: len(idx)] = data
    return Column(dpad, vpad, col.dtype)


def host_gather_batch(batch: ColumnarBatch, idx: np.ndarray
                      ) -> ColumnarBatch:
    cols = [host_gather_column(c, idx) for c in batch.columns]
    return ColumnarBatch(cols, len(idx), batch.schema)


# ---------------------------------------------------------------------------
# host row-range slice (partition emission without gathers)
# ---------------------------------------------------------------------------

def host_slice_column(col: Column, lo: int, hi: int) -> Column:
    """Rows [lo, hi) of a host-backed column as a compact column — the
    gather-free partition emission (ISSUE 9 satellite): offsets rebase
    by subtraction, validity/data/bytes copy as contiguous slices.
    Output arrays match host_gather_column(col, arange(lo, hi)) exactly
    (same capacity buckets, same padding), so serialized frames are
    byte-identical between the two paths."""
    n = hi - lo
    cap = bucket_capacity(max(n, 1))
    vpad = np.zeros(cap, np.bool_)
    vpad[:n] = _np(col.validity)[lo:hi]

    def _sliced_offsets(off: np.ndarray):
        base = int(off[lo])
        end = int(off[hi]) if n else base
        new_off = np.zeros(cap + 1, np.int32)
        new_off[: n + 1] = off[lo: hi + 1] - base
        new_off[n + 1:] = new_off[n]
        return new_off, base, end

    if isinstance(col, StringColumn):
        new_off, base, end = _sliced_offsets(_np(col.offsets))
        out = np.zeros(bucket_capacity(max(end - base, 1)), np.uint8)
        out[: end - base] = _np(col.data)[base:end]
        return StringColumn(out, new_off, vpad, col.dtype)

    if isinstance(col, ArrayColumn):
        new_off, base, end = _sliced_offsets(_np(col.offsets))
        child = host_slice_column(col.child, base, end)
        return ArrayColumn(child, new_off, vpad, col.dtype)

    if isinstance(col, MapColumn):
        new_off, base, end = _sliced_offsets(_np(col.offsets))
        keys = host_slice_column(col.keys, base, end)
        vals = host_slice_column(col.values, base, end)
        return MapColumn(keys, vals, new_off, vpad, col.dtype)

    if isinstance(col, StructColumn):
        kids = tuple(host_slice_column(c, lo, hi) for c in col.children)
        return type(col)(kids, vpad, col.dtype)  # incl. Decimal128

    data = _np(col.data)
    dpad = np.zeros(cap, data.dtype)
    dpad[:n] = data[lo:hi]
    return Column(dpad, vpad, col.dtype)


def host_slice_batch(batch: ColumnarBatch, lo: int, hi: int
                     ) -> ColumnarBatch:
    cols = [host_slice_column(c, lo, hi) for c in batch.columns]
    return ColumnarBatch(cols, hi - lo, batch.schema)
