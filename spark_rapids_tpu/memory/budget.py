"""HBM budget manager — the RMM-pool analog (reference
GpuDeviceManager.scala:275 initializeRmm + DeviceMemoryEventHandler.scala).

XLA owns the physical HBM allocator; this layer does *accounting*: operators
reserve their padded worst-case footprint before launching device programs.
When a reservation would exceed the budget, registered spillables are
synchronously spilled (largest-priority first) until it fits — the
DeviceMemoryEventHandler loop (:58-90) — else TpuRetryOOM is raised for the
retry framework to handle.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..config import HBM_BUDGET_BYTES, HBM_POOL_FRACTION, active_conf
from .retry import TpuRetryOOM

_DEFAULT_HBM = 16 << 30  # v5e/v5p chips have 16 GiB HBM per core


class MemoryBudget:
    def __init__(self, limit_bytes: Optional[int] = None):
        if limit_bytes is None:
            conf = active_conf()
            override = conf.get(HBM_BUDGET_BYTES)
            if override:
                limit_bytes = override
            else:
                limit_bytes = int(_detect_hbm() * conf.get(HBM_POOL_FRACTION))
        self.limit = limit_bytes
        self.used = 0
        self._lock = threading.Condition()
        self.peak = 0
        self.spill_requests = 0

    def reserve(self, nbytes: int):
        """Reserve accounting space; spill-then-raise on pressure."""
        with self._lock:
            if self.used + nbytes <= self.limit:
                self.used += nbytes
                self.peak = max(self.peak, self.used)
                return
        # out of budget: try to make room by spilling catalog buffers
        from .catalog import buffer_catalog
        needed = nbytes - (self.limit - self.used)
        freed = buffer_catalog().synchronous_spill(needed)
        with self._lock:
            self.spill_requests += 1
            if self.used + nbytes <= self.limit:
                self.used += nbytes
                self.peak = max(self.peak, self.used)
                return
        raise TpuRetryOOM(
            f"HBM budget exhausted: need {nbytes}, used {self.used} of "
            f"{self.limit} (freed {freed} by spill)")

    def release(self, nbytes: int):
        with self._lock:
            self.used = max(0, self.used - nbytes)
            self._lock.notify_all()


def _detect_hbm() -> int:
    try:
        import jax
        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return _DEFAULT_HBM


_budget: Optional[MemoryBudget] = None
_budget_lock = threading.Lock()


def memory_budget() -> MemoryBudget:
    global _budget
    with _budget_lock:
        if _budget is None:
            _budget = MemoryBudget()
        return _budget


def reset_memory_budget(limit_bytes: Optional[int] = None):
    """Test hook: install a fresh (possibly tiny) budget — the analog of the
    reference's 512MiB test RMM pool (RmmSparkRetrySuiteBase.scala:35)."""
    global _budget
    with _budget_lock:
        _budget = MemoryBudget(limit_bytes)
    return _budget


def spill_for_retry():
    """Between OOM retries, aggressively push device buffers down a tier
    (reference: synchronous spill in DeviceMemoryEventHandler)."""
    from .catalog import buffer_catalog
    buffer_catalog().synchronous_spill(None)
