"""HBM budget manager — the RMM-pool analog (reference
GpuDeviceManager.scala:275 initializeRmm + DeviceMemoryEventHandler.scala).

XLA owns the physical HBM allocator; this layer does *accounting*: operators
reserve their padded worst-case footprint before launching device programs.
When a reservation would exceed the budget, registered spillables are
synchronously spilled (largest-priority first) until it fits — the
DeviceMemoryEventHandler loop (:58-90) — else TpuRetryOOM is raised for the
retry framework to handle.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..config import HBM_BUDGET_BYTES, HBM_POOL_FRACTION, active_conf
from .retry import TpuRetryOOM

_DEFAULT_HBM = 16 << 30  # v5e/v5p chips have 16 GiB HBM per core


class MemoryBudget:
    def __init__(self, limit_bytes: Optional[int] = None):
        if limit_bytes is None:
            conf = active_conf()
            override = conf.get(HBM_BUDGET_BYTES)
            if override:
                limit_bytes = override
            else:
                limit_bytes = int(_detect_hbm() * conf.get(HBM_POOL_FRACTION))
        self.limit = limit_bytes
        self.used = 0
        self._lock = threading.Condition()
        self.peak = 0
        self.spill_requests = 0

    def reserve(self, nbytes: int, wait_for_writeback: bool = True):
        """Reserve accounting space; spill-then-raise on pressure.

        `wait_for_writeback=False` is REQUIRED when the caller holds the
        buffer-catalog lock (catalog._unspill_locked): draining waits on
        the spill-writer thread, which needs that lock to finalize — a
        guaranteed deadlock. Without the drain, pressure surfaces as
        TpuRetryOOM and the retry loop waits the writebacks out instead.

        Per-query quota (ISSUE 7): under the workload governor, a query
        past its soft share of the budget that hits THIS pressure path
        spills its OWN catalog entries (quota_spill event) and raises
        its own TpuRetryOOM when that is not enough — it must not push a
        neighbor's working set down a tier. The quota is consulted only
        here (pressure), never on the in-budget fast path, so a lone or
        ungoverned query pays nothing.
        """
        with self._lock:
            if self.used + nbytes <= self.limit:
                self.used += nbytes
                self.peak = max(self.peak, self.used)
                return
        # out of budget: try to make room by spilling catalog buffers
        from .catalog import buffer_catalog
        from ..exec import workload
        needed = nbytes - (self.limit - self.used)
        hops: list = []
        ticket = workload.current_ticket()
        quota = workload.quota_bytes(self.limit) \
            if ticket is not None else None
        over_quota = quota is not None \
            and ticket.device_bytes + nbytes > quota
        if over_quota:
            # the offender spills the offender: only entries owned by
            # THIS query's ticket are candidates
            freed = buffer_catalog().synchronous_spill(
                needed, events_out=hops, owner=ticket)
            workload.note_quota_spill(ticket, nbytes, quota, freed)
        else:
            freed = buffer_catalog().synchronous_spill(needed,
                                                       events_out=hops)
        with self._lock:
            self.spill_requests += 1
            if self.used + nbytes <= self.limit:
                self.used += nbytes
                self.peak = max(self.peak, self.used)
                return
        # async writeback (spill.asyncWrite) frees the budget only when
        # each device->host copy LANDS: wait the in-flight hops out
        # before declaring OOM
        if wait_for_writeback:
            # first only the copies THIS spill queued — a full-queue
            # drain would serialize the reserve behind unrelated (and
            # later-enqueued) hops from concurrently spilling threads
            for ev in hops:
                ev.wait()
            with self._lock:
                if self.used + nbytes <= self.limit:
                    self.used += nbytes
                    self.peak = max(self.peak, self.used)
                    return
            if not over_quota:
                # last resort: hops queued by OTHER threads' spills may
                # still hold the bytes this reservation needs. An
                # over-quota query skips it — waiting out NEIGHBORS'
                # writebacks to grab the bytes they freed is exactly the
                # stealing the quota exists to stop; its own retry lane
                # (spill_for_retry between attempts) settles instead.
                buffer_catalog().drain_writeback()
                with self._lock:
                    if self.used + nbytes <= self.limit:
                        self.used += nbytes
                        self.peak = max(self.peak, self.used)
                        return
        if over_quota:
            raise TpuRetryOOM(
                f"per-query memory quota exceeded under pressure: need "
                f"{nbytes}, query holds {ticket.device_bytes} of a "
                f"{quota}-byte share ({self.used} of {self.limit} total; "
                f"freed {freed} from own entries)")
        raise TpuRetryOOM(
            f"HBM budget exhausted: need {nbytes}, used {self.used} of "
            f"{self.limit} (freed {freed} by spill)")

    def release(self, nbytes: int):
        with self._lock:
            self.used = max(0, self.used - nbytes)
            self._lock.notify_all()


def _detect_hbm() -> int:
    try:
        import jax
        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return _DEFAULT_HBM


_budget: Optional[MemoryBudget] = None
_budget_lock = threading.Lock()


def memory_budget() -> MemoryBudget:
    global _budget
    with _budget_lock:
        if _budget is None:
            _budget = MemoryBudget()
        return _budget


def reset_memory_budget(limit_bytes: Optional[int] = None):
    """Test hook: install a fresh (possibly tiny) budget — the analog of the
    reference's 512MiB test RMM pool (RmmSparkRetrySuiteBase.scala:35)."""
    global _budget
    with _budget_lock:
        _budget = MemoryBudget(limit_bytes)
    return _budget


def spill_for_retry():
    """Between OOM retries, aggressively push device buffers down a tier
    (reference: synchronous spill in DeviceMemoryEventHandler).

    With spill.asyncWrite the hand-offs queued here (and writebacks
    already in flight — including the ones behind a
    reserve(wait_for_writeback=False) TpuRetryOOM from the
    unspill-under-catalog-lock path, which cannot drain itself) only
    free budget when the writer lands each device->host copy. No
    catalog lock is held between retry attempts, so this is the one
    safe place to wait the writer out before the next attempt —
    otherwise the retry loop spins through its attempts in microseconds
    while the bytes it needs are still queued behind the writer thread.

    Per-query quota (ISSUE 7): the isolation reserve() enforces must
    hold on THIS lane too — a quota TpuRetryOOM lands exactly here one
    frame up, and an unfiltered pass would push every neighbor's
    working set down a tier and wait their writebacks out so the
    offender can take the bytes they freed. While the current query is
    still over its share, only its own entries spill and only its own
    hops are waited; once it drops back under, it is no longer the
    offender and the global pass applies.
    """
    from .catalog import buffer_catalog
    from ..exec import workload
    cat = buffer_catalog()
    hops: list = []
    ticket = workload.current_ticket()
    if ticket is not None:
        quota = workload.quota_bytes(memory_budget().limit)
        if quota is not None and ticket.device_bytes > quota:
            cat.synchronous_spill(None, events_out=hops, owner=ticket)
            for ev in hops:
                ev.wait()
            return
    cat.synchronous_spill(None, events_out=hops)
    for ev in hops:
        ev.wait()
    cat.drain_writeback()
