"""Bounded host allocator — the reference's HostAlloc.scala:24 (pinned
pool preferred, bounded non-pinned overflow, blocking until memory frees):
host staging buffers for shuffle/spill/IO must not grow without bound just
because device memory is budgeted.

TPU shape: there is no cudaHostAlloc pinning; "pinned" here is a reserved
fast-lane quota for transfer-critical allocations (spill writes, shuffle
frames) and the rest contends for the bounded general pool. Allocation
blocks (with timeout) instead of failing, mirroring HostAlloc's
synchronous wait-for-free behavior; a timeout raises HostOOM so the
caller's retry machinery can split (the same escalation path as device
OOM, memory/retry.py).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class HostOOM(MemoryError):
    pass


class HostAllocation:
    """Tracked host buffer; release via close() (ARM-style, reference
    withResource discipline)."""

    __slots__ = ("buffer", "nbytes", "pinned", "_pool", "_closed")

    def __init__(self, buffer: np.ndarray, nbytes: int, pinned: bool,
                 pool: "HostAlloc"):
        self.buffer = buffer
        self.nbytes = nbytes
        self.pinned = pinned
        self._pool = pool
        self._closed = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._pool._release(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HostAlloc:
    """Bounded two-lane host memory pool (reference HostAlloc.scala:24,
    :103-111 tryAlloc pinned-first policy)."""

    def __init__(self, limit_bytes: int, pinned_bytes: int = 0):
        assert pinned_bytes <= limit_bytes
        self.limit_bytes = limit_bytes
        self.pinned_bytes = pinned_bytes
        self._lock = threading.Condition()
        self._used = 0          # general lane
        self._pinned_used = 0   # reserved fast lane

    # -- accounting --------------------------------------------------------
    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used + self._pinned_used

    @property
    def free_bytes(self) -> int:
        return self.limit_bytes - self.used_bytes

    def _try_reserve(self, nbytes: int, prefer_pinned: bool) -> Optional[bool]:
        """Returns pinned-lane flag, or None if nothing fits right now."""
        if prefer_pinned \
                and self._pinned_used + nbytes <= self.pinned_bytes:
            self._pinned_used += nbytes
            return True
        general_cap = self.limit_bytes - self.pinned_bytes
        if self._used + nbytes <= general_cap:
            self._used += nbytes
            return False
        return None

    # -- API ---------------------------------------------------------------
    def try_alloc(self, nbytes: int, prefer_pinned: bool = True
                  ) -> Optional[HostAllocation]:
        """Non-blocking (reference HostAlloc.tryAlloc)."""
        with self._lock:
            lane = self._try_reserve(nbytes, prefer_pinned)
        if lane is None:
            return None
        return HostAllocation(np.empty(nbytes, np.uint8), nbytes, lane,
                              self)

    def alloc(self, nbytes: int, prefer_pinned: bool = True,
              timeout_s: float = 30.0) -> HostAllocation:
        """Blocking: waits for releases like the reference's synchronous
        host alloc; HostOOM after timeout_s (callers' retry/split logic
        then shrinks the request)."""
        # a request can only ever fit in a lane it is ALLOWED to use;
        # waiting on a larger one would stall the full timeout against
        # an empty pool (non-pinned requests never enter the fast lane)
        general_cap = self.limit_bytes - self.pinned_bytes
        serveable = max(general_cap,
                        self.pinned_bytes if prefer_pinned else 0)
        if nbytes > serveable:
            raise HostOOM(
                f"request {nbytes} exceeds the largest host lane "
                f"({serveable} of {self.limit_bytes} total)")
        deadline = None
        with self._lock:
            while True:
                lane = self._try_reserve(nbytes, prefer_pinned)
                if lane is not None:
                    break
                import time
                if deadline is None:
                    deadline = time.monotonic() + timeout_s
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._lock.wait(remaining):
                    raise HostOOM(
                        f"host allocation of {nbytes} bytes timed out "
                        f"({self.used_bytes}/{self.limit_bytes} in use)")
        return HostAllocation(np.empty(nbytes, np.uint8), nbytes, lane,
                              self)

    def _release(self, a: HostAllocation) -> None:
        with self._lock:
            if a.pinned:
                self._pinned_used -= a.nbytes
            else:
                self._used -= a.nbytes
            self._lock.notify_all()


_DEFAULT: Optional[HostAlloc] = None
_DEFAULT_LOCK = threading.Lock()


def host_alloc(conf=None) -> HostAlloc:
    """Process-wide pool sized from spark.rapids.memory.host.* confs."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                from ..config import HOST_SPILL_LIMIT, active_conf
                c = conf or active_conf()
                limit = c.get(HOST_SPILL_LIMIT)
                _DEFAULT = HostAlloc(limit, pinned_bytes=limit // 4)
    return _DEFAULT
