"""Device manager — plugin-lifecycle device/mesh acquisition (reference
GpuDeviceManager.scala:115 setGpuDeviceAndAcquire, :150
initializeGpuAndMemory). On TPU the 'device' is a jax device (one chip per
executor, the SURVEY §2.10 pinning model) or a Mesh over many for the ICI
shuffle/collective path."""

from __future__ import annotations

import threading
from typing import Optional

import jax

from .budget import memory_budget, reset_memory_budget
from .semaphore import reset_tpu_semaphore


class DeviceManager:
    def __init__(self):
        self.initialized = False
        self.device = None
        self.mesh = None
        self._lock = threading.Lock()

    def initialize(self, device_ordinal: int = 0,
                   mesh_axes: Optional[dict] = None):
        """Executor init (reference Plugin.scala:484 RapidsExecutorPlugin):
        pick the chip, size the HBM budget, arm the admission semaphore,
        optionally build the pod mesh."""
        with self._lock:
            if self.initialized:
                return self
            devices = jax.devices()
            self.device = devices[min(device_ordinal, len(devices) - 1)]
            memory_budget()  # force budget sizing against this device
            reset_tpu_semaphore()
            if mesh_axes:
                from ..parallel.mesh import build_mesh
                self.mesh = build_mesh(**mesh_axes)
            self.initialized = True
            return self

    def shutdown(self):
        with self._lock:
            self.initialized = False
            self.device = None
            self.mesh = None


_manager: Optional[DeviceManager] = None
_mgr_lock = threading.Lock()


def device_manager() -> DeviceManager:
    global _manager
    with _mgr_lock:
        if _manager is None:
            _manager = DeviceManager()
        return _manager
