"""SpillableBatch — handle wrapper letting operator state spill while not
actively in use (reference SpillableColumnarBatch.scala). Operators hold
these between kernel launches instead of raw device batches so the catalog
can steal their memory under pressure."""

from __future__ import annotations

from typing import Optional

from ..columnar.batch import ColumnarBatch
from .catalog import ACTIVE_BATCHING_PRIORITY, buffer_catalog


class SpillableBatch:
    def __init__(self, handle: str, num_rows, schema):
        self._handle = handle
        self._num_rows = num_rows  # host int OR device scalar (lazy)
        self._schema = schema
        self._closed = False

    @staticmethod
    def from_batch(batch: ColumnarBatch,
                   priority: int = ACTIVE_BATCHING_PRIORITY,
                   origin: Optional[str] = None) -> "SpillableBatch":
        handle = buffer_catalog().add(batch, priority, origin=origin)
        # keep the row count lazy: forcing it here would put one d2h sync
        # on every operator's per-batch path (row counts are device scalars
        # after filters/joins); only split/debug paths need the host value.
        rows = batch._host_rows if batch._host_rows is not None \
            else batch.num_rows
        return SpillableBatch(handle, rows, batch.schema)

    @property
    def num_rows(self) -> int:
        if not isinstance(self._num_rows, int):
            self._num_rows = int(self._num_rows)
        return self._num_rows

    @property
    def schema(self):
        return self._schema

    def size_bytes(self) -> int:
        return buffer_catalog().size_of(self._handle)

    def get_batch(self) -> ColumnarBatch:
        """Bring the batch to the device and pin it (unspillable) until
        `release()` / `close()`."""
        assert not self._closed, "use after close"
        return buffer_catalog().acquire(self._handle)

    def release(self):
        buffer_catalog().release(self._handle)

    def close(self):
        if not self._closed:
            self._closed = True
            buffer_catalog().remove(self._handle)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
