"""3-tier spill store: DEVICE -> HOST -> DISK.

Port of the *contract* of the reference's RapidsBufferCatalog.scala:62-795 +
RapidsDeviceMemoryStore / RapidsHostMemoryStore / RapidsDiskStore — not the
code: tiers here hold jax device pytrees, numpy host pytrees, and .npz spill
files. The catalog is the single registry; SpillableBatch handles point into
it. Spill policy: spillable (not in-use) entries, lowest priority first,
moved one tier down until the requested bytes are freed
(SpillPriorities.scala semantics).

Background writeback (ISSUE 3, conf spark.rapids.tpu.spill.asyncWrite,
reference analog: the async spill path of RapidsBufferCatalog): a tier
hop marks the entry's TARGET tier under the catalog lock and hands the
actual byte movement (device->host copy / host->disk write + fsync) to a
single writer thread, releasing the triggering operator immediately. A
reader (`acquire`) of an entry whose writeback is still in flight waits
for it to land first, so results are identical with the writer on or
off. Catalog state transitions stay under the existing lock; the writer
takes it only for the brief finalize step, never waits on events, and
disk files are fsync'd before the hop counts as complete.
"""

from __future__ import annotations

import io
import itertools
import os
import queue
import struct
import tempfile
import threading
import time
import uuid
import zlib
from enum import IntEnum
from typing import Dict, List, Optional

import jax
import numpy as np

from ..config import (HOST_SPILL_LIMIT, SPILL_ASYNC_WRITE, SPILL_DIR,
                      active_conf)
from .. import faults


class StorageTier(IntEnum):
    DEVICE = 0
    HOST = 1
    DISK = 2


# reference SpillPriorities.scala
ACTIVE_ON_DECK_PRIORITY = 100
ACTIVE_BATCHING_PRIORITY = 50
OUTPUT_FOR_SHUFFLE_PRIORITY = 0
HOST_MEMORY_BUFFER_PRIORITY = -100


def _leaf_nbytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "nbytes"))


class _Entry:
    __slots__ = ("handle_id", "tier", "device_tree", "host_leaves", "treedef",
                 "disk_path", "nbytes", "priority", "in_use", "closed",
                 "writeback", "pending_device", "owner", "seq", "origin")

    def __init__(self, handle_id, tree, priority, owner=None, seq=0,
                 origin=None):
        self.handle_id = handle_id
        self.tier = StorageTier.DEVICE
        self.device_tree = tree
        self.host_leaves = None
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.nbytes = _leaf_nbytes(tree)
        self.disk_path = None
        self.priority = priority
        self.in_use = 0
        self.closed = False
        #: event of the in-flight async tier hop, None when settled
        self.writeback: Optional[threading.Event] = None
        #: device leaves handed to the writer (to_host hop in flight)
        self.pending_device = None
        #: workload-governor ticket of the admitting query (ISSUE 7):
        #: quota accounting mirrors this entry's budget reserve/release
        #: calls against it, and an over-quota reserve spills only its
        #: owner's entries
        self.owner = owner
        #: deterministic per-catalog registration ordinal — the
        #: fault-injection work-item key (ISSUE 7 satellite): handle_id
        #: is a uuid that differs across runs, this does not
        self.seq = seq
        #: which engine seam registered the buffer (ISSUE 16: the ICI
        #: exchange tags its staged shards "ici_exchange" so the spill
        #: plane can attribute device pressure to the shuffle lane);
        #: None for plain operator state
        self.origin = origin

    @property
    def fault_key(self) -> str:
        return f"spill:{self.seq}"


#: spill file container (ISSUE 4 integrity): magic | u32 crc32 |
#: u64 payload length | npz payload. The CRC is stamped at write and
#: verified at read; a mismatch (bit rot, torn write, injected
#: corruption) quarantines the file and recovers by recompute.
_SPILL_MAGIC = b"SRTPUSP1"
_SPILL_HEADER = struct.Struct("<8sIQ")


class SpillFileCorruption(faults.IntegrityError):
    """Spill file failed its CRC32 / structure check at read."""


def _flush_events(out_events) -> None:
    """Emit buffered (kind, fields) records — called OUTSIDE the
    catalog lock (ISSUE 12 lock-blocking-call fix: the bus takes its
    own lock and writes a file; spill-path emits are buffered under the
    lock and flushed here, the PR 6 workload-governor pattern)."""
    if not out_events:
        return
    from ..obs import events as obs_events
    for kind, fields in out_events:
        obs_events.emit(kind, **fields)
    out_events.clear()


def _write_npz(path: str, host_leaves, key: Optional[str] = None) -> None:
    """Spill file write: CRC32-stamped container, durable (fsync'd)
    before the hop counts as complete. `key` is the owning entry's
    deterministic fault key (ISSUE 7 satellite: the spill writer runs
    on its own thread, so without a work-item key the injection
    PLACEMENT — which entry's write draws the fault — depended on
    thread scheduling; keyed, placement replays exactly)."""
    buf = io.BytesIO()
    # contract: ok lock-blocking-call — reached under the catalog lock
    # only on the SYNC spill lane and the dead-writer drain (both by
    # design: the entry must not be observable mid-hop); steady-state
    # async writes run on the writer thread lock-free
    np.savez(buf, **{str(i): a for i, a in enumerate(host_leaves)})
    payload = buf.getvalue()
    # fault point: kind=io dies here (the entry stays on HOST);
    # kind=corrupt flips a byte of the STORED payload after the true CRC
    # is taken, so the damage is exactly what the read-side check catches
    crc = zlib.crc32(payload)
    payload = faults.apply("spill.disk_write", payload, key=key)
    # contract: ok lock-blocking-call — see the savez note above
    with open(path, "wb") as f:
        f.write(_SPILL_HEADER.pack(_SPILL_MAGIC, crc, len(payload)))
        f.write(payload)
        f.flush()
        # contract: ok lock-blocking-call — see the savez note above
        os.fsync(f.fileno())


def _read_npz(path: str, key: Optional[str] = None) -> List[np.ndarray]:
    """Verified spill file read: any structural or checksum failure
    raises SpillFileCorruption (the caller quarantines + recomputes)."""
    faults.check("spill.disk_read", key=key)
    # contract: ok lock-blocking-call — disk unspill runs under the
    # catalog RLock by design (atomic promotion, module docstring); the
    # async writer never calls this
    with open(path, "rb") as f:
        header = f.read(_SPILL_HEADER.size)
        if len(header) < _SPILL_HEADER.size:
            raise SpillFileCorruption(f"truncated spill header: {path}")
        magic, crc, length = _SPILL_HEADER.unpack(header)
        if magic != _SPILL_MAGIC:
            raise SpillFileCorruption(f"bad spill magic: {path}")
        payload = f.read(length)
    if len(payload) != length or zlib.crc32(payload) != crc:
        raise SpillFileCorruption(f"spill file checksum mismatch: {path}")
    with np.load(io.BytesIO(payload)) as z:
        return [z[str(i)] for i in range(len(z.files))]


class BufferCatalog:
    def __init__(self):
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.RLock()
        self.spilled_device_bytes = 0
        self.spilled_host_bytes = 0
        self._spill_dir: Optional[str] = None
        self._write_q: Optional["queue.Queue"] = None
        self._writer: Optional[threading.Thread] = None
        #: deterministic registration ordinal (fault-injection keys)
        self._add_seq = itertools.count(1)

    # -- registration ------------------------------------------------------
    def add(self, tree, priority: int = ACTIVE_BATCHING_PRIORITY,
            origin: Optional[str] = None) -> str:
        """Register a device pytree; returns a handle id. Accounts its
        footprint against the HBM budget, attributed to the admitting
        query's workload ticket (ISSUE 7 quota accounting). `origin`
        labels the registering seam for introspection
        (bytes_by_origin)."""
        from .budget import memory_budget
        from ..exec import workload
        handle = uuid.uuid4().hex
        owner = workload.current_ticket()
        with self._lock:
            seq = next(self._add_seq)
        entry = _Entry(handle, tree, priority, owner=owner, seq=seq,
                       origin=origin)
        memory_budget().reserve(entry.nbytes)
        workload.charge(owner, entry.nbytes)
        with self._lock:
            self._entries[handle] = entry
        return handle

    def acquire(self, handle: str):
        """Return the device pytree, promoting back up tiers if spilled.
        Marks the entry in-use (unspillable) until `release`. An entry
        whose async writeback is still in flight is waited for OUTSIDE
        the lock (the writer needs the lock to finish the hop)."""
        while True:
            evs: List[tuple] = []
            try:
                with self._lock:
                    entry = self._entries[handle]
                    assert not entry.closed, "acquire after close"
                    ev = entry.writeback
                    if ev is None or ev.is_set():
                        entry.writeback = None
                        if entry.tier != StorageTier.DEVICE:
                            self._unspill_locked(entry, evs)
                        entry.in_use += 1
                        return entry.device_tree
            finally:
                _flush_events(evs)
            # bounded wait + watchdog: a writer that died with this
            # hop still queued would otherwise park us here forever.
            # The lifecycle governor checks here too (ISSUE 6): a
            # cancelled/expired query blocked on an in-flight writeback
            # unwinds with spill-wait phase attribution instead of
            # waiting the hop out
            from ..exec import lifecycle
            from ..obs import phase as obs_phase
            lifecycle.check_current("spill-wait")
            t0w = time.perf_counter_ns()
            try:
                if not ev.wait(timeout=1.0):
                    self._writer_ok()
            finally:
                # phase attribution (ISSUE 17): blocked-on-writeback
                # time, accrued even when check_current raises next
                obs_phase.add("spill-wait",
                              time.perf_counter_ns() - t0w)
            lifecycle.check_current("spill-wait")

    def release(self, handle: str):
        with self._lock:
            entry = self._entries.get(handle)
            if entry is not None:
                entry.in_use = max(0, entry.in_use - 1)

    def remove(self, handle: str):
        from .budget import memory_budget
        with self._lock:
            entry = self._entries.pop(handle, None)
            if entry is None or entry.closed:
                return
            entry.closed = True  # an in-flight writeback sees this and
            # discards its result (incl. unlinking a just-written file)
        if entry.tier == StorageTier.DEVICE:
            memory_budget().release(entry.nbytes)
            from ..exec import workload
            workload.discharge(entry.owner, entry.nbytes)
        if entry.disk_path and os.path.exists(entry.disk_path):
            os.unlink(entry.disk_path)

    def tier_of(self, handle: str) -> StorageTier:
        with self._lock:
            return self._entries[handle].tier

    def size_of(self, handle: str) -> int:
        with self._lock:
            return self._entries[handle].nbytes

    # -- spilling ----------------------------------------------------------
    def synchronous_spill(self, target_bytes: Optional[int],
                          events_out: Optional[List[threading.Event]] = None,
                          owner=None) -> int:
        """Move spillable DEVICE entries to HOST (lowest priority first)
        until target_bytes are freed (None = spill everything spillable).
        Overflows HOST to DISK past the host limit. Returns bytes freed from
        device (reference DeviceMemoryEventHandler.scala:58-90 loop). With
        spill.asyncWrite the copies run on the writer thread and this
        returns as soon as the hand-offs are queued; `events_out` then
        collects each queued device->host hop's completion event, so a
        caller under budget pressure can wait for exactly the copies ITS
        spill started instead of draining the whole writer queue.

        `owner` (ISSUE 7): restrict victims to entries owned by that
        workload ticket — the over-quota reserve path spills the
        offending query's own working set, never a neighbor's."""
        from .budget import memory_budget
        from ..exec import workload
        async_write = bool(active_conf().get(SPILL_ASYNC_WRITE))
        t0s = time.perf_counter_ns()
        freed = 0
        while target_bytes is None or freed < target_bytes:
            evs: List[tuple] = []
            try:
                with self._lock:
                    candidates = [e for e in self._entries.values()
                                  if e.tier == StorageTier.DEVICE and
                                  e.in_use == 0 and not e.closed and
                                  (owner is None or e.owner is owner)]
                    if not candidates:
                        break
                    victim = min(candidates, key=lambda e: e.priority)
                    self._spill_to_host_locked(victim, async_write, evs)
                    if async_write and events_out is not None:
                        events_out.append(victim.writeback)
                    freed += victim.nbytes
            finally:
                # spill/spill_error events land OUTSIDE the catalog
                # lock (ISSUE 12), incl. on the raise path
                _flush_events(evs)
            if not async_write:
                # async: the device buffer is still physically alive in
                # entry.pending_device until the writer's device_get
                # lands — the writer releases the budget then, so the
                # accounting never under-reports live HBM
                memory_budget().release(victim.nbytes)
                workload.discharge(victim.owner, victim.nbytes)
        self._enforce_host_limit(async_write, owner=owner)
        # phase attribution (ISSUE 17): the pass ran on the thread
        # whose reservation hit pressure — its wall is that query's
        # spill-wait share (the async lane's queued hops are waited
        # for, and accrued, at the acquire/budget seams instead)
        from ..obs import phase as obs_phase
        obs_phase.add("spill-wait", time.perf_counter_ns() - t0s)
        if freed:
            # per-query spill attribution (ISSUE 11): the reserving
            # thread's governed query experienced this pressure —
            # active_queries() reports it per in-flight query
            from ..exec import lifecycle
            lifecycle.note_spill(freed)
        return freed

    def _spill_to_host_locked(self, entry: _Entry, async_write: bool,
                              out_events: List[tuple]):
        leaves = jax.tree_util.tree_leaves(entry.device_tree)
        entry.device_tree = None
        entry.tier = StorageTier.HOST
        if async_write:
            # hand the device buffers to the writer and return: the
            # triggering operator is released as soon as the copy starts
            entry.pending_device = leaves
            entry.writeback = threading.Event()
            self._enqueue_writeback("to_host", entry, None,
                                    entry.writeback, out_events)
        else:
            try:
                faults.check("spill.d2h_copy", key=entry.fault_key)
                # contract: ok lock-blocking-call — the SYNC lane
                # (asyncWrite=false) deliberately copies under the
                # catalog lock: the entry must not be observable
                # mid-hop, and the async lane exists precisely for
                # callers that cannot afford this hold
                entry.host_leaves = [np.asarray(jax.device_get(x))
                                     for x in leaves]
            except Exception as e:  # noqa: BLE001 — transient device
                # error mid-copy: the data never left the device — put
                # the entry back intact and surface a task-level retry
                # (the classified recovery for a failed movement)
                entry.device_tree = jax.tree_util.tree_unflatten(
                    entry.treedef, leaves)
                entry.tier = StorageTier.DEVICE
                out_events.append(("spill_error", dict(
                    stage="d2h_copy", sync=True, error=str(e)[:200])))
                from ..faults import TpuTaskRetryError
                raise TpuTaskRetryError(
                    f"device->host spill copy failed: {e}") from e
        self.spilled_device_bytes += entry.nbytes
        out_events.append(("spill", dict(
            tier="device->host", bytes=entry.nbytes,
            priority=entry.priority, background=async_write)))

    def _enforce_host_limit(self, async_write: bool = False, owner=None):
        """`owner` (ISSUE 7): an owner-scoped quota spill must not
        demote NEIGHBORS' host entries to disk either — the host limit
        is soft, and the next unscoped pass re-enforces it globally."""
        limit = active_conf().get(HOST_SPILL_LIMIT)
        evs: List[tuple] = []
        try:
            with self._lock:
                host_entries = [e for e in self._entries.values()
                                if e.tier == StorageTier.HOST
                                and not e.closed
                                and (owner is None or e.owner is owner)]
                host_total = sum(e.nbytes for e in host_entries)
                for e in sorted(host_entries, key=lambda x: x.priority):
                    if host_total <= limit:
                        break
                    # a sync disk-write failure leaves the entry on
                    # HOST (returns False): don't count those bytes as
                    # moved, or the pass stops early without trying
                    # other candidates
                    if self._spill_to_disk_locked(e, async_write, evs):
                        host_total -= e.nbytes
        finally:
            _flush_events(evs)  # spill events outside the lock (ISSUE 12)

    def _spill_to_disk_locked(self, entry: _Entry, async_write: bool,
                              out_events: List[tuple]) -> bool:
        """Returns True when the hop landed (or was queued to the
        writer); False when a sync write failed and the entry stayed on
        the HOST tier."""
        path = os.path.join(self._spill_dir_path(),
                            f"spill-{entry.handle_id}.npz")
        entry.tier = StorageTier.DISK
        if entry.writeback is not None and not entry.writeback.is_set():
            # a device->host copy for this entry is still in flight
            # (asyncWrite toggled off mid-query): the disk hop must go
            # through the writer queue too — FIFO lands it after the
            # copy; waiting here would deadlock on the catalog lock
            async_write = True
        if async_write:
            # FIFO on the single writer thread: a pending to_host hop
            # for this entry lands before this job runs
            entry.writeback = threading.Event()
            self._enqueue_writeback("to_disk", entry, path,
                                    entry.writeback, out_events)
        else:
            try:
                _write_npz(path, entry.host_leaves, key=entry.fault_key)
            except Exception as e:  # noqa: BLE001 — disk full/
                # unwritable: the host copy is intact, so staying on the
                # HOST tier (over its soft limit) beats failing the
                # query; the next enforcement pass will try again
                entry.tier = StorageTier.HOST
                try:
                    os.unlink(path)
                except OSError:
                    pass
                out_events.append(("spill_error", dict(
                    stage="disk_write", sync=True, error=str(e)[:200])))
                return False
            entry.host_leaves = None
            entry.disk_path = path
        self.spilled_host_bytes += entry.nbytes
        out_events.append(("spill", dict(
            tier="host->disk", bytes=entry.nbytes,
            priority=entry.priority, background=async_write)))
        return True

    def _unspill_locked(self, entry: _Entry, out_events: List[tuple]):
        from .budget import memory_budget
        if entry.tier == StorageTier.DISK:
            try:
                entry.host_leaves = _read_npz(entry.disk_path,
                                              key=entry.fault_key)
            except SpillFileCorruption as e:
                # integrity failure: quarantine the evidence (never feed
                # corrupt bytes downstream) and recover by recompute —
                # the task-attempt layer re-executes from the sources
                qpath = entry.disk_path + ".quarantined"
                try:
                    os.replace(entry.disk_path, qpath)
                    entry.disk_path = qpath  # remove() still cleans up
                except OSError:
                    pass
                out_events.append(("integrity_fail", dict(
                    what="spill_file", path=entry.disk_path,
                    bytes=entry.nbytes, error=str(e)[:200])))
                # provenance (ISSUE 6): a spill entry is intermediate
                # state with no captured lineage — the task-retry layer
                # sees this as AMBIGUOUS provenance and takes the
                # whole-plan lane (docs/robustness.md)
                e.provenance = {"kind": "spill_file",
                                "handle": entry.handle_id}
                raise
            except OSError as e:
                out_events.append(("spill_error", dict(
                    stage="disk_read", sync=True, error=str(e)[:200])))
                from ..faults import TpuTaskRetryError
                raise TpuTaskRetryError(
                    f"spill file unreadable: {e}") from e
            os.unlink(entry.disk_path)
            entry.disk_path = None
            entry.tier = StorageTier.HOST
        if entry.tier == StorageTier.HOST:
            # caller holds self._lock: must NOT drain the writer (it
            # needs this lock to finalize) — see MemoryBudget.reserve
            memory_budget().reserve(entry.nbytes,
                                    wait_for_writeback=False)
            from ..exec import workload
            workload.charge(entry.owner, entry.nbytes)
            # unspill ingest seam (ISSUE 10): the whole spilled tree
            # crosses host->device as ONE packed upload (per-leaf
            # jnp.asarray when packedUpload is off), keyed by the
            # entry's deterministic registration ordinal for seeded
            # chaos. The upload can now FAIL (injected device fault /
            # real device error) between the charge above and the tier
            # flip below — unwind both, or the entry stays HOST with
            # the reservation and quota charge leaked forever (remove()
            # only releases DEVICE-tier entries, and a retried acquire
            # would charge again)
            from ..columnar.upload import upload_leaves
            try:
                # contract: ok lock-blocking-call — unspill promotes
                # under the catalog RLock by design (atomic: the entry
                # must not be observable mid-promotion; module
                # docstring); reserve above uses the documented
                # lock-safe wait_for_writeback=False form
                leaves = upload_leaves(entry.host_leaves,
                                       fault_key=f"unspill:{entry.seq}",
                                       seam="unspill")
            except BaseException:
                memory_budget().release(entry.nbytes)
                workload.discharge(entry.owner, entry.nbytes)
                raise
            entry.device_tree = jax.tree_util.tree_unflatten(
                entry.treedef, leaves)
            entry.host_leaves = None
            entry.tier = StorageTier.DEVICE

    def _spill_dir_path(self) -> str:
        if self._spill_dir is None:
            conf_dir = active_conf().get(SPILL_DIR)
            self._spill_dir = conf_dir or tempfile.mkdtemp(prefix="srtpu-spill-")
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    # -- background writer -------------------------------------------------
    def _enqueue_writeback(self, kind: str, entry: _Entry,
                           path: Optional[str], ev: threading.Event,
                           out_events: List[tuple]) -> None:
        """Queue one tier hop's byte movement (caller holds the lock;
        `ev` is THAT hop's completion event — entry.writeback may point
        at a later hop by the time the job runs). A dead writer thread
        (killed by something harsher than the per-job except) is
        detected here: its stranded queue is drained synchronously and a
        fresh writer spawned, so one writer death never wedges spilling
        for the rest of the process."""
        if self._writer is not None and not self._writer.is_alive():
            self._recover_dead_writer_locked(out_events)
        if self._write_q is None:
            self._write_q = queue.Queue()
            self._writer = threading.Thread(
                target=self._writer_loop, args=(self._write_q,),
                name="spill-writer", daemon=True)
            self._writer.start()
        from ..obs import events as obs_events
        # the enqueuing query's id rides the job (ISSUE 12 thread-adopt
        # fix): the singleton writer serves EVERY query — per-job
        # adoption keeps async spill_error events attributed instead of
        # landing with query: null
        # contract: ok lock-blocking-call — unbounded queue: put() never
        # blocks, it is a list append under the queue's own mutex
        self._write_q.put((kind, entry, path, ev,
                           obs_events.current_query_id()))

    def _recover_dead_writer_locked(self, out_events: List[tuple]
                                    ) -> None:
        """Caller holds the catalog lock. Drain the dead writer's queue
        synchronously (running each stranded hop's byte movement on THIS
        thread — the 'queue drained synchronously' watchdog of ISSUE 4)
        and detach it so the next enqueue starts a fresh writer. The
        spill_writer_dead event is buffered into `out_events` (flushed
        by the caller outside the lock, ISSUE 12)."""
        from ..obs import events as obs_events
        q, self._write_q, self._writer = self._write_q, None, None
        out_events.append(("spill_writer_dead", dict(
            pending=q.qsize() if q is not None else 0)))
        if q is None:
            return
        while True:
            try:
                job = q.get_nowait()
            except queue.Empty:
                return
            if job is None:
                q.task_done()
                continue
            kind, entry, path, ev, qid = job
            try:
                # NOTE: we already hold self._lock (RLock) — fine, the
                # writeback takes it re-entrantly for its finalize
                # steps. Each stranded job still runs under ITS query's
                # event attribution, not the detecting thread's.
                obs_events.with_query_id(qid, self._run_writeback,
                                         kind, entry, path)
            except Exception:  # noqa: BLE001 — same contract as the
                pass           # writer loop: the event must still set
            finally:
                ev.set()
                q.task_done()

    def _writer_ok(self) -> None:
        """Watchdog probe used by waiters and the drain/shutdown entry
        points: if the writer died with jobs still queued, drain them
        synchronously. No return value — callers re-check their own
        wait condition afterwards."""
        evs: List[tuple] = []
        try:
            with self._lock:
                if self._writer is not None and \
                        not self._writer.is_alive():
                    self._recover_dead_writer_locked(evs)
        finally:
            _flush_events(evs)

    def _writer_loop(self, q: "queue.Queue") -> None:
        # the queue travels as an argument, not through self._write_q:
        # shutdown_writer detaches the attribute while this thread may
        # still be finishing the drained jobs
        from ..obs import events as obs_events
        while True:
            job = q.get()
            if job is None:
                q.task_done()
                return
            kind, entry, path, ev, qid = job
            try:
                # per-job query attribution (ISSUE 12): the enqueuing
                # thread's id rides the job so the writer's spill_error
                # events don't land with query: null
                obs_events.with_query_id(qid, self._run_writeback,
                                         kind, entry, path)
            except Exception:  # noqa: BLE001 — a failed writeback must
                # not kill the writer; the event is still set so waiters
                # don't hang (they will fail loudly on the missing data)
                pass
            finally:
                ev.set()
                q.task_done()

    def _run_writeback(self, kind: str, entry: _Entry,
                       path: Optional[str]) -> None:
        """One hop's data movement. The expensive part (d2h copy / file
        write + fsync) runs WITHOUT the catalog lock; only the state
        finalize takes it."""
        if kind == "to_host":
            from .budget import memory_budget
            from ..exec import workload
            with self._lock:
                pending = entry.pending_device
                if entry.closed:
                    # removed before the copy started: don't waste a
                    # full d2h transfer on a dead buffer — drop it,
                    # free the budget it still held, and un-count the
                    # hop (no bytes ever moved; keeps the counters
                    # consistent with the failure branches below)
                    entry.pending_device = None
                    if pending is not None:
                        memory_budget().release(entry.nbytes)
                        workload.discharge(entry.owner, entry.nbytes)
                        self.spilled_device_bytes -= entry.nbytes
                    return
            if pending is None:
                return
            try:
                faults.check("spill.d2h_copy", key=entry.fault_key)
                # contract: ok lock-blocking-call — lock-free on the
                # writer thread (steady state); under the catalog RLock
                # only on the dead-writer synchronous drain (recovery)
                host = [np.asarray(jax.device_get(x)) for x in pending]
            except Exception as e:  # noqa: BLE001 — transient device
                # error: the data never left the device; put the entry
                # back on the DEVICE tier intact (budget never released)
                from ..obs import events as obs_events
                # contract: ok lock-blocking-call — lock-free on the
                # writer thread; under the RLock only on the dead-writer
                # drain (rare recovery; the bus lock is the leaf)
                obs_events.emit("spill_error", stage="d2h_copy",
                                sync=False, error=str(e)[:200])
                with self._lock:
                    entry.pending_device = None
                    if not entry.closed:
                        entry.device_tree = jax.tree_util.tree_unflatten(
                            entry.treedef, pending)
                        entry.tier = StorageTier.DEVICE
                        # the hop never happened: un-count it so a
                        # retried spill of this entry isn't double-counted
                        self.spilled_device_bytes -= entry.nbytes
                        return
                memory_budget().release(entry.nbytes)
                workload.discharge(entry.owner, entry.nbytes)
                return
            with self._lock:
                entry.pending_device = None
                if not entry.closed:
                    entry.host_leaves = host
            # the device buffers are dropped HERE (copy landed or entry
            # closed): only now is the HBM actually free
            memory_budget().release(entry.nbytes)
            workload.discharge(entry.owner, entry.nbytes)
            return
        # to_disk: by single-writer FIFO the to_host hop (if any) has
        # already landed, so host_leaves is populated
        with self._lock:
            host = entry.host_leaves
            closed = entry.closed
            if host is None or closed:
                # the disk write will never run (the preceding to_host
                # copy failed and restored the entry to DEVICE, or the
                # buffer was removed first): un-count the bytes
                # _spill_to_disk_locked charged for the hop
                self.spilled_host_bytes -= entry.nbytes
        if closed or host is None:
            return
        try:
            _write_npz(path, host, key=entry.fault_key)
        except Exception as e:  # noqa: BLE001 — disk full/unwritable:
            # the host copy is still intact, so the entry simply stays
            # on the HOST tier; drop any partial file
            from ..obs import events as obs_events
            # contract: ok lock-blocking-call — lock-free on the writer
            # thread; under the RLock only on the dead-writer drain
            # (rare recovery; the bus lock is the leaf)
            obs_events.emit("spill_error", stage="disk_write",
                            sync=False, error=str(e)[:200])
            with self._lock:
                if not entry.closed:
                    entry.tier = StorageTier.HOST
                    # un-count the hop that never landed (a retried
                    # disk spill would double-count this entry)
                    self.spilled_host_bytes -= entry.nbytes
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        with self._lock:
            if entry.closed:
                unlink = True
            else:
                entry.host_leaves = None
                entry.disk_path = path
                unlink = False
        if unlink:
            try:
                os.unlink(path)
            except OSError:
                pass

    def drain_writeback(self) -> None:
        """Block until every queued writeback has landed (test/bench
        hook; queries never need it — acquire() waits per entry)."""
        self._writer_ok()  # a dead writer is drained synchronously here
        with self._lock:  # snapshot: shutdown_writer detaches under
            q = self._write_q  # the same lock
        if q is not None:
            q.join()

    def shutdown_writer(self) -> None:
        """Stop the writer thread after draining (test isolation). The
        queue is DETACHED under the catalog lock first: _enqueue runs
        under that lock, so a concurrent spill either lands its job
        before the drain below or sees _write_q None and starts a fresh
        writer — it can never enqueue onto a queue whose writer already
        exited (that hop's completion event would never be set and a
        later acquire() of the entry would wait forever)."""
        self._writer_ok()  # a dead writer's stranded jobs drain here
        with self._lock:
            q, writer = self._write_q, self._writer
            self._write_q = None
            self._writer = None
        if q is not None:
            q.join()
            q.put(None)
            writer.join()

    # -- introspection (test surface) -------------------------------------
    def device_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if e.tier == StorageTier.DEVICE and not e.closed)

    def num_entries(self) -> int:
        with self._lock:
            return len(self._entries)

    def bytes_by_owner(self):
        """Per-owner resident-byte attribution for the telemetry plane
        (ISSUE 11): ({owner: device bytes}, {owner: host bytes},
        device total, host total), all from ONE lock pass so the
        per-owner sums equal the totals EXACTLY at this snapshot.
        Owners are the admitting workload tickets (`q<ticket_id>`);
        entries from ungoverned queries land under `unowned`. An entry
        whose async writeback is still in flight counts at its TARGET
        tier (the tier field the hop already flipped) — the documented
        one-in-flight-writeback tolerance of the attribution."""
        dev: Dict[str, int] = {}
        host: Dict[str, int] = {}
        dev_total = 0
        host_total = 0
        with self._lock:
            for e in self._entries.values():
                if e.closed:
                    continue
                owner = f"q{e.owner.ticket_id}" if e.owner is not None \
                    else "unowned"
                if e.tier == StorageTier.DEVICE:
                    dev[owner] = dev.get(owner, 0) + e.nbytes
                    dev_total += e.nbytes
                elif e.tier == StorageTier.HOST:
                    host[owner] = host.get(owner, 0) + e.nbytes
                    host_total += e.nbytes
        return dev, host, dev_total, host_total

    def bytes_by_origin(self):
        """Per-seam resident-byte attribution (ISSUE 16): {origin:
        (device bytes, host bytes)} over open entries, untagged entries
        under "untagged". One lock pass, same writeback tolerance as
        bytes_by_owner. The ICI shuffle's staged shards show up here
        under "ici_exchange" — the spill-contract test surface."""
        out: Dict[str, list] = {}
        with self._lock:
            for e in self._entries.values():
                if e.closed:
                    continue
                row = out.setdefault(e.origin or "untagged", [0, 0])
                if e.tier == StorageTier.DEVICE:
                    row[0] += e.nbytes
                elif e.tier == StorageTier.HOST:
                    row[1] += e.nbytes
        return {k: tuple(v) for k, v in out.items()}


_catalog: Optional[BufferCatalog] = None
_catalog_lock = threading.Lock()


def buffer_catalog() -> BufferCatalog:
    global _catalog
    with _catalog_lock:
        if _catalog is None:
            _catalog = BufferCatalog()
        return _catalog


def reset_buffer_catalog() -> BufferCatalog:
    global _catalog
    with _catalog_lock:
        old, _catalog = _catalog, BufferCatalog()
    if old is not None:
        try:
            old.shutdown_writer()
        except Exception:  # noqa: BLE001 — teardown only
            pass
    return _catalog
