"""3-tier spill store: DEVICE -> HOST -> DISK.

Port of the *contract* of the reference's RapidsBufferCatalog.scala:62-795 +
RapidsDeviceMemoryStore / RapidsHostMemoryStore / RapidsDiskStore — not the
code: tiers here hold jax device pytrees, numpy host pytrees, and .npz spill
files. The catalog is the single registry; SpillableBatch handles point into
it. Spill policy: spillable (not in-use) entries, lowest priority first,
moved one tier down until the requested bytes are freed
(SpillPriorities.scala semantics).
"""

from __future__ import annotations

import os
import tempfile
import threading
import uuid
from enum import IntEnum
from typing import Dict, List, Optional

import jax
import numpy as np

from ..config import HOST_SPILL_LIMIT, SPILL_DIR, active_conf


class StorageTier(IntEnum):
    DEVICE = 0
    HOST = 1
    DISK = 2


# reference SpillPriorities.scala
ACTIVE_ON_DECK_PRIORITY = 100
ACTIVE_BATCHING_PRIORITY = 50
OUTPUT_FOR_SHUFFLE_PRIORITY = 0
HOST_MEMORY_BUFFER_PRIORITY = -100


def _leaf_nbytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "nbytes"))


class _Entry:
    __slots__ = ("handle_id", "tier", "device_tree", "host_leaves", "treedef",
                 "disk_path", "nbytes", "priority", "in_use", "closed")

    def __init__(self, handle_id, tree, priority):
        self.handle_id = handle_id
        self.tier = StorageTier.DEVICE
        self.device_tree = tree
        self.host_leaves = None
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.nbytes = _leaf_nbytes(tree)
        self.disk_path = None
        self.priority = priority
        self.in_use = 0
        self.closed = False


class BufferCatalog:
    def __init__(self):
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.RLock()
        self.spilled_device_bytes = 0
        self.spilled_host_bytes = 0
        self._spill_dir: Optional[str] = None

    # -- registration ------------------------------------------------------
    def add(self, tree, priority: int = ACTIVE_BATCHING_PRIORITY) -> str:
        """Register a device pytree; returns a handle id. Accounts its
        footprint against the HBM budget."""
        from .budget import memory_budget
        handle = uuid.uuid4().hex
        entry = _Entry(handle, tree, priority)
        memory_budget().reserve(entry.nbytes)
        with self._lock:
            self._entries[handle] = entry
        return handle

    def acquire(self, handle: str):
        """Return the device pytree, promoting back up tiers if spilled.
        Marks the entry in-use (unspillable) until `release`."""
        from .budget import memory_budget
        with self._lock:
            entry = self._entries[handle]
            assert not entry.closed, "acquire after close"
            if entry.tier != StorageTier.DEVICE:
                self._unspill_locked(entry)
            entry.in_use += 1
            return entry.device_tree

    def release(self, handle: str):
        with self._lock:
            entry = self._entries.get(handle)
            if entry is not None:
                entry.in_use = max(0, entry.in_use - 1)

    def remove(self, handle: str):
        from .budget import memory_budget
        with self._lock:
            entry = self._entries.pop(handle, None)
        if entry is None or entry.closed:
            return
        entry.closed = True
        if entry.tier == StorageTier.DEVICE:
            memory_budget().release(entry.nbytes)
        if entry.disk_path and os.path.exists(entry.disk_path):
            os.unlink(entry.disk_path)

    def tier_of(self, handle: str) -> StorageTier:
        with self._lock:
            return self._entries[handle].tier

    def size_of(self, handle: str) -> int:
        with self._lock:
            return self._entries[handle].nbytes

    # -- spilling ----------------------------------------------------------
    def synchronous_spill(self, target_bytes: Optional[int]) -> int:
        """Move spillable DEVICE entries to HOST (lowest priority first)
        until target_bytes are freed (None = spill everything spillable).
        Overflows HOST to DISK past the host limit. Returns bytes freed from
        device (reference DeviceMemoryEventHandler.scala:58-90 loop)."""
        from .budget import memory_budget
        freed = 0
        while target_bytes is None or freed < target_bytes:
            with self._lock:
                candidates = [e for e in self._entries.values()
                              if e.tier == StorageTier.DEVICE and
                              e.in_use == 0 and not e.closed]
                if not candidates:
                    break
                victim = min(candidates, key=lambda e: e.priority)
                self._spill_to_host_locked(victim)
                freed += victim.nbytes
            memory_budget().release(victim.nbytes)
        self._enforce_host_limit()
        return freed

    def _spill_to_host_locked(self, entry: _Entry):
        leaves = jax.tree_util.tree_leaves(entry.device_tree)
        entry.host_leaves = [np.asarray(jax.device_get(x)) for x in leaves]
        entry.device_tree = None
        entry.tier = StorageTier.HOST
        self.spilled_device_bytes += entry.nbytes
        from ..obs import events as obs_events
        obs_events.emit("spill", tier="device->host", bytes=entry.nbytes,
                        priority=entry.priority)

    def _enforce_host_limit(self):
        limit = active_conf().get(HOST_SPILL_LIMIT)
        with self._lock:
            host_entries = [e for e in self._entries.values()
                            if e.tier == StorageTier.HOST and not e.closed]
            host_total = sum(e.nbytes for e in host_entries)
            for e in sorted(host_entries, key=lambda x: x.priority):
                if host_total <= limit:
                    break
                self._spill_to_disk_locked(e)
                host_total -= e.nbytes

    def _spill_to_disk_locked(self, entry: _Entry):
        path = os.path.join(self._spill_dir_path(),
                            f"spill-{entry.handle_id}.npz")
        np.savez(path, **{str(i): a for i, a in enumerate(entry.host_leaves)})
        entry.host_leaves = None
        entry.disk_path = path
        entry.tier = StorageTier.DISK
        self.spilled_host_bytes += entry.nbytes
        from ..obs import events as obs_events
        obs_events.emit("spill", tier="host->disk", bytes=entry.nbytes,
                        priority=entry.priority)

    def _unspill_locked(self, entry: _Entry):
        from .budget import memory_budget
        import jax.numpy as jnp
        if entry.tier == StorageTier.DISK:
            with np.load(entry.disk_path) as z:
                entry.host_leaves = [z[str(i)] for i in range(len(z.files))]
            os.unlink(entry.disk_path)
            entry.disk_path = None
            entry.tier = StorageTier.HOST
        if entry.tier == StorageTier.HOST:
            memory_budget().reserve(entry.nbytes)
            leaves = [jnp.asarray(a) for a in entry.host_leaves]
            entry.device_tree = jax.tree_util.tree_unflatten(
                entry.treedef, leaves)
            entry.host_leaves = None
            entry.tier = StorageTier.DEVICE

    def _spill_dir_path(self) -> str:
        if self._spill_dir is None:
            conf_dir = active_conf().get(SPILL_DIR)
            self._spill_dir = conf_dir or tempfile.mkdtemp(prefix="srtpu-spill-")
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    # -- introspection (test surface) -------------------------------------
    def device_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if e.tier == StorageTier.DEVICE and not e.closed)

    def num_entries(self) -> int:
        with self._lock:
            return len(self._entries)


_catalog: Optional[BufferCatalog] = None
_catalog_lock = threading.Lock()


def buffer_catalog() -> BufferCatalog:
    global _catalog
    with _catalog_lock:
        if _catalog is None:
            _catalog = BufferCatalog()
        return _catalog


def reset_buffer_catalog() -> BufferCatalog:
    global _catalog
    with _catalog_lock:
        _catalog = BufferCatalog()
        return _catalog
