"""TpuSemaphore — device admission control (reference GpuSemaphore.scala:51).

TPU programs serialize per core, so this is an admission queue into the
per-chip executor: at most `spark.rapids.sql.concurrentGpuTasks` tasks may
hold the device; others block (and their operator state, held as
SpillableBatch, remains stealable). Wait time is tracked for task metrics
(reference GpuTaskMetrics semWaitTime)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..config import CONCURRENT_TPU_TASKS, active_conf


class TpuSemaphore:
    def __init__(self, permits: Optional[int] = None):
        self._permits = permits or active_conf().get(CONCURRENT_TPU_TASKS)
        self._sem = threading.Semaphore(self._permits)
        self._holders: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.total_wait_ns = 0

    def acquire_if_necessary(self, task_id: int):
        """Idempotent per task (reference acquireIfNecessary
        GpuSemaphore.scala:100): first call blocks for a permit, reentrant
        calls are free."""
        with self._lock:
            if self._holders.get(task_id, 0) > 0:
                self._holders[task_id] += 1
                return
        t0 = time.monotonic_ns()
        self._sem.acquire()
        waited = time.monotonic_ns() - t0
        self.total_wait_ns += waited
        with self._lock:
            self._holders[task_id] = self._holders.get(task_id, 0) + 1
        from ..obs import events as obs_events
        obs_events.emit("semaphore_acquire", task_id=task_id,
                        wait_ns=waited)

    def release_if_necessary(self, task_id: int):
        with self._lock:
            count = self._holders.pop(task_id, 0)
        if count > 0:
            self._sem.release()

    def held_by(self, task_id: int) -> bool:
        with self._lock:
            return self._holders.get(task_id, 0) > 0

    @property
    def available(self) -> int:
        # not exact under contention; test/debug surface only
        return self._sem._value  # noqa: SLF001


_semaphore: Optional[TpuSemaphore] = None
_sem_lock = threading.Lock()


def tpu_semaphore() -> TpuSemaphore:
    global _semaphore
    with _sem_lock:
        if _semaphore is None:
            _semaphore = TpuSemaphore()
        return _semaphore


def reset_tpu_semaphore(permits: Optional[int] = None) -> TpuSemaphore:
    global _semaphore
    with _sem_lock:
        _semaphore = TpuSemaphore(permits)
        return _semaphore
