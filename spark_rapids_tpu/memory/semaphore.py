"""TpuSemaphore — device admission control (reference GpuSemaphore.scala:51).

TPU programs serialize per core, so this is an admission queue into the
per-chip executor: at most `spark.rapids.sql.concurrentGpuTasks` tasks may
hold the device; others block (and their operator state, held as
SpillableBatch, remains stealable). Wait time is tracked for task metrics
(reference GpuTaskMetrics semWaitTime).

Re-entrant ACROSS THREADS per task (ISSUE 3): a pipeline producer thread
uploading batches for the same task as its consumer shares that task's
one permit — when two threads race the task's FIRST acquire, the loser
waits for the winner instead of taking a second permit (the reference
has the same property: one semaphore acquisition per Spark task however
many threads serve it). A producer blocked waiting for a permit polls an
optional `cancel` predicate so an abandoned pipelined query can always
tear down.

Fair wakeup (ISSUE 7): permit grants are priority-then-FIFO across
tasks of different queries — a waiter's priority class comes from its
query's workload ticket (exec/workload.py PRIORITIES; interactive when
ungoverned), ties break in registration order, and every
workload.AGING_EVERY-th grant goes to the OLDEST waiter regardless of
class, so a batch query can never starve behind a steady interactive
stream. Before this the permit pool was a bare threading.Semaphore:
grant order under contention was whatever the OS scheduler woke first.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, Optional

from ..config import CONCURRENT_TPU_TASKS, active_conf

_POLL_S = 0.05


class _Waiter:
    __slots__ = ("priority", "seq", "granted")

    def __init__(self, priority: int, seq: int):
        self.priority = priority
        self.seq = seq
        self.granted = False


class _FairPermits:
    """Permit pool with deterministic priority-then-FIFO-with-aging
    grant order. Waiters register once per blocked acquire (stable FIFO
    seq across poll timeouts) and poll `try_acquire`; a permit goes to
    the waiter `_next_waiter` picks, never to whoever the scheduler
    happens to wake."""

    def __init__(self, permits: int):
        self._cond = threading.Condition()
        self._avail = permits
        self._waiters: list = []
        self._seq = itertools.count(1)
        self._grants = 0

    def register(self, priority: int) -> _Waiter:
        with self._cond:
            w = _Waiter(priority, next(self._seq))
            self._waiters.append(w)
            return w

    def _next_waiter(self) -> Optional[_Waiter]:
        # the ONE fair-selection rule, shared with the admission queue
        from ..exec.workload import pick_fair
        return pick_fair(self._waiters, self._grants,
                         rank=lambda w: w.priority, seq=lambda w: w.seq)

    def try_acquire(self, w: _Waiter, timeout: float) -> bool:
        """True when `w` was granted a permit; False on timeout (the
        caller runs its cancellation checks and re-polls — `w` keeps
        its place in line)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if self._avail > 0 and self._next_waiter() is w:
                    self._avail -= 1
                    self._grants += 1
                    self._waiters.remove(w)
                    w.granted = True
                    # the chosen-next identity changed: other waiters
                    # must re-evaluate
                    self._cond.notify_all()
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def deregister(self, w: _Waiter) -> None:
        """A waiter that gives up (cancelled / abandoned task) leaves
        the line; whoever is next must re-evaluate."""
        with self._cond:
            if not w.granted and w in self._waiters:
                self._waiters.remove(w)
                self._cond.notify_all()

    def release(self) -> None:
        with self._cond:
            self._avail += 1
            self._cond.notify_all()

    @property
    def available(self) -> int:
        return self._avail


def _waiter_priority() -> int:
    from ..exec.workload import current_priority_rank
    return current_priority_rank()


class _TaskHold:
    __slots__ = ("count", "ready", "abandoned")

    def __init__(self):
        self.count = 0                  # re-entrant depth (one permit)
        self.ready = threading.Event()  # set once the permit is held
        self.abandoned = False          # task released mid-first-acquire


class TpuSemaphore:
    def __init__(self, permits: Optional[int] = None):
        self._permits_n = permits or active_conf().get(CONCURRENT_TPU_TASKS)
        self._pool = _FairPermits(self._permits_n)
        self._holders: Dict[int, _TaskHold] = {}
        self._lock = threading.Lock()
        self.total_wait_ns = 0

    def acquire_if_necessary(self, task_id: int,
                             cancel: Optional[Callable[[], bool]] = None
                             ) -> bool:
        """Idempotent per task (reference acquireIfNecessary
        GpuSemaphore.scala:100): the task's first call blocks for a
        permit, re-entrant calls — from ANY thread — are free. Returns
        False — with the permit NOT held — when `cancel()` went true
        while waiting, or when another thread released the task's hold
        (task end) while this first acquire was still blocked."""
        t0 = time.monotonic_ns()
        raced = False
        while True:
            with self._lock:
                hold = self._holders.get(task_id)
                if hold is not None and hold.count > 0:
                    hold.count += 1
                    if not raced:
                        return True
                    # this thread LOST the race for the task's first
                    # acquire and parked in the waiter loop below: its
                    # blocked time is real semaphore wait and must show
                    # up in semWaitTimeNs like the winner's does
                    waited = time.monotonic_ns() - t0
                    self.total_wait_ns += waited
                    break
                if hold is None:
                    hold = _TaskHold()
                    self._holders[task_id] = hold
                    raced = False
                    break  # this thread owns the first acquire
            # another thread is mid-first-acquire for this task: wait
            # for it (or for its cancellation) and re-check
            raced = True
            hold.ready.wait(_POLL_S)
            if cancel is not None and cancel():
                return False
            # lifecycle governor (ISSUE 6): a cancelled/expired query
            # must not keep parking here — nothing is registered for
            # this thread yet, so raising is clean
            from ..exec import lifecycle
            lifecycle.check_current("sem-wait")
            if hold.abandoned:
                # release_if_necessary (task end) ran while the first
                # acquire this thread was waiting on was still blocked:
                # re-racing a fresh acquire for the ended task would
                # take a permit nobody ever releases
                return False
        if raced:
            # re-entrant success after losing the first-acquire race:
            # the permit is the winner's, but the wait was this
            # thread's — attribute it
            from ..obs import events as obs_events
            from ..obs import phase as obs_phase
            obs_phase.add("semaphore-wait", waited)
            obs_events.emit("semaphore_acquire", task_id=task_id,
                            wait_ns=waited)
            return True
        w = self._pool.register(_waiter_priority())
        try:
            while not self._pool.try_acquire(w, timeout=_POLL_S):
                if hold.abandoned:
                    # release_if_necessary (task end) ran while this
                    # first acquire was still blocked: the outcome is
                    # already False — stop competing for a permit that
                    # would only be handed straight back (the holder
                    # entry is gone)
                    hold.ready.set()
                    return False
                if cancel is not None and cancel():
                    with self._lock:
                        if self._holders.get(task_id) is hold:
                            del self._holders[task_id]
                    hold.ready.set()  # waiters re-race a fresh acquire
                    return False
                from ..exec import lifecycle
                if lifecycle.current_cancelled():
                    # governed-query cancellation while blocked for a
                    # permit: same cleanup as the cancel predicate (this
                    # thread owns the pending hold entry but no permit),
                    # then raise with sem-wait phase attribution
                    with self._lock:
                        if self._holders.get(task_id) is hold:
                            del self._holders[task_id]
                    hold.ready.set()
                    lifecycle.check_current("sem-wait")
        finally:
            if not w.granted:
                self._pool.deregister(w)
        waited = time.monotonic_ns() - t0
        with self._lock:
            abandoned = hold.abandoned
            if abandoned:
                if self._holders.get(task_id) is hold:
                    del self._holders[task_id]
            else:
                # under the lock: concurrent producer threads' first
                # acquires would otherwise lose updates to this counter
                self.total_wait_ns += waited
                hold.count = 1
        if abandoned:
            # release_if_necessary ran while we were blocked: keeping
            # this permit would leak it forever (the task never
            # releases again), so hand it straight back
            self._pool.release()
            hold.ready.set()
            return False
        hold.ready.set()
        from ..obs import events as obs_events
        from ..obs import phase as obs_phase
        obs_phase.add("semaphore-wait", waited)
        obs_events.emit("semaphore_acquire", task_id=task_id,
                        wait_ns=waited)
        return True

    def release_if_necessary(self, task_id: int):
        """Release the task's permit entirely (task end — the reference
        releases the whole task's hold, not one nesting level)."""
        with self._lock:
            hold = self._holders.get(task_id)
            if hold is not None:
                del self._holders[task_id]
                # any thread still parked in the waiter loop holds a
                # stale reference to this hold: abandoned stops a late
                # wake-up from re-racing a fresh acquire for the ended
                # task (which would take a permit nobody ever releases)
                hold.abandoned = True
                if hold.count == 0:
                    # a first acquire for this task is still blocked on
                    # another thread: it must hand its permit straight
                    # back when it lands (no permit is held right now)
                    hold = None
        if hold is not None:
            hold.ready.set()
            self._pool.release()

    def held_by(self, task_id: int) -> bool:
        with self._lock:
            hold = self._holders.get(task_id)
            return hold is not None and hold.count > 0

    @property
    def available(self) -> int:
        # not exact under contention; test/debug surface only
        return self._pool.available


_semaphore: Optional[TpuSemaphore] = None
_sem_lock = threading.Lock()


def tpu_semaphore() -> TpuSemaphore:
    global _semaphore
    with _sem_lock:
        if _semaphore is None:
            _semaphore = TpuSemaphore()
        return _semaphore


def reset_tpu_semaphore(permits: Optional[int] = None) -> TpuSemaphore:
    global _semaphore
    with _sem_lock:
        _semaphore = TpuSemaphore(permits)
        return _semaphore
