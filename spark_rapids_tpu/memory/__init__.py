"""Memory & OOM-retry runtime (reference SURVEY §2.4 — the heart of
robustness): HBM budget, 3-tier spill catalog, spillable handles,
retry/split-retry discipline with fault injection, admission semaphore."""

from .budget import MemoryBudget, memory_budget, reset_memory_budget
from .catalog import (
    ACTIVE_BATCHING_PRIORITY, ACTIVE_ON_DECK_PRIORITY, BufferCatalog,
    StorageTier, buffer_catalog, reset_buffer_catalog,
)
from .retry import (
    CpuRetryOOM, TpuOOMError, TpuRetryOOM, TpuSplitAndRetryOOM,
    current_task_id, force_retry_oom, force_split_and_retry_oom, oom_guard,
    register_task, split_in_half_by_rows, task_retry_counts,
    unregister_task, with_retry, with_retry_no_split,
)
from .semaphore import TpuSemaphore, reset_tpu_semaphore, tpu_semaphore
from .spillable import SpillableBatch
from .device_manager import DeviceManager, device_manager
