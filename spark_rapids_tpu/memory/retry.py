"""OOM-retry framework — the contract of the reference's
RmmRapidsRetryIterator.scala:33,62-100 + JNI RmmSpark per-thread OOM state
machine, rebuilt for TPU.

On GPUs the reference gets an async callback from RMM when an allocation
fails, spills synchronously, and retries the kernel. XLA on TPU gives no
such callback mid-program, so the discipline is *proactive budgeting*: every
operator reserves its worst-case padded footprint against an accounted HBM
budget BEFORE launching device work. Reservation failure raises TpuRetryOOM
(spill then retry) or, if the batch is the problem, the retry loop escalates
to TpuSplitAndRetryOOM semantics by splitting the input and re-running —
identical control flow to the reference, different trigger.

Fault injection (`spark.rapids.sql.test.injectRetryOOM` = 'retry:N' or
'split:N') throws on the Nth guarded section of a task — the reference's
RmmSpark.forceRetryOOM test pattern (RmmSparkRetrySuiteBase.scala:35-80),
and the backbone of the chaos-test suites in tests/test_retry.py.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence, TypeVar

from ..config import (OOM_RETRY_BACKOFF_MS, RETRY_MAX_ATTEMPTS,
                      TEST_RETRY_OOM_INJECTION_MODE, active_conf)
from ..faults import check as _fault_check
from ..faults import is_oom_error


class TpuOOMError(MemoryError):
    pass


class TpuRetryOOM(TpuOOMError):
    """Transient: spill/wait should free memory; re-run the SAME input."""


class TpuSplitAndRetryOOM(TpuOOMError):
    """The input itself is too big: split it and run the halves."""


class CpuRetryOOM(TpuOOMError):
    """Host-memory pressure analog (reference CpuRetryOOM)."""


class _TaskState(threading.local):
    def __init__(self):
        self.task_id: Optional[int] = None
        self.guarded_calls = 0
        self.inject_mode: Optional[str] = None
        self.inject_at = 0
        self.inject_remaining = 0
        self.injected = False
        self.retry_count = 0
        self.split_retry_count = 0


_state = _TaskState()


def register_task(task_id: int):
    """Associate this thread with a task (reference RmmSpark task/thread
    registration). Resets injection + metrics counters."""
    _state.task_id = task_id
    _state.guarded_calls = 0
    _state.injected = False
    _state.retry_count = 0
    _state.split_retry_count = 0
    inj = active_conf().get(TEST_RETRY_OOM_INJECTION_MODE)
    if inj:
        mode, _, n = inj.partition(":")
        _state.inject_mode = mode
        _state.inject_at = int(n or 1)
        _state.inject_remaining = 1
    else:
        _state.inject_mode = None
        _state.inject_remaining = 0


def unregister_task():
    _state.task_id = None
    _state.inject_mode = None


def current_task_id() -> Optional[int]:
    """This thread's registered task id (None outside a task) — the key
    the fault-injection plan (faults.py) uses for deterministic replay."""
    return _state.task_id


def force_retry_oom(num_ooms: int = 1):
    """Arm injection on this thread for the next `num_ooms` guarded
    sections (test API, reference RmmSpark.forceRetryOOM)."""
    _state.inject_mode = "retry"
    _state.inject_at = _state.guarded_calls + 1
    _state.inject_remaining = num_ooms
    _state.injected = False


def force_split_and_retry_oom(num_ooms: int = 1):
    _state.inject_mode = "split"
    _state.inject_at = _state.guarded_calls + 1
    _state.inject_remaining = num_ooms
    _state.injected = False


def oom_guard():
    """Called at the top of every guarded device section; applies OOM
    injection (the legacy injectRetryOOM path) and the registered
    `device.dispatch` chaos fault point (faults.py)."""
    _state.guarded_calls += 1
    if (_state.inject_mode and _state.inject_remaining > 0
            and _state.guarded_calls >= _state.inject_at):
        _state.inject_remaining -= 1
        _state.injected = _state.inject_remaining <= 0
        if _state.inject_mode == "retry":
            raise TpuRetryOOM("injected retry OOM")
        if _state.inject_mode == "split":
            raise TpuSplitAndRetryOOM("injected split-and-retry OOM")
    _fault_check("device.dispatch")


def task_retry_counts():
    return _state.retry_count, _state.split_retry_count


T = TypeVar("T")
R = TypeVar("R")

#: OOM backoff cap: the point of the sleep is to let in-flight frees
#: land, not to stall a query for seconds
_OOM_BACKOFF_CAP_MS = 200


def _oom_backoff_ns(attempt: int) -> int:
    """Capped exponential backoff with deterministic jitter for OOM
    retry attempt N (1-based). Jitter is a pure hash of (task, attempt)
    so chaos runs replay exactly."""
    from ..faults import backoff_s
    base_ms = active_conf().get(OOM_RETRY_BACKOFF_MS)
    if base_ms <= 0:
        return 0
    return int(backoff_s(attempt, base_ms, _OOM_BACKOFF_CAP_MS,
                         f"oom:{_state.task_id}:{attempt}") * 1e9)


def split_in_half_by_rows(item):
    """Default split policy: halve a (Spillable)ColumnarBatch by rows
    (reference splitSpillableInHalfByRows). The halves are registered
    BEFORE the source's budget is released so the accounting never
    undercounts live device memory mid-split; with_retry owns (and closes)
    the returned halves."""
    from .spillable import SpillableBatch
    if isinstance(item, SpillableBatch):
        batch = item.get_batch()
        try:
            a, b = _split_batch(batch)
            halves = [SpillableBatch.from_batch(a),
                      SpillableBatch.from_batch(b)]
        finally:
            item.release()
        item.close()
        return halves
    return list(_split_batch(item))


def _split_batch(batch):
    from ..columnar.batch import ColumnarBatch
    from ..ops.basic import slice_rows
    n = batch.num_rows_host
    if n < 2:
        raise TpuSplitAndRetryOOM("cannot split a batch with < 2 rows")
    half = n // 2
    cap = batch.capacity
    import jax.numpy as jnp
    left = ColumnarBatch(
        [slice_rows(c, jnp.int32(0), jnp.int32(half), cap)
         for c in batch.columns], half, batch.schema)
    right = ColumnarBatch(
        [slice_rows(c, jnp.int32(half), jnp.int32(n - half), cap)
         for c in batch.columns], n - half, batch.schema)
    return left, right


def with_retry(input_item: T, fn: Callable[[T], R],
               split_policy: Optional[Callable[[T], List[T]]] = None,
               ) -> Iterator[R]:
    """Run fn over input_item with OOM retry/split-retry semantics
    (reference withRetry). Yields one result per (sub-)input. fn MUST be
    idempotent; inputs should be spillable while waiting.
    """
    from .budget import spill_for_retry
    from .spillable import SpillableBatch
    max_attempts = active_conf().retry_max_attempts
    queue: List[T] = [input_item]
    owned: set = set()  # split products with_retry must close itself

    def _close_owned(item):
        if id(item) in owned and isinstance(item, SpillableBatch):
            owned.discard(id(item))
            item.close()

    def handle_retry_oom(attempts: int):
        """Shared TpuRetryOOM bookkeeping: count, emit (with the
        attempt/backoff surface ISSUE 4 added), spill, then sleep a
        capped exponential backoff — CHANGES PR 3 round-5 observed the
        loop spinning through all 10 attempts in microseconds while the
        bytes it needed were still in flight."""
        _state.retry_count += 1
        backoff_ns = _oom_backoff_ns(attempts)
        from ..obs import events as obs_events
        obs_events.emit("oom_retry", oom="retry", attempt=attempts,
                        max_attempts=max_attempts, backoff_ns=backoff_ns,
                        task_id=_state.task_id)
        if attempts >= max_attempts:
            return False
        spill_for_retry()
        if backoff_ns:
            # phase attribution (ISSUE 17): the deliberate let-frees-
            # land sleep is retry-backoff; the spill pass above accrues
            # its own wall as spill-wait inside synchronous_spill
            from ..obs import phase as obs_phase
            t0b = time.perf_counter_ns()
            time.sleep(backoff_ns / 1e9)
            obs_phase.add("retry-backoff",
                          time.perf_counter_ns() - t0b)
        return True

    try:
        while queue:
            item = queue.pop(0)
            attempts = 0
            try:
                while True:
                    attempts += 1
                    try:
                        oom_guard()
                        result = fn(item)
                        _close_owned(item)
                        yield result
                        break
                    except TpuRetryOOM:
                        if not handle_retry_oom(attempts):
                            raise
                    except TpuSplitAndRetryOOM:
                        _state.split_retry_count += 1
                        from ..obs import events as obs_events
                        obs_events.emit("oom_retry", oom="split",
                                        attempt=attempts,
                                        max_attempts=max_attempts,
                                        backoff_ns=0,
                                        task_id=_state.task_id)
                        if split_policy is None:
                            raise
                        # OOM-feedback batch right-sizing (ISSUE 19):
                        # the device just proved this batch size wrong —
                        # shrink the governed query's batch target so
                        # CoalesceBatchesExec stops rebuilding batches
                        # that re-trigger this lane
                        from ..exec import adaptive
                        adaptive.note_oom_split()
                        halves = split_policy(item)
                        owned.discard(id(item))
                        owned.update(id(h) for h in halves)
                        queue = halves + queue
                        break
                    except Exception as e:
                        # taxonomy (faults.py): XLA RESOURCE_EXHAUSTED is
                        # an OOM in runtime-error clothing — recover it on
                        # the spill-and-retry lane here, at the guarded
                        # section, instead of failing the whole task
                        if not is_oom_error(e):
                            raise
                        if not handle_retry_oom(attempts):
                            raise TpuRetryOOM(str(e)) from e
            except BaseException:
                _close_owned(item)  # the in-flight item, if owned
                raise
    except BaseException:
        for item in queue:
            _close_owned(item)
        raise


def with_retry_no_split(input_item: T, fn: Callable[[T], R]) -> R:
    """withRetryNoSplit: retry on TpuRetryOOM only; split escalates."""
    for result in with_retry(input_item, fn, split_policy=None):
        return result
    raise RuntimeError("with_retry produced no result")
