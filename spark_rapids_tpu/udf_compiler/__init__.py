"""UDF compiler — the TPU build's analog of the reference's udf-compiler
module (LambdaReflection.scala:34 / CFG.scala:131 / Instruction.scala /
CatalystExpressionBuilder.scala:45): instead of decompiling JVM lambda
bytecode into Catalyst expressions, this decompiles *CPython* bytecode
(`dis`) into the engine's expression tree, so a `udf(lambda x: ...)`
becomes a fused device expression — no host callback round trip at all.

Technique mirrors the reference: symbolic execution of the bytecode over
an operand stack of Expression nodes; conditional jumps fork both paths
and reconverge as `If(cond, then, else)` (the reference's CFG + expression
builder). Loops, exceptions, and unknown calls raise UdfCompileError —
the caller keeps the host-callback PythonUDF for those, exactly like the
reference falling back to the JVM UDF when compilation fails.

Semantics note (same caveat the reference documents): a compiled UDF uses
Spark SQL null semantics (NULL propagates through operators), while the
interpreted Python function would raise on None. Only compile functions
whose authors expect SQL semantics — which is why the rewrite is gated by
spark.rapids.sql.udfCompiler.enabled.
"""

from __future__ import annotations

import dis
import types
from typing import Dict, List, Optional, Sequence

from ..expr import arithmetic as A
from ..expr import conditional as C
from ..expr import predicates as P
from ..expr import stringexprs as S
from ..expr.core import Expression, Literal, lit
from ..types import BooleanType, StringType

MAX_FORKS = 200


class UdfCompileError(Exception):
    pass


def _is_stringy(e: Expression) -> bool:
    try:
        return isinstance(e.data_type, StringType)
    except (TypeError, NotImplementedError):
        return False


class _Callable:
    """Marker for a resolved callable sitting on the symbolic stack."""

    def __init__(self, kind: str, target=None):
        self.kind = kind      # builtin name or "method"
        self.target = target  # method receiver Expression


_BIN_OPS = {
    "+": lambda a, b: S.Concat(a, b) if _is_stringy(a) or _is_stringy(b)
    else A.Add(a, b),
    "-": A.Subtract,
    "*": A.Multiply,
    "/": A.Divide,
    "//": A.IntegralDivide,
    "%": A.Remainder,
    "**": lambda a, b: _pow(a, b),
}

_CMP_OPS = {
    "<": P.LessThan, "<=": P.LessThanOrEqual, ">": P.GreaterThan,
    ">=": P.GreaterThanOrEqual, "==": P.EqualTo,
    "!=": lambda a, b: P.Not(P.EqualTo(a, b)),
}


def _pow(a, b):
    from ..expr.math import Pow
    return Pow(a, b)


def _call_builtin(name: str, args: List[Expression]) -> Expression:
    if name == "abs" and len(args) == 1:
        return A.Abs(args[0])
    if name == "len" and len(args) == 1:
        return S.Length(args[0])
    if name == "min" and len(args) >= 2:
        return A.Least(*args)
    if name == "max" and len(args) >= 2:
        return A.Greatest(*args)
    if name in ("float", "int", "str", "bool") and len(args) == 1:
        from ..types import BOOLEAN, DOUBLE, LONG, STRING
        to = {"float": DOUBLE, "int": LONG, "str": STRING,
              "bool": BOOLEAN}[name]
        return args[0].cast(to)
    if name in ("sqrt", "exp", "log", "sin", "cos", "tan", "floor",
                "ceil") and len(args) == 1:
        from ..expr import math as M
        cls = {"sqrt": M.Sqrt, "exp": M.Exp, "log": M.Log, "sin": M.Sin,
               "cos": M.Cos, "tan": M.Tan, "floor": M.Floor,
               "ceil": M.Ceil}[name]
        return cls(args[0])
    # NOTE: Python round() is banker's rounding; the engine's Round is
    # Spark HALF_UP — compiling it would silently change results, so it
    # stays a host callback.
    raise UdfCompileError(f"cannot compile call to {name!r}")


def _call_method(recv: Expression, name: str, args: List[Expression]
                 ) -> Expression:
    def _litval(e):
        if not isinstance(e, Literal):
            raise UdfCompileError(
                f"str.{name} argument must be a constant")
        return e.value

    if name == "upper" and not args:
        return S.Upper(recv)
    if name == "lower" and not args:
        return S.Lower(recv)
    if name == "strip" and not args:
        return S.StringTrim(recv)
    if name == "lstrip" and not args:
        return S.StringTrimLeft(recv)
    if name == "rstrip" and not args:
        return S.StringTrimRight(recv)
    if name == "startswith" and len(args) == 1:
        return S.StartsWith(recv, _litval(args[0]))
    if name == "endswith" and len(args) == 1:
        return S.EndsWith(recv, _litval(args[0]))
    if name == "replace" and len(args) == 2:
        return S.StringReplace(recv, _litval(args[0]), _litval(args[1]))
    raise UdfCompileError(f"cannot compile method .{name}()")


class _Compiler:
    def __init__(self, fn, arg_exprs: Sequence[Expression]):
        self.fn = fn
        code = fn.__code__
        if code.co_argcount != len(arg_exprs):
            raise UdfCompileError(
                f"udf takes {code.co_argcount} args, given {len(arg_exprs)}")
        self.instrs = list(dis.get_instructions(fn))
        self.by_offset = {i.offset: idx for idx, i in enumerate(self.instrs)}
        self.locals: Dict[str, Expression] = {
            code.co_varnames[i]: e for i, e in enumerate(arg_exprs)}
        self.forks = 0

    def _global(self, name: str):
        if name in self.fn.__globals__:
            return self.fn.__globals__[name]
        import builtins
        if hasattr(builtins, name):
            return getattr(builtins, name)
        raise UdfCompileError(f"unresolved global {name!r}")

    def run(self) -> Expression:
        return self._run(0, [])

    def _run(self, idx: int, stack: List) -> Expression:
        """Symbolically execute from instruction `idx` until RETURN."""
        self.forks += 1
        if self.forks > MAX_FORKS:
            raise UdfCompileError("control flow too complex")
        instrs = self.instrs
        while idx < len(instrs):
            ins = instrs[idx]
            op = ins.opname
            if op in ("RESUME", "NOP", "PRECALL", "CACHE", "PUSH_NULL",
                      "MAKE_CELL", "COPY_FREE_VARS"):
                if op == "PUSH_NULL":
                    stack.append(None)
                idx += 1
                continue
            if op == "LOAD_CONST":
                try:
                    stack.append(lit(ins.argval))
                except TypeError:
                    stack.append(_Const(ins.argval))  # e.g. tuple for `in`
                idx += 1
                continue
            if op in ("LOAD_FAST", "LOAD_FAST_CHECK"):
                if ins.argval not in self.locals:
                    raise UdfCompileError(
                        f"local {ins.argval!r} read before assignment")
                stack.append(self.locals[ins.argval])
                idx += 1
                continue
            if op == "STORE_FAST":
                self.locals[ins.argval] = stack.pop()
                idx += 1
                continue
            if op == "LOAD_GLOBAL":
                # 3.11+: low bit of arg pushes NULL before the global
                if ins.arg & 1:
                    stack.append(None)
                obj = self._global(ins.argval)
                name = getattr(obj, "__name__", ins.argval)
                stack.append(_Callable(name))
                idx += 1
                continue
            if op == "LOAD_DEREF":
                # closure constant (captured value)
                for cname, cell in zip(
                        self.fn.__code__.co_freevars,
                        self.fn.__closure__ or ()):
                    if cname == ins.argval:
                        v = cell.cell_contents
                        if callable(v) or isinstance(v, types.ModuleType):
                            stack.append(_Callable(
                                getattr(v, "__name__", ins.argval)))
                        else:
                            try:
                                stack.append(lit(v))
                            except TypeError as e:
                                raise UdfCompileError(str(e))
                        break
                else:
                    raise UdfCompileError(
                        f"unresolved closure var {ins.argval!r}")
                idx += 1
                continue
            if op in ("LOAD_ATTR", "LOAD_METHOD"):
                recv = stack.pop()
                if not isinstance(recv, Expression):
                    raise UdfCompileError(
                        f"attribute on non-expression: {ins.argval}")
                stack.append(_Callable("method", recv))
                stack.append(_MethodName(ins.argval))
                idx += 1
                continue
            if op == "CALL":
                n = ins.arg
                args = [stack.pop() for _ in range(n)][::-1]
                tos = stack.pop()
                callee = None
                if isinstance(tos, _MethodName):
                    callee = stack.pop()  # the _Callable("method")
                    result = _call_method(callee.target, tos.name, args)
                elif isinstance(tos, _Callable):
                    if stack and stack[-1] is None:
                        stack.pop()  # the PUSH_NULL slot
                    result = _call_builtin(tos.kind, args)
                else:
                    raise UdfCompileError("cannot compile dynamic call")
                stack.append(result)
                idx += 1
                continue
            if op == "BINARY_OP":
                b = stack.pop()
                a = stack.pop()
                fn = _BIN_OPS.get(ins.argrepr.rstrip("="))
                if fn is None:
                    raise UdfCompileError(
                        f"unsupported operator {ins.argrepr!r}")
                stack.append(fn(_as_expr(a), _as_expr(b)))
                idx += 1
                continue
            if op == "COMPARE_OP":
                b = stack.pop()
                a = stack.pop()
                fn = _CMP_OPS.get(ins.argval)
                if fn is None:
                    raise UdfCompileError(
                        f"unsupported comparison {ins.argval!r}")
                stack.append(fn(_as_expr(a), _as_expr(b)))
                idx += 1
                continue
            if op == "IS_OP":
                b = stack.pop()
                a = stack.pop()
                if isinstance(b, Literal) and b.value is None:
                    e = P.IsNotNull(a) if ins.arg else P.IsNull(a)
                    stack.append(e)
                    idx += 1
                    continue
                raise UdfCompileError("`is` supported only against None")
            if op == "CONTAINS_OP":
                b = stack.pop()
                a = stack.pop()
                if isinstance(b, _Const) \
                        and isinstance(b.value, (tuple, list, frozenset,
                                                 set)):
                    e = P.In(_as_expr(a), list(b.value))
                elif isinstance(b, Expression) and _is_stringy(b) \
                        and isinstance(a, Literal):
                    e = S.Contains(b, a.value)
                else:
                    raise UdfCompileError("unsupported `in` operands")
                if ins.arg:
                    e = P.Not(e)
                stack.append(e)
                idx += 1
                continue
            if op == "UNARY_NEGATIVE":
                stack.append(A.UnaryMinus(_as_expr(stack.pop())))
                idx += 1
                continue
            if op == "UNARY_NOT":
                stack.append(P.Not(_as_expr(stack.pop())))
                idx += 1
                continue
            if op in ("COPY",):
                stack.append(stack[-ins.arg])
                idx += 1
                continue
            if op in ("SWAP",):
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                idx += 1
                continue
            if op == "POP_TOP":
                stack.pop()
                idx += 1
                continue
            if op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                      "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                cond = _as_expr(stack.pop())
                target = self.by_offset[ins.argval]
                if op == "POP_JUMP_IF_NONE":
                    cond = P.IsNotNull(cond)      # fallthrough if not None
                elif op == "POP_JUMP_IF_NOT_NONE":
                    cond = P.IsNull(cond)
                elif op == "POP_JUMP_IF_TRUE":
                    cond = P.Not(_boolify(cond))
                else:
                    cond = _boolify(cond)
                # Fork locals per path like the operand stack: STORE_FAST in
                # the then-branch must not leak into the else-branch.
                saved_locals = dict(self.locals)
                then_r = self._run(idx + 1, list(stack))
                self.locals = dict(saved_locals)
                else_r = self._run(target, list(stack))
                self.locals = saved_locals
                return C.If(cond, then_r, else_r)
            if op in ("JUMP_FORWARD",):
                idx = self.by_offset[ins.argval]
                continue
            if op in ("JUMP_BACKWARD", "JUMP_BACKWARD_NO_INTERRUPT",
                      "FOR_ITER"):
                raise UdfCompileError("loops cannot be compiled")
            if op == "RETURN_VALUE":
                return _as_expr(stack.pop())
            if op == "RETURN_CONST":
                return lit(ins.argval)
            raise UdfCompileError(f"unsupported opcode {op}")
        raise UdfCompileError("fell off end of bytecode")


class _MethodName:
    def __init__(self, name: str):
        self.name = name


class _Const:
    """Non-expressible constant (tuple/set for `in`, etc.)."""

    def __init__(self, value):
        self.value = value


def _as_expr(v) -> Expression:
    if isinstance(v, Expression):
        return v
    raise UdfCompileError(f"non-expression on stack: {v!r}")


def _boolify(e: Expression) -> Expression:
    """Python truthiness → SQL boolean where safe (booleans pass through;
    anything else must already be a predicate)."""
    try:
        if isinstance(e.data_type, BooleanType):
            return e
    except (TypeError, NotImplementedError):
        return e  # unresolved reference: assume caller passed a predicate
    raise UdfCompileError(
        "non-boolean condition (Python truthiness on "
        f"{e.data_type.simple_name()} is not SQL semantics)")


def compile_udf(fn, arg_exprs: Sequence[Expression]) -> Expression:
    """Python function + argument expressions → engine expression tree.
    Raises UdfCompileError when any construct has no SQL equivalent."""
    if not isinstance(fn, types.FunctionType):
        raise UdfCompileError("only plain Python functions compile")
    if fn.__code__.co_flags & 0x20:  # generator
        raise UdfCompileError("generators cannot be compiled")
    return _Compiler(fn, arg_exprs).run()


def maybe_compile_plan_udfs(plan, conf):
    """Logical-plan rewrite (reference LogicalPlanRules.scala:29): replace
    host-callback PythonUDF expressions with compiled device expressions
    wherever compilation succeeds. Project/Filter nodes only — the UDF
    call sites Spark's rule covers too."""
    from ..config import UDF_COMPILER_ENABLED
    from ..expr.udf import PythonUDF
    from ..plan import logical as L
    if not conf.get(UDF_COMPILER_ENABLED):
        return plan

    def rewrite_expr(e: Expression) -> Expression:
        def fn(node):
            if isinstance(node, PythonUDF):
                try:
                    compiled = compile_udf(node.fn, list(node.children))
                    return compiled.cast(node.return_type)
                except UdfCompileError:
                    return node
            return node
        return e.transform_up(fn)

    def walk(p):
        kids = [walk(c) for c in p.children]
        if isinstance(p, L.LogicalProject):
            return L.LogicalProject([rewrite_expr(e) for e in p.exprs],
                                    kids[0])
        if isinstance(p, L.LogicalFilter):
            return L.LogicalFilter(rewrite_expr(p.condition), kids[0])
        if kids != list(p.children):
            import copy
            q = copy.copy(p)
            q.children = kids
            return q
        return p

    return walk(plan)
