"""Spark-semantics data types for the TPU-native columnar engine.

Mirrors the type universe the reference plugin supports (see reference
sql-plugin TypeChecks.scala:168 TypeSig enum: BOOLEAN..DAYTIME, nested
ARRAY/MAP/STRUCT), re-expressed for a JAX/XLA backend where every column is
one or more dense device arrays.

Physical encodings on TPU:
  - fixed-width types -> a single device array of the listed jnp dtype
  - BOOLEAN           -> bool_ array (validity is carried separately)
  - STRING / BINARY   -> twin arrays: uint8 byte buffer + int32 offsets
                         (Arrow-style; XLA has no ragged support so the byte
                         buffer is padded to a byte-capacity bucket)
  - DECIMAL(p<=18)    -> int64 unscaled values + (precision, scale) metadata
  - DECIMAL(p>18)     -> two int64 limbs (hi, lo) -- decimal128
  - DATE              -> int32 days since epoch  (Spark CatalystType DateType)
  - TIMESTAMP         -> int64 microseconds since epoch UTC
  - NULL              -> all-invalid validity, no data array
  - ARRAY             -> child column + int32 offsets
  - STRUCT            -> child columns side by side
  - MAP               -> ARRAY<STRUCT<key,value>> encoding (like Arrow/cuDF)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class DataType:
    """Base of the engine's logical type lattice."""

    #: logical default; overridden per type
    nullable_physical = True

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, NumericType)

    @property
    def is_fixed_width(self) -> bool:
        return self.jnp_dtype is not None and not isinstance(self, (StringType, BinaryType))

    # jnp dtype of the primary data buffer; None for nested/varlen
    jnp_dtype: Optional[np.dtype] = None

    def simple_name(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.simple_name()

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and dataclasses.asdict(self) == dataclasses.asdict(other) \
            if dataclasses.is_dataclass(self) else type(self) is type(other)

    def __hash__(self) -> int:
        return hash(repr(self))


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    jnp_dtype = np.dtype(np.bool_)


class ByteType(IntegralType):
    jnp_dtype = np.dtype(np.int8)
    byte_width = 1


class ShortType(IntegralType):
    jnp_dtype = np.dtype(np.int16)
    byte_width = 2


class IntegerType(IntegralType):
    jnp_dtype = np.dtype(np.int32)
    byte_width = 4

    def simple_name(self) -> str:
        return "int"


class LongType(IntegralType):
    jnp_dtype = np.dtype(np.int64)
    byte_width = 8

    def simple_name(self) -> str:
        return "bigint"


class FloatType(FractionalType):
    jnp_dtype = np.dtype(np.float32)
    byte_width = 4


class DoubleType(FractionalType):
    jnp_dtype = np.dtype(np.float64)
    byte_width = 8


class DateType(DataType):
    """Days since unix epoch, proleptic Gregorian (int32)."""
    jnp_dtype = np.dtype(np.int32)
    byte_width = 4


class TimestampType(DataType):
    """Microseconds since unix epoch UTC (int64)."""
    jnp_dtype = np.dtype(np.int64)
    byte_width = 8


class TimestampNTZType(DataType):
    """Timestamp without timezone; micros since epoch in local wall clock."""
    jnp_dtype = np.dtype(np.int64)
    byte_width = 8


class StringType(DataType):
    """UTF-8 bytes + int32 offsets (Arrow layout, padded byte buffer)."""
    jnp_dtype = None


class BinaryType(DataType):
    jnp_dtype = None


class NullType(DataType):
    jnp_dtype = None


@dataclasses.dataclass(frozen=True, eq=True)
class DecimalType(FractionalType):
    """Fixed-point decimal. p<=18 packs in one int64 of unscaled value
    (Spark's Decimal64 fast path); p<=38 in two int64 limbs (decimal128)."""
    precision: int = 10
    scale: int = 0

    MAX_INT_DIGITS = 9
    MAX_LONG_DIGITS = 18
    MAX_PRECISION = 38

    def __post_init__(self):
        assert 1 <= self.precision <= self.MAX_PRECISION, self.precision
        assert 0 <= self.scale <= self.precision, (self.precision, self.scale)

    @property
    def jnp_dtype(self):  # type: ignore[override]
        return np.dtype(np.int64)

    @property
    def is_decimal128(self) -> bool:
        return self.precision > self.MAX_LONG_DIGITS

    def simple_name(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __hash__(self) -> int:
        return hash(("decimal", self.precision, self.scale))


@dataclasses.dataclass(frozen=True, eq=True)
class ArrayType(DataType):
    element_type: DataType = dataclasses.field(default_factory=IntegerType)
    contains_null: bool = True
    jnp_dtype = None

    def simple_name(self) -> str:
        return f"array<{self.element_type.simple_name()}>"

    def __hash__(self) -> int:
        return hash(("array", self.element_type))


@dataclasses.dataclass(frozen=True, eq=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True

    def __hash__(self) -> int:
        return hash((self.name, self.data_type, self.nullable))


@dataclasses.dataclass(frozen=True, eq=True)
class StructType(DataType):
    fields: Tuple[StructField, ...] = ()
    jnp_dtype = None

    def simple_name(self) -> str:
        inner = ",".join(f"{f.name}:{f.data_type.simple_name()}" for f in self.fields)
        return f"struct<{inner}>"

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __hash__(self) -> int:
        return hash(("struct", self.fields))


@dataclasses.dataclass(frozen=True, eq=True)
class MapType(DataType):
    key_type: DataType = dataclasses.field(default_factory=StringType)
    value_type: DataType = dataclasses.field(default_factory=StringType)
    value_contains_null: bool = True
    jnp_dtype = None

    def simple_name(self) -> str:
        return f"map<{self.key_type.simple_name()},{self.value_type.simple_name()}>"

    def __hash__(self) -> int:
        return hash(("map", self.key_type, self.value_type))


# Canonical singletons (Spark-style)
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
TIMESTAMP_NTZ = TimestampNTZType()
NULL = NullType()

_NUMERIC_ORDER = [ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType]


def is_orderable(dt: DataType) -> bool:
    return not isinstance(dt, (MapType, NullType))


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Spark's binary-arithmetic common type for non-decimal numerics."""
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        raise TypeError("decimal promotion handled by DecimalPrecision rules")
    ia = _NUMERIC_ORDER.index(type(a))
    ib = _NUMERIC_ORDER.index(type(b))
    return (a, b)[ia < ib]


def from_arrow(at) -> DataType:
    """Map a pyarrow DataType to the engine type."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BOOLEAN
    if pa.types.is_int8(at):
        return BYTE
    if pa.types.is_int16(at):
        return SHORT
    if pa.types.is_int32(at):
        return INT
    if pa.types.is_int64(at):
        return LONG
    if pa.types.is_float32(at):
        return FLOAT
    if pa.types.is_float64(at):
        return DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return BINARY
    if pa.types.is_date32(at):
        return DATE
    if pa.types.is_timestamp(at):
        return TIMESTAMP if at.tz is not None else TIMESTAMP_NTZ
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_struct(at):
        return StructType(tuple(StructField(f.name, from_arrow(f.type)) for f in at))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    if pa.types.is_null(at):
        return NULL
    if pa.types.is_dictionary(at):
        # dictionary encoding is a physical layout, not a logical type:
        # the engine schema carries the VALUE type; the encoded lane
        # (columnar/encoded.py) keeps the layout at the column level
        return from_arrow(at.value_type)
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow(dt: DataType):
    import pyarrow as pa
    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, StringType):
        return pa.string()
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, TimestampNTZType):
        return pa.timestamp("us")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element_type))
    if isinstance(dt, StructType):
        return pa.struct([pa.field(f.name, to_arrow(f.data_type), f.nullable) for f in dt.fields])
    if isinstance(dt, MapType):
        return pa.map_(to_arrow(dt.key_type), to_arrow(dt.value_type))
    if isinstance(dt, NullType):
        return pa.null()
    raise TypeError(f"unsupported type {dt}")


def jnp_zero(dt: DataType):
    """Neutral fill value used in padded (invalid) slots."""
    if dt.jnp_dtype is None:
        raise TypeError(f"{dt} has no single-buffer physical encoding")
    return jnp.zeros((), dtype=dt.jnp_dtype)


@dataclasses.dataclass(frozen=True)
class Schema:
    """Ordered named columns; the engine's row-schema object."""
    fields: Tuple[StructField, ...]

    def __post_init__(self):
        assert len({f.name for f in self.fields}) == len(self.fields), "duplicate column names"

    @property
    def names(self):
        return [f.name for f in self.fields]

    @property
    def types(self):
        return [f.data_type for f in self.fields]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"column {name!r} not in schema {self.names}")

    def __len__(self):
        return len(self.fields)

    def __getitem__(self, i):
        return self.fields[i]

    @staticmethod
    def of(**name_types: DataType) -> "Schema":
        return Schema(tuple(StructField(n, t) for n, t in name_types.items()))
