"""Trace-purity rules (ISSUE 12 rule family 3).

``trace-module-jnp``: a module-level ``jnp.*(...)`` binding creates a
jax array at import time; when the module is first imported INSIDE a
jit trace (lazy imports are everywhere in this engine), the "constant"
captures a tracer and every later use leaks it — the exact
order-dependent failure PR 2 fixed across seven ops modules. Constants
belong as plain Python ints / numpy scalars; bare attribute references
(``_mk('Sqrt', jnp.sqrt)``) are fine and not flagged.

``trace-host-sync``: host-sync / materialization calls (``np.asarray``,
``.item()``, ``.tolist()``, ``jax.device_get``, ``.block_until_ready``)
on values inside a ``@jit``-decorated function or a Pallas kernel body
(``*_kernel`` by the repo's naming convention) force a device sync mid-
trace or fail outright on tracers.
"""

from __future__ import annotations

import ast

from .callgraph import ModuleGraph, attr_root, unparse
from .core import Finding, ModuleInfo
from .registry import HOST_SYNC_ATTRS, HOST_SYNC_NP_ATTRS


def check_module_jnp(module: ModuleInfo, graph: ModuleGraph, reg):
    if reg.scope_prefix not in module.path:
        return []  # tools/bench are scripts: module scope IS their main
    aliases = set(graph.jnp_aliases)
    if not aliases:
        return []
    out = []
    for stmt in module.tree.body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
            continue
        value = stmt.value
        if value is None:
            continue
        for call in ast.walk(value):
            if isinstance(call, ast.Call) and \
                    attr_root(call.func) in aliases:
                target = stmt.targets[0] if isinstance(
                    stmt, ast.Assign) else stmt.target
                out.append(Finding(
                    "trace-module-jnp", module.path, stmt.lineno,
                    "<module>", unparse(target),
                    f"module-level `{unparse(call)[:60]}` builds a jax "
                    "array at import time — first import inside a jit "
                    "trace captures a tracer (PR 2 bug class); use a "
                    "Python int / numpy scalar"))
                break  # one finding per binding
    return out


def _numpy_aliases(tree: ast.Module):
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def _is_traced_def(fnode: ast.FunctionDef) -> bool:
    if fnode.name.endswith("_kernel"):
        return True
    for dec in fnode.decorator_list:
        if "jit" in unparse(dec):
            return True
    return False


def check_host_sync(module: ModuleInfo, graph: ModuleGraph, reg):
    if reg.scope_prefix not in module.path:
        return []
    np_aliases = _numpy_aliases(module.tree)
    out = []
    for qual, cls, fnode in graph.scopes():
        if not _is_traced_def(fnode):
            continue
        for node in ast.walk(fnode):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            recv_root = attr_root(node.func.value)
            hit = None
            if attr in HOST_SYNC_ATTRS:
                hit = f".{attr}()"
            elif attr in HOST_SYNC_NP_ATTRS and recv_root in np_aliases:
                hit = f"{recv_root}.{attr}(...)"
            if hit is not None:
                out.append(Finding(
                    "trace-host-sync", module.path, node.lineno, qual,
                    f"{attr}",
                    f"host-sync `{hit}` inside traced body `{qual}` — "
                    "forces a device sync mid-trace (or fails on a "
                    "tracer); materialize at the batch boundary"))
    return out
