"""Stage-governance rule (ISSUE 14 satellite).

``stage-governance``: a function handed to the dispatch-ledger
chokepoint (``obs.dispatch.instrument`` / ``TpuExec._site``) is a
TRACED STAGE BODY — pure dataflow jax re-runs whenever the program
traces. Per-batch governance hooks inside such a body are latent bugs
of two shapes:

* **silently dead**: the hook runs only on the (rare) trace, not per
  batch — a lifecycle ``tick()``, a chaos ``faults.check`` or a metric
  timer inside a jitted body fires once per compiled shape instead of
  once per batch, so cancellation latency, fault coverage and metric
  totals all lie;
* **trace-impure**: hooks that mutate host state (event ``emit``,
  gather ``observe``, engagement notes) from inside a trace replay
  unpredictably under jit caching.

They belong in the stage-boundary harness (``TpuExec.batch_harness``
and the ``TpuExec._drive`` batch loop) — the ISSUE 14 refactor this
rule keeps honest. The walk resolves the function object handed to the
chokepoint (a local def, ``self._method``, a lambda, a
``partial(...)`` wrapper or an ``@instrument``/``@partial(instrument,
...)`` decorator) and flags governance calls in its body and in
module-local calls one hop down.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .callgraph import ModuleGraph, attr_root
from .core import Finding, ModuleInfo

#: attribute calls that are per-batch governance hooks, never traced
#: dataflow (names chosen to not collide with jnp/array attributes)
_HOOK_ATTRS = frozenset({
    "tick",            # lifecycle cancellation check
    "note_batch",      # lifecycle live progress
    "ns_timer",        # metric wall timers
    "add_device",      # metric device accumulation
    "observe",         # GatherTracker scopes
    "emit",            # event-bus records
    "batch_harness",   # the harness itself must wrap, not be traced
})

#: bare-name governance calls
_HOOK_NAMES = frozenset({
    "note_engagement", "engage_domain", "record_domain_failure",
    "breaker_allows",
})

#: roots whose .check(...) is the chaos fault-point hook (dict.check
#: etc. do not exist; scoping by root keeps jnp.* clean)
_FAULT_ROOTS = frozenset({"faults"})


def _hook_calls(fn: ast.AST) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _HOOK_ATTRS:
                out.append((node.lineno, f.attr))
            elif f.attr == "check" and attr_root(f) in _FAULT_ROOTS:
                out.append((node.lineno, "faults.check"))
            elif f.attr in _HOOK_NAMES:
                out.append((node.lineno, f.attr))
        elif isinstance(f, ast.Name) and f.id in _HOOK_NAMES:
            out.append((node.lineno, f.id))
    return out


def _unwrap_fn_arg(arg: ast.AST) -> Optional[ast.AST]:
    """The function expression inside an instrument() argument:
    a Name, self._method attribute, lambda, or partial(fn, ...)."""
    if isinstance(arg, (ast.Name, ast.Attribute, ast.Lambda)):
        return arg
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) \
            and arg.func.id == "partial" and arg.args:
        return _unwrap_fn_arg(arg.args[0])
    return None


def _is_chokepoint(func: ast.AST) -> bool:
    """instrument / _instrument aliases and the TpuExec._site helper."""
    if isinstance(func, ast.Name):
        return func.id.endswith("instrument")
    if isinstance(func, ast.Attribute):
        return func.attr in ("instrument", "_site")
    return False


def _resolve_body(expr: ast.AST, graph: ModuleGraph,
                  cls: Optional[str]) -> Optional[ast.AST]:
    if isinstance(expr, ast.Lambda):
        return expr
    if isinstance(expr, ast.Name):
        hit = graph.resolve_name(expr.id, cls)
        return hit[1] if hit else None
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id in ("self", "cls") and cls:
        key = (cls, expr.attr)
        fn = graph.functions.get(key)
        if fn is None:
            fn = graph.by_name.get(expr.attr)
        return fn
    return None


def check(module: ModuleInfo, graph: ModuleGraph, reg):
    if reg.scope_prefix not in module.path:
        return []
    out: List[Finding] = []
    #: (body node id) already reported per hook line — a body handed to
    #: two sites (tier dicts) must not double-report
    seen: Set[Tuple[int, int, str]] = set()

    def flag_body(body: ast.AST, cls: Optional[str],
                  site_line: int) -> None:
        hooks = list(_hook_calls(body))
        # one hop into module-local callees: a hook moved into a local
        # helper is the same bug
        for call in ast.walk(body):
            if not isinstance(call, ast.Call):
                continue
            hit = graph.resolve_call(call, cls)
            if hit is not None and hit[1] is not body:
                hooks.extend(_hook_calls(hit[1]))
        name = getattr(body, "name", "<lambda>")
        for line, hook in hooks:
            key = (id(body), line, hook)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                "stage-governance", module.path, line,
                name, hook,
                f"governance hook `{hook}` inside the traced stage "
                f"body handed to the dispatch chokepoint at line "
                f"{site_line} — per-batch hooks run once per TRACE "
                "there (silently dead under jit caching); move it to "
                "the stage-boundary harness (TpuExec.batch_harness / "
                "the _drive batch loop)"))

    # class context for attribute resolution
    def walk_scope(nodes, cls: Optional[str]):
        for node in nodes:
            if isinstance(node, ast.ClassDef):
                walk_scope(node.body, node.name)
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) \
                        or not _is_chokepoint(call.func):
                    continue
                # positional fn (instrument(fn, ...) / _site(fn, ...))
                cands = [a for a in call.args]
                # decorator-factory form has no fn argument here; the
                # decorated def is handled below
                for a in cands:
                    fexpr = _unwrap_fn_arg(a)
                    if fexpr is None:
                        continue
                    body = _resolve_body(fexpr, graph, cls)
                    if body is not None:
                        flag_body(body, cls, call.lineno)
            # decorated defs: @instrument(label=...) / @partial(
            # instrument, ...) / @partial(self._site, ...)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and (
                            _is_chokepoint(dec.func)
                            or (isinstance(dec.func, ast.Name)
                                and dec.func.id == "partial"
                                and dec.args
                                and _is_chokepoint(dec.args[0]))):
                        flag_body(node, cls, dec.lineno)
                walk_scope(node.body, cls)

    walk_scope(module.tree.body, None)
    return out
