"""THE rule registry (ISSUE 12): rule metadata plus the engine contract
data the rules check against — named locks with reentrancy and a
declared partial order, the thread-local adopt helpers, the cross-query
entry points whose call paths must not read `active_conf`, and the
paired accounting calls that must stay symmetric.

One registry, lint-checked three ways: docs/static_analysis.md's rule
table must list exactly RULES (tests/test_contract_check.py), every
lock/entry spec must name a real module (same test), and
tests/test_docs_lint.py delegates its conf-key AST scan to the
`conf-key-registered` rule's scanner so the registries cannot drift.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Iterable, List, Optional, Tuple


class LockSpec:
    """One named engine lock. `expr` is the acquisition expression as
    written at the hold sites (`with self._lock:`), `cls` scopes it to
    a class (None = module-global name)."""

    __slots__ = ("name", "module", "cls", "expr", "reentrant", "note")

    def __init__(self, name: str, module: str, cls: Optional[str],
                 expr: str, reentrant: bool, note: str):
        self.name = name
        self.module = module
        self.cls = cls
        self.expr = expr
        self.reentrant = reentrant
        self.note = note


class EntrySpec:
    """A function that runs on a producer/cross-query thread (or on an
    arbitrary caller's thread servicing OTHER queries' state): conf
    reads along its module-local call paths must ride a captured
    conf/Ticket, never the executing thread's `active_conf`."""

    __slots__ = ("module", "cls", "func", "note")

    def __init__(self, module: str, cls: Optional[str], func: str,
                 note: str):
        self.module = module
        self.cls = cls
        self.func = func
        self.note = note


class PairSpec:
    """Registry-declared paired accounting calls. `escrow` maps a
    function qualname to the justification for holding the obligation
    open past its own frame (ownership transfer)."""

    __slots__ = ("name", "open_attr", "close_attr", "receiver_hint",
                 "modules", "escrow")

    def __init__(self, name: str, open_attr: str, close_attr: str,
                 receiver_hint: str, modules: Tuple[str, ...],
                 escrow: Dict[str, str]):
        self.name = name
        self.open_attr = open_attr
        self.close_attr = close_attr
        self.receiver_hint = receiver_hint
        self.modules = modules
        self.escrow = escrow


class ContractRegistry:
    """The data half of the registry. Tests run rules against a fixture
    instance; the CLI and tier-1 use DEFAULT_REGISTRY."""

    def __init__(self, locks: List[LockSpec], lock_order: List[str],
                 cross_query_entries: List[EntrySpec],
                 pairs: List[PairSpec],
                 adopt_helpers: Iterable[str],
                 extra_blocking_calls: Dict[str, str],
                 scope_prefix: str = "spark_rapids_tpu/"):
        #: path substring gating the package-wide rules (thread/trace):
        #: tools/bench are scripts — module scope IS their main — so the
        #: engine registry scopes those rules to the package; fixture
        #: registries pass "" to run them anywhere
        self.scope_prefix = scope_prefix
        self.locks = locks
        #: outermost-first acquisition order; acquiring a lock that
        #: sorts EARLIER than one already held is a lock-order finding
        self.lock_order = lock_order
        self.cross_query_entries = cross_query_entries
        self.pairs = pairs
        self.adopt_helpers = frozenset(adopt_helpers)
        #: cross-module calls known to block (module-level walks cannot
        #: see into them): callable name -> why it blocks
        self.extra_blocking_calls = dict(extra_blocking_calls)

    def locks_for(self, relpath: str) -> List[LockSpec]:
        return [s for s in self.locks if relpath.endswith(s.module)]

    def entries_for(self, relpath: str) -> List[EntrySpec]:
        return [e for e in self.cross_query_entries
                if relpath.endswith(e.module)]

    def pairs_for(self, relpath: str) -> List[PairSpec]:
        return [p for p in self.pairs
                if any(relpath.endswith(m) for m in p.modules)]


#: attribute calls that block (or do IO) regardless of receiver
BLOCKING_ATTRS = frozenset({
    "wait", "join", "sleep", "fsync", "savez", "device_get",
    "block_until_ready", "result",
})
#: .get()/.put() block only on queue-like receivers (dict.get is not IO)
QUEUE_BLOCKING_ATTRS = frozenset({"get", "put"})
QUEUE_RECEIVER_RE = re.compile(r"(^|\.)_?(write_)?q(ueue)?$")
#: bare-name calls that do IO
BLOCKING_NAMES = frozenset({"open"})
#: `.emit(...)` on an event-bus-ish receiver — the PR 6 r4 class: the
#: bus takes its own lock and writes a file, never do that under an
#: engine lock
EMIT_RECEIVER_HINTS = ("events", "bus")

#: thread-local capture/adopt helpers a spawned target must route
#: through (PRs 3/4/5/6: conf, query id, speculation scope, task
#: attempt, lifecycle context, breaker engagement)
ADOPT_HELPERS = frozenset({
    "set_active_conf", "adopt_query_id", "adopt_context",
    "adopt_attempt", "adopt_engagement", "query_scope",
    # pool-thread wrapper (obs.events): submit(with_query_id, qid, fn, ...)
    "with_query_id",
})

#: host-sync / materialization calls that must not run on tracer values
#: inside a @jit / Pallas body
HOST_SYNC_ATTRS = frozenset({
    "item", "tolist", "block_until_ready", "device_get",
})
HOST_SYNC_NP_ATTRS = frozenset({"asarray", "array", "frombuffer"})


class RuleMeta:
    __slots__ = ("id", "family", "bug_class", "origin", "example",
                 "checker")

    def __init__(self, id: str, family: str, bug_class: str, origin: str,
                 example: str, checker: Optional[Callable]):
        self.id = id
        self.family = family
        self.bug_class = bug_class
        self.origin = origin
        self.example = example
        self.checker = checker


def _build_rules() -> Dict[str, RuleMeta]:
    from . import (rules_accounting, rules_bounded, rules_conf,
                   rules_dispatch, rules_locks, rules_registry,
                   rules_stage, rules_threads, rules_trace)
    rules = [
        RuleMeta(
            "lock-blocking-call", "lock-discipline",
            "blocking call (IO, wait, queue op, event emit, device "
            "transfer) reachable while a registered engine lock is held",
            "PR 6 r4 (admission events under the manager cond); "
            "PR 3 r2 (writer drain under the catalog lock)",
            "obs_events.emit(...) inside `with self._lock:`",
            rules_locks.check_blocking),
        RuleMeta(
            "lock-reacquire", "lock-discipline",
            "re-acquisition of a non-reentrant lock along a "
            "module-local call path",
            "PR 5 (HeartbeatManager.heartbeat -> register deadlock)",
            "method holding self._lock calls a method that takes it",
            rules_locks.check_reacquire),
        RuleMeta(
            "lock-order", "lock-discipline",
            "acquiring a lock that sorts EARLIER in the declared "
            "partial order than one already held",
            "declared order (registry.lock_order), PR 3 writer/catalog "
            "deadlock analysis",
            "taking the catalog lock while holding the event-bus lock",
            rules_locks.check_order),
        RuleMeta(
            "bounded-wait", "lock-discipline",
            "unbounded blocking rendezvous — wait/get/result/sleep "
            "with no positional args and no timeout= keyword parks "
            "its thread beyond every watchdog, deadline and "
            "cancellation poll",
            "ISSUE 20 (straggler & stall shield: stalls the shield "
            "cannot observe cannot be mitigated)",
            "self._done.wait() / fut.result() with no timeout",
            rules_bounded.check),
        RuleMeta(
            "thread-adopt", "thread-propagation",
            "threading.Thread / pool submit whose target never routes "
            "through the thread-local capture/adopt helpers",
            "PRs 3/4/5 (conf, query id, speculation, attempt, "
            "engagement adoption at every producer boundary)",
            "threading.Thread(target=self._loop) with no adopt_* in "
            "_loop",
            rules_threads.check),
        RuleMeta(
            "trace-module-jnp", "trace-purity",
            "module-level jnp.* call binding — captures a tracer when "
            "the module is first imported inside a jit trace",
            "PR 2 (order-dependent tracer leak across 7 ops modules)",
            "_C1 = jnp.uint32(0xcc9e2d51) at module scope",
            rules_trace.check_module_jnp),
        RuleMeta(
            "trace-host-sync", "trace-purity",
            "host-sync / materialization call inside a @jit or Pallas "
            "kernel body",
            "PR 1/2 jit discipline (device syncs belong at the batch "
            "boundary)",
            "np.asarray(x) inside a @jax.jit function",
            rules_trace.check_host_sync),
        RuleMeta(
            "conf-provenance", "conf-provenance",
            "active_conf() read reachable from a producer-thread or "
            "cross-query entry point — the value must ride a captured "
            "conf or the admitting Ticket",
            "PR 6 (3x: release cap, quota fraction, breaker consult "
            "all read the CALLING thread's conf)",
            "active_conf().get(...) inside the spill-writer's reach",
            rules_conf.check),
        RuleMeta(
            "accounting-symmetry", "accounting-symmetry",
            "registry-declared paired calls (reserve/release, "
            "charge/discharge) unbalanced: open with no close on any "
            "path, or an exception edge that drops the close",
            "PRs 3/4/6 (budget counters asymmetric on failure "
            "branches, quota charge/discharge mirrors)",
            "memory_budget().reserve(n) with no release on the raise "
            "path",
            rules_accounting.check),
        RuleMeta(
            "conf-key-registered", "registry-drift",
            "full spark.rapids.* conf-key literal not present in the "
            "config registry",
            "PR 2 docs lint (folded into the analyzer, ISSUE 12 "
            "satellite)",
            '"spark.rapids.tpu.sucht.nicht" anywhere in code',
            rules_registry.check_conf_keys),
        RuleMeta(
            "event-kind-registered", "registry-drift",
            "emit() with a literal event kind missing from "
            "obs.events.EVENT_LEVELS (it would silently default to "
            "MODERATE and never reach the docs schema table)",
            "PR 2 docs lint (EVENT_LEVELS registry)",
            'obs_events.emit("not_a_kind", ...)',
            rules_registry.check_event_kinds),
        RuleMeta(
            "dispatch-ledger", "dispatch-discipline",
            "jax.jit / pallas_call site that does not route through "
            "the dispatch-ledger chokepoint (obs.dispatch.instrument) "
            "— its dispatches/compiles/storms are invisible to the "
            "observability plane",
            "ISSUE 13 (dispatch & compile observability plane)",
            "self._jit = jax.jit(self._kernel) in an exec",
            rules_dispatch.check),
        RuleMeta(
            "stage-governance", "dispatch-discipline",
            "per-batch governance hook (lifecycle tick, chaos fault "
            "point, metric timer, event emit, gather observe, breaker "
            "engagement) inside a traced stage body handed to the "
            "dispatch chokepoint — it runs once per TRACE, not per "
            "batch, so it is silently dead under jit caching; hooks "
            "belong in the stage-boundary harness",
            "ISSUE 14 (whole-stage compilation: governance extracted "
            "to the stage boundary)",
            "faults.check(...) inside a fn passed to instrument()",
            rules_stage.check),
        RuleMeta(
            "suppression-empty", "analyzer-meta",
            "a `# contract: ok` suppression with no justification, or "
            "naming a rule that does not exist",
            "ISSUE 12 (justification required, linted non-empty)",
            "# contract: ok lock-blocking-call —",
            None),
        RuleMeta(
            "baseline-invalid", "analyzer-meta",
            "a baseline entry with an empty/UNREVIEWED justification "
            "or a non-positive count",
            "ISSUE 12 (baselined findings carry a why, like "
            "suppressions)",
            '{"count": 0, "why": ""}',
            None),
    ]
    return {r.id: r for r in rules}


RULES: Dict[str, RuleMeta] = _build_rules()

#: rule families (docs/static_analysis.md groups its table by these)
FAMILIES = tuple(dict.fromkeys(r.family for r in RULES.values()))


DEFAULT_REGISTRY = ContractRegistry(
    locks=[
        LockSpec("catalog", "memory/catalog.py", "BufferCatalog",
                 "self._lock", reentrant=True,
                 note="3-tier spill store registry (RLock: the writer's "
                 "finalize re-enters via _recover_dead_writer_locked)"),
        LockSpec("budget-cond", "memory/budget.py", "MemoryBudget",
                 "self._lock", reentrant=True,
                 note="HBM budget condition (reserve/release/waiters)"),
        LockSpec("workload-cond", "exec/workload.py", "WorkloadManager",
                 "self._cond", reentrant=True,
                 note="admission queue + quota accounting condition"),
        LockSpec("semaphore-cond", "memory/semaphore.py", "_FairPermits",
                 "self._cond", reentrant=True,
                 note="fair permit registry condition"),
        LockSpec("semaphore", "memory/semaphore.py", "TpuSemaphore",
                 "self._lock", reentrant=False,
                 note="per-task hold table"),
        LockSpec("breaker", "exec/lifecycle.py", None, "_breaker_lock",
                 reentrant=False,
                 note="circuit-breaker domain state"),
        LockSpec("heartbeat", "parallel/heartbeat.py",
                 "HeartbeatManager", "self._lock", reentrant=False,
                 note="peer table (the PR 5 deadlock lived here)"),
        LockSpec("telemetry", "obs/telemetry.py", "TelemetryRegistry",
                 "self._lock", reentrant=False,
                 note="counter + ring-buffer state"),
        LockSpec("telemetry-config", "obs/telemetry.py", None,
                 "_registry_lock", reentrant=False,
                 note="registry singleton install/teardown"),
        LockSpec("stats", "obs/stats.py", "ExchangeStats", "self._lock",
                 reentrant=False, note="per-exchange distribution state"),
        LockSpec("stats-global", "obs/stats.py", None, "_global_lock",
                 reentrant=False, note="process-wide stats collector"),
        LockSpec("dispatch-ledger", "obs/dispatch.py", "DispatchLedger",
                 "self._lock", reentrant=False,
                 note="program-stats registry (events buffered under "
                 "it, emitted after it drops)"),
        LockSpec("dispatch-config", "obs/dispatch.py", None,
                 "_ledger_lock", reentrant=False,
                 note="ledger singleton install/teardown"),
        LockSpec("phase-global", "obs/phase.py", None, "_global_lock",
                 reentrant=False,
                 note="process-cumulative per-phase ns counters"),
        LockSpec("phase-ledger", "obs/phase.py", "PhaseLedger",
                 "self._lock", reentrant=False,
                 note="per-query phase books (direct/folded maps)"),
        LockSpec("event-bus-config", "obs/events.py", None, "_bus_lock",
                 reentrant=False, note="bus singleton install/teardown"),
        LockSpec("event-bus", "obs/events.py", "EventBus", "self._lock",
                 reentrant=False,
                 note="JSONL sink write serialization (leaf lock: nothing "
                 "may be acquired under it)"),
        LockSpec("history-config", "obs/history.py", None, "_store_lock",
                 reentrant=False,
                 note="history store singleton install/teardown"),
        LockSpec("history", "obs/history.py", "HistoryStore",
                 "self._lock", reentrant=False,
                 note="capsule JSONL sink write serialization (leaf "
                 "lock, the event-bus pattern)"),
    ],
    # outermost-first: a lock may only be acquired while holding locks
    # that sort strictly BEFORE it
    lock_order=[
        "catalog", "workload-cond", "budget-cond", "semaphore-cond",
        "semaphore", "heartbeat", "breaker", "telemetry-config",
        "telemetry", "stats", "stats-global", "dispatch-config",
        "dispatch-ledger", "phase-global", "phase-ledger",
        "event-bus-config", "event-bus", "history-config", "history",
    ],
    cross_query_entries=[
        EntrySpec("memory/catalog.py", "BufferCatalog", "_writer_loop",
                  "spill-writer thread serves every query's hops"),
        EntrySpec("memory/catalog.py", "BufferCatalog",
                  "_recover_dead_writer_locked",
                  "drains OTHER queries' stranded hops on the "
                  "detecting thread"),
        EntrySpec("memory/catalog.py", "BufferCatalog",
                  "synchronous_spill",
                  "a neighbor's reserve pressure spills THIS query's "
                  "entries on the neighbor's thread"),
        EntrySpec("memory/semaphore.py", "TpuSemaphore", "__init__",
                  "process singleton sized by whichever thread "
                  "constructs it first"),
        EntrySpec("exec/workload.py", "WorkloadManager", "release",
                  "releasing thread pumps grants for OTHER queries "
                  "(the PR 6 cap bug lived here)"),
        EntrySpec("exec/workload.py", "WorkloadManager", "charge",
                  "mirrors catalog accounting from any spilling thread"),
        EntrySpec("exec/workload.py", "WorkloadManager", "discharge",
                  "mirrors catalog accounting from any spilling thread"),
        EntrySpec("obs/telemetry.py", "TelemetryRegistry", "_loop",
                  "sampler thread carries no query context"),
        EntrySpec("parallel/heartbeat.py", "HeartbeatEndpoint", "_loop",
                  "heartbeat daemon carries no query context"),
        EntrySpec("io/multifile.py", None, "retrying",
                  "shared decode-pool worker (conf must ride the "
                  "captured closure, never the pool thread's TLS)"),
    ],
    pairs=[
        PairSpec(
            # add() is deliberately NOT escrowed here: its reserve has
            # no release in-frame AND no guarding except — the window
            # between reserve and registration is accepted debt,
            # carried in the baseline with its justification
            "hbm-budget", "reserve", "release", receiver_hint="budget",
            modules=("memory/catalog.py",),
            escrow={}),
        PairSpec(
            "workload-quota", "charge", "discharge",
            receiver_hint="workload",
            modules=("memory/catalog.py",),
            escrow={
                "BufferCatalog.add":
                    "quota charge mirrors the entry's budget reserve; "
                    "remove()/writeback discharges it",
            }),
    ],
    adopt_helpers=ADOPT_HELPERS,
    extra_blocking_calls={
        "upload_leaves": "host->device transfer (may compile + block "
                         "on the device)",
        "device_put": "host->device transfer",
        "with_io_retry": "file IO with bounded retry + backoff sleeps",
        "synchronous_spill": "spill pass: d2h copies / disk writes (or "
                             "writer-queue hand-off) per victim",
        "shutdown": "joins a worker/sampler thread on teardown",
    },
)
